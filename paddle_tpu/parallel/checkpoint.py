"""Sharding-aware checkpoint/resume for the jax-native training path.

Reference capability: save/load_persistables (io.py:501,769) and the
distributed-aware save that reassembles pserver-resident shards
(io.py:320). The Program path already has those (paddle_tpu.io); THIS
module covers the flagship jax-native path (parallel/train.py
TrainState): parameters + optimizer moments may be sharded over the
mesh (ZeRO-1), and a checkpoint must round-trip those shardings. Orbax
is the TPU-native serialization engine — each host writes its own
shards (the multi-host story for free), and restore lays arrays out
directly into the target NamedShardings.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax

from .train import TrainState

# leaf-dtype manifest written next to every orbax payload: restore
# compares it against the template's dtypes so a checkpoint written
# under one precision policy can never SILENTLY restore into another
# width — it either casts explicitly (cast_dtypes=True) or fails with
# the mismatch list. Pre-manifest checkpoints restore as before.
DTYPES_FILE = "_DTYPES.json"

# mesh manifest written next to the payload: world size + axis sizes of
# the mesh the state was sharded over at save time. Restore compares it
# against the template's mesh to detect a CROSS-WORLD-SIZE restore (a
# mesh-4 checkpoint onto a mesh-3 job after elastic scale-in) — orbax
# lays shards out into the template's NamedShardings either way, but
# the reshard is surfaced as a `restore_resharded` event + elastic
# resharding metrics, and genuinely incompatible layouts (leaf shapes
# that differ) are refused with ReshardError before orbax dies with an
# opaque per-array error. Pre-manifest checkpoints restore as before.
MESH_FILE = "_MESH.json"


class PrecisionMismatchError(ValueError):
    """Checkpoint leaf dtypes disagree with the restore template's —
    e.g. a bf16-policy checkpoint restored into an f32-policy run.
    Re-restore with cast_dtypes=True to convert explicitly, or rebuild
    the template under the checkpoint's policy."""


class ReshardError(ValueError):
    """Checkpoint cannot be resharded onto the restore template: leaf
    global SHAPES disagree (a different model, layer width, or a
    world-size-dependent layout), as opposed to the same logical arrays
    merely sharded over a different mesh — that case reshards fine.
    Raised by `restore_train_state` / `reshard_train_state` so an
    elastic resize fails loudly instead of restoring garbage."""


def _payload(state: TrainState) -> Dict:
    payload = {"params": state.params, "opt_state": state.opt_state,
               "step": state.step}
    if getattr(state, "loss_scale", None) is not None:
        # dynamic loss-scaling state (mixed-precision policies) rides
        # the same orbax payload, so CheckpointManager round-trips it
        payload["loss_scale"] = state.loss_scale
    return payload


def _dtype_manifest(tree) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        dt = getattr(leaf, "dtype", None)
        if dt is not None:
            out[jax.tree_util.keystr(path)] = str(dt)
    return out


def _tree_mesh(tree):
    """The Mesh the first NamedSharding-carrying leaf lives on, or
    None for host-only trees (numpy payload tests)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = getattr(leaf, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "devices", None) is not None:
            return mesh
    return None


def _mesh_manifest(tree) -> Optional[Dict]:
    mesh = _tree_mesh(tree)
    if mesh is None:
        return None
    return {"world_size": int(mesh.devices.size),
            "axes": {str(a): int(s)
                     for a, s in dict(mesh.shape).items()}}


def save_train_state(path: str, state: TrainState, force: bool = False):
    """Write {params, opt_state, step[, loss_scale]} with their
    shardings to `path`, plus a leaf-dtype manifest (_DTYPES.json) that
    restore uses to refuse silent cross-precision restores.

    force=False refuses to overwrite an existing checkpoint: orbax
    deletes the old directory BEFORE the new write commits, so
    overwriting in place would leave zero restorable checkpoints if the
    process dies mid-save. Periodic savers should write step-stamped
    dirs (`root/step_N`, see latest_step_dir) and prune old ones only
    after the new save returns."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    payload = _payload(state)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, payload, force=force)
    from ..observability import events as _events
    from ..resilience.atomic import json_dump

    json_dump(_dtype_manifest(payload), os.path.join(path, DTYPES_FILE))
    mesh_meta = _mesh_manifest(payload)
    if mesh_meta is not None:
        json_dump(mesh_meta, os.path.join(path, MESH_FILE))
    _events.emit("checkpoint", site="save_train_state", dir=path,
                 step=int(state.step))


def restore_train_state(path: str, template: TrainState,
                        cast_dtypes: bool = False) -> TrainState:
    """Restore into the TEMPLATE's structure and shardings — pass a
    freshly-built `init_state(params)` result; its (possibly ZeRO-1
    sharded) layout tells orbax where every shard of every array lands.

    Precision safety: when the checkpoint carries a dtype manifest and
    any leaf width disagrees with the template (a bf16 checkpoint into
    an f32-policy template, or vice versa), the restore FAILS with a
    PrecisionMismatchError listing the offenders — restoring across
    widths silently would corrupt the run's numerics story. Pass
    cast_dtypes=True to reshard dtypes explicitly instead: leaves are
    read back at their SAVED dtype and cast to the template's.

    The same contract covers STRUCTURE: dynamic loss-scaling state
    exists only under mixed policies, so a checkpoint and template
    disagreeing on its presence is also a cross-precision restore —
    it fails with PrecisionMismatchError, or under cast_dtypes=True
    reshards explicitly (template-side loss-scale state keeps its
    fresh init; checkpoint-side state is read and dropped)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    target = {"params": template.params,
              "opt_state": template.opt_state,
              "step": template.step}
    if getattr(template, "loss_scale", None) is not None:
        target["loss_scale"] = template.loss_scale

    saved_dtypes: Optional[Dict[str, str]] = None
    manifest_path = os.path.join(path, DTYPES_FILE)
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            saved_dtypes = json.load(f)

    # cross-world-size detection: a mesh manifest that disagrees with
    # the template's mesh means this restore is an elastic RESHARD —
    # refuse incompatible layouts up front, surface the reshard in
    # events/metrics, and let orbax lay the shards out into the
    # template's shardings (the actual data movement).
    saved_mesh: Optional[Dict] = None
    mesh_path = os.path.join(path, MESH_FILE)
    if os.path.exists(mesh_path):
        with open(mesh_path) as f:
            saved_mesh = json.load(f)
    tmpl_mesh = _mesh_manifest(target)
    resharding = (saved_mesh is not None and tmpl_mesh is not None
                  and saved_mesh != tmpl_mesh)
    if resharding:
        _check_reshardable(path, target)
    import time as _time

    t0 = _time.perf_counter()

    # structure guard BEFORE the per-leaf dtype loop (which only sees
    # keys present on both sides): loss-scale presence differing would
    # otherwise die inside orbax with an opaque tree-structure error
    # that cast_dtypes could never fix. Manifest-less checkpoints
    # predate loss-scale payloads, so no manifest == no saved state.
    tmpl_has_ls = "loss_scale" in target
    saved_has_ls = (saved_dtypes is not None
                    and any(k.startswith("['loss_scale']")
                            for k in saved_dtypes))
    drop_saved_ls = False
    if saved_has_ls != tmpl_has_ls:
        if not cast_dtypes:
            side = ("the checkpoint carries dynamic loss-scaling state "
                    "but the restore template has none"
                    if saved_has_ls else
                    "the restore template expects dynamic loss-scaling "
                    "state but the checkpoint has none")
            raise PrecisionMismatchError(
                f"checkpoint at {path} was written under a different "
                f"precision policy than the restore template ({side}). "
                f"Restore with cast_dtypes=True to reshard explicitly "
                f"— the template's fresh loss-scale state is kept, a "
                f"checkpoint-side one is dropped — or rebuild the "
                f"template under the checkpoint's policy.")
        if tmpl_has_ls:
            # f32-era checkpoint into a mixed template: restore the
            # shared items; the template keeps its fresh loss scale
            target.pop("loss_scale")

        else:
            drop_saved_ls = True

    mismatches = []
    if saved_dtypes is not None:
        for key, want in _dtype_manifest(target).items():
            have = saved_dtypes.get(key)
            if have is not None and have != want:
                mismatches.append((key, have, want))
        if mismatches and not cast_dtypes:
            head = ", ".join(f"{k}: checkpoint {h} vs template {w}"
                             for k, h, w in mismatches[:8])
            raise PrecisionMismatchError(
                f"checkpoint at {path} was written under a different "
                f"precision than the restore template ({len(mismatches)}"
                f" leaf dtype mismatches: {head}"
                f"{', ...' if len(mismatches) > 8 else ''}). Restore "
                f"with cast_dtypes=True to convert explicitly, or "
                f"rebuild the template under the checkpoint's policy.")

    mismatch_keys = {k for k, _, _ in mismatches}

    def leaf_abstract(kpath, x):
        if not hasattr(x, "sharding"):
            return x
        dtype = x.dtype
        key = jax.tree_util.keystr(kpath)
        if key in mismatch_keys:
            # explicit dtype reshard: read at the SAVED width (the
            # bytes on disk), cast to the template width afterwards
            import numpy as np

            dtype = np.dtype(saved_dtypes[key])
        return jax.ShapeDtypeStruct(x.shape, dtype, sharding=x.sharding)

    abstract = jax.tree_util.tree_map_with_path(leaf_abstract, target)
    with ocp.StandardCheckpointer() as ckptr:
        if drop_saved_ls:
            # orbax demands an exact top-level structure match, so the
            # checkpoint-only loss_scale item must appear in the
            # abstract tree — shape/dtype come from the checkpoint's
            # own metadata; the restored values are dropped below
            import numpy as np

            abstract["loss_scale"] = jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(
                    tuple(m.shape), np.dtype(str(m.dtype))),
                ckptr.metadata(path)["loss_scale"])
        restored = ckptr.restore(path, abstract)
    if drop_saved_ls:
        restored.pop("loss_scale", None)
    if mismatch_keys:
        def recast(kpath, saved, tmpl):
            if jax.tree_util.keystr(kpath) in mismatch_keys:
                return jax.device_put(saved.astype(tmpl.dtype),
                                      tmpl.sharding)
            return saved

        restored = jax.tree_util.tree_map_with_path(
            lambda p, s, t: recast(p, s, t), restored, target)
    loss_scale = restored.get("loss_scale")
    if tmpl_has_ls and loss_scale is None:
        # explicit cross-precision reshard into a mixed template: the
        # checkpoint had no loss-scale state, keep the fresh init
        loss_scale = template.loss_scale
    if resharding:
        from ..distributed.rendezvous import RESHARD_SECONDS
        from ..observability import events as _events

        seconds = _time.perf_counter() - t0
        RESHARD_SECONDS.observe(seconds)
        _events.emit("restore_resharded", dir=path,
                     from_world=saved_mesh["world_size"],
                     to_world=tmpl_mesh["world_size"],
                     from_axes=saved_mesh["axes"],
                     to_axes=tmpl_mesh["axes"],
                     seconds=round(seconds, 6))
    return TrainState(restored["params"], restored["opt_state"],
                      restored["step"], loss_scale)


def _check_reshardable(path: str, target) -> None:
    """Refusal path for cross-mesh restores: every leaf's GLOBAL shape
    in the checkpoint must match the template's. Sharding may differ
    arbitrarily (that's the reshard); shapes may not — a shape mismatch
    means a different model or a world-size-dependent layout, and orbax
    would otherwise fail per-array with no layout diagnosis."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        meta = ckptr.metadata(path)
    bad = []
    tgt_leaves = {jax.tree_util.keystr(p): l for p, l in
                  jax.tree_util.tree_flatten_with_path(target)[0]}
    for p, m in jax.tree_util.tree_flatten_with_path(dict(meta))[0]:
        key = jax.tree_util.keystr(p)
        tl = tgt_leaves.get(key)
        if tl is None or not hasattr(tl, "shape") \
                or not hasattr(m, "shape"):
            continue
        if tuple(m.shape) != tuple(tl.shape):
            bad.append((key, tuple(m.shape), tuple(tl.shape)))
    if bad:
        head = ", ".join(f"{k}: checkpoint {s} vs template {t}"
                         for k, s, t in bad[:8])
        raise ReshardError(
            f"checkpoint at {path} cannot be resharded onto this "
            f"template: {len(bad)} leaf shape mismatches ({head}"
            f"{', ...' if len(bad) > 8 else ''}) — resharding moves "
            f"the SAME logical arrays onto a different mesh; it cannot "
            f"reconcile different shapes")


def reshard_train_state(state: TrainState, template: TrainState) -> TrainState:
    """In-process cross-mesh reshard: lay every leaf of `state` out on
    `template`'s shardings (per-leaf `jax.device_put`; a transfer the
    runtime refuses — e.g. source buffers on devices the new mesh no
    longer includes — falls back to gather-to-host + re-put). The
    no-checkpoint-round-trip path for an elastic resize when the state
    is already in memory; the checkpoint path is `restore_train_state`
    with a template built on the new mesh. Values are moved, never
    recomputed — leaves stay bit-identical. Shape disagreements raise
    ReshardError (same refusal contract as the checkpoint path)."""
    import numpy as np

    from ..distributed.rendezvous import RESHARD_SECONDS
    import time as _time

    t0 = _time.perf_counter()

    def move(kpath, leaf, tleaf):
        sh = getattr(tleaf, "sharding", None)
        if sh is None:
            return leaf
        if hasattr(leaf, "shape") and tuple(leaf.shape) != tuple(tleaf.shape):
            raise ReshardError(
                f"cannot reshard leaf {jax.tree_util.keystr(kpath)}: "
                f"state shape {tuple(leaf.shape)} vs template "
                f"{tuple(tleaf.shape)}")
        try:
            return jax.device_put(leaf, sh)
        except Exception:  # lint-exempt:swallow: jax raises several types for cross-mesh puts; gather fallback below is the contract
            return jax.device_put(np.asarray(leaf), sh)

    out = jax.tree_util.tree_map_with_path(move, state, template)
    RESHARD_SECONDS.observe(_time.perf_counter() - t0)
    return out


def latest_step_dir(root: str, committed_only: bool = False) -> Optional[str]:
    """Resume helper: `root/step_N` directories -> the highest-N path.

    CAUTION: with committed_only=False (the legacy default) this returns
    the highest-numbered directory even if it is a PARTIAL write left by
    a process that died mid-save. committed_only=True only counts
    directories carrying resilience.CheckpointManager's commit marker;
    for managed checkpoints prefer `CheckpointManager.restore_latest`,
    which additionally falls back past corrupt-but-committed dirs."""
    if not os.path.isdir(root):
        return None
    if committed_only:
        from ..resilience.checkpoint_manager import CheckpointManager

        return CheckpointManager(root).latest_committed_dir()
    best, best_n = None, -1
    for d in os.listdir(root):
        if d.startswith("step_") and os.path.isdir(os.path.join(root, d)):
            try:
                n = int(d.split("_", 1)[1])
            except ValueError:
                continue
            if n > best_n:
                best, best_n = os.path.join(root, d), n
    return best
