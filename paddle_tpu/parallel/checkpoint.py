"""Sharding-aware checkpoint/resume for the jax-native training path.

Reference capability: save/load_persistables (io.py:501,769) and the
distributed-aware save that reassembles pserver-resident shards
(io.py:320). The Program path already has those (paddle_tpu.io); THIS
module covers the flagship jax-native path (parallel/train.py
TrainState): parameters + optimizer moments may be sharded over the
mesh (ZeRO-1), and a checkpoint must round-trip those shardings. Orbax
is the TPU-native serialization engine — each host writes its own
shards (the multi-host story for free), and restore lays arrays out
directly into the target NamedShardings.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from .train import TrainState


def save_train_state(path: str, state: TrainState, force: bool = False):
    """Write {params, opt_state, step} with their shardings to `path`.

    force=False refuses to overwrite an existing checkpoint: orbax
    deletes the old directory BEFORE the new write commits, so
    overwriting in place would leave zero restorable checkpoints if the
    process dies mid-save. Periodic savers should write step-stamped
    dirs (`root/step_N`, see latest_step_dir) and prune old ones only
    after the new save returns."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"params": state.params,
                          "opt_state": state.opt_state,
                          "step": state.step}, force=force)
    from ..observability import events as _events

    _events.emit("checkpoint", site="save_train_state", dir=path,
                 step=int(state.step))


def restore_train_state(path: str, template: TrainState) -> TrainState:
    """Restore into the TEMPLATE's structure and shardings — pass a
    freshly-built `init_state(params)` result; its (possibly ZeRO-1
    sharded) layout tells orbax where every shard of every array lands.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    target = {"params": template.params,
              "opt_state": template.opt_state,
              "step": template.step}
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding") else x, target)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, abstract)
    return TrainState(restored["params"], restored["opt_state"],
                      restored["step"])


def latest_step_dir(root: str, committed_only: bool = False) -> Optional[str]:
    """Resume helper: `root/step_N` directories -> the highest-N path.

    CAUTION: with committed_only=False (the legacy default) this returns
    the highest-numbered directory even if it is a PARTIAL write left by
    a process that died mid-save. committed_only=True only counts
    directories carrying resilience.CheckpointManager's commit marker;
    for managed checkpoints prefer `CheckpointManager.restore_latest`,
    which additionally falls back past corrupt-but-committed dirs."""
    if not os.path.isdir(root):
        return None
    if committed_only:
        from ..resilience.checkpoint_manager import CheckpointManager

        return CheckpointManager(root).latest_committed_dir()
    best, best_n = None, -1
    for d in os.listdir(root):
        if d.startswith("step_") and os.path.isdir(os.path.join(root, d)):
            try:
                n = int(d.split("_", 1)[1])
            except ValueError:
                continue
            if n > best_n:
                best, best_n = os.path.join(root, d), n
    return best
