"""DistributedStrategy — the fleet-facing strategy object.

Reference: incubate/fleet/collective/__init__.py:94 DistributedStrategy
(extends BuildStrategy) + DistributeTranspilerConfig
(transpiler/distribute_transpiler.py:131). One object collects every
distributed-training knob; fleet.distributed_optimizer interprets it.

Mapping to TPU-native mechanisms:
  mode collective        → single pjit mesh (ICI/DCN collectives by XLA)
  use_hierarchical_allreduce → mesh factorization (mesh.py AXIS_ORDER)
  nccl_comm_num          → moot (one ICI domain); recorded
  use_local_sgd          → parallel/collective.py LocalSGD transpile
  use_dgc                → DGCMomentumOptimizer (top-k grad compression)
  gradient_merge_k       → TrainStrategy.accum_steps / GradientMergeOptimizer
  recompute              → TrainStrategy.recompute / RecomputeOptimizer
  pipeline               → parallel/pipeline.py ('pp' axis)
  sharding (ZeRO)        → TrainStrategy.shard_optimizer_states
  amp                    → amp.decorate (bf16 policy)
  tensor/sequence/expert parallel degrees → mesh axes tp/sp/ep
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..core.compiler import BuildStrategy, ExecutionStrategy


@dataclasses.dataclass
class DistributedStrategy:
    # parallelism degrees (mesh axes)
    data_parallel_degree: int = -1
    tensor_parallel_degree: int = 1
    pipeline_parallel_degree: int = 1
    sequence_parallel_degree: int = 1
    expert_parallel_degree: int = 1
    # optimizer-side features
    use_local_sgd: bool = False
    local_sgd_steps: int = 1
    use_dgc: bool = False
    gradient_merge_k: int = 1
    recompute: bool = False
    recompute_checkpoints: Optional[List[str]] = None
    sharding: bool = False           # ZeRO-1 optimizer-state sharding
    use_amp: bool = False
    amp_loss_scale: float = 32768.0
    lamb: bool = False
    # pipeline details
    pipeline_micro_batches: int = 1
    # parity-only knobs (reference semantics absorbed by XLA/ICI)
    use_hierarchical_allreduce: bool = False
    hierarchical_allreduce_inter_nranks: int = 0
    nccl_comm_num: int = 1
    fuse_all_reduce_ops: bool = True
    fuse_grad_size_in_MB: int = 32
    # execution mode: GSPMD CompiledProgram (default) vs per-device graph
    # with explicit c_allreduce ops run by SPMDRunner (the reference's
    # collective-transpiler semantics)
    use_graph_collectives: bool = False
    # multihost
    num_trainers: int = 1
    trainer_id: int = 0
    trainer_endpoints: Optional[List[str]] = None
    # legacy containers for API parity
    build_strategy: Optional[BuildStrategy] = None
    exec_strategy: Optional[ExecutionStrategy] = None

    def mesh_config(self):
        from .mesh import MeshConfig

        return MeshConfig(dp=self.data_parallel_degree,
                          tp=self.tensor_parallel_degree,
                          pp=self.pipeline_parallel_degree,
                          sp=self.sequence_parallel_degree,
                          ep=self.expert_parallel_degree)

    def train_strategy(self):
        from .train import TrainStrategy

        return TrainStrategy(
            shard_optimizer_states=self.sharding,
            accum_steps=max(1, self.gradient_merge_k),
            recompute=self.recompute)
