"""Logical-axis sharding rules.

The reference expresses device placement imperatively — graph passes clone
ops per device and insert collectives (ir/multi_devices_graph_pass/). The
TPU-native equivalent is declarative: tensors carry *logical* axis names
("batch", "embed", "mlp", ...) and a rule table maps logical axes to mesh
axes; GSPMD inserts the collectives. This is the BuildStrategy of the
rebuild: switching dp→dp+tp is a rule-table change, not a graph rewrite.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical axis marker for "never shard this axis".
NO_SHARD = None

LogicalAxes = Tuple[Optional[str], ...]


class LogicalRules:
    """Ordered mapping logical-axis-name -> mesh axis (or None)."""

    def __init__(self, rules: Union[Dict[str, Optional[str]],
                                    Sequence[Tuple[str, Optional[str]]]]):
        self._rules = dict(rules)

    def mesh_axis(self, logical: Optional[str]) -> Optional[str]:
        if logical is None:
            return None
        return self._rules.get(logical)

    def spec(self, axes: Sequence[Optional[str]]) -> P:
        return P(*(self.mesh_axis(a) for a in axes))

    def updated(self, **kw) -> "LogicalRules":
        d = dict(self._rules)
        d.update(kw)
        return LogicalRules(d)

    def __repr__(self):
        return f"LogicalRules({self._rules})"


# The default rule table used by models/: megatron-style TP + batch DP + SP.
DEFAULT_RULES = LogicalRules({
    "batch": "dp",
    "seq": "sp",          # sequence/context parallelism
    "embed": None,        # hidden dim of activations stays replicated-ish
    "heads": "tp",
    "kv": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    "stage": "pp",
    "conv_out": None,
})

_rules_stack: List[LogicalRules] = []


def current_rules() -> LogicalRules:
    return _rules_stack[-1] if _rules_stack else DEFAULT_RULES


@contextlib.contextmanager
def with_rules(rules: LogicalRules):
    _rules_stack.append(rules)
    try:
        yield rules
    finally:
        _rules_stack.pop()


def logical_to_mesh(axes: Sequence[Optional[str]],
                    rules: Optional[LogicalRules] = None) -> P:
    return (rules or current_rules()).spec(axes)


def in_manual_region() -> bool:
    """True when tracing inside a manual shard_map region (e.g. the 'pp'
    pipeline). XLA's partial-manual partitioner cannot handle nested manual
    subregions or extra sharding constraints there — callers skip both."""
    abstract = jax.sharding.get_abstract_mesh()
    return (abstract is not None and not abstract.empty
            and bool(getattr(abstract, "manual_axes", ())))


def shard(x, axes: Sequence[Optional[str]],
          rules: Optional[LogicalRules] = None):
    """Annotate a traced value with a sharding constraint by logical axes —
    the in-graph replacement for the reference's per-device graph cloning.
    No-op outside a mesh_guard (single-device eager use)."""
    from .mesh import current_mesh

    mesh = current_mesh()
    if mesh is None or in_manual_region():
        return x
    spec = logical_to_mesh(axes, rules)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def shard_params_spec(param_axes: Dict[str, LogicalAxes],
                      rules: Optional[LogicalRules] = None) -> Dict[str, P]:
    """Map {param name: logical axes} -> {param name: PartitionSpec}."""
    rules = rules or current_rules()
    return {k: rules.spec(v) for k, v in param_axes.items()}


def named_sharding_tree(mesh: Mesh, spec_tree):
    """PartitionSpec pytree -> NamedSharding pytree."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda s: isinstance(s, P))
