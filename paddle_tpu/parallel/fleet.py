"""Fleet — the unified distributed-training facade.

Reference: incubate/fleet/base/fleet_base.py:38 (Fleet), fleet/collective
(`CollectiveOptimizer`), used as:

    fleet.init(PaddleCloudRoleMaker())
    optimizer = fleet.distributed_optimizer(optimizer, strategy)
    optimizer.minimize(loss)
    ... exe.run(fleet.main_program)

TPU-native: init() wires jax.distributed for multi-host (the coordinator
replaces gen_nccl_id RPC bootstrap, SURVEY §5), builds the global mesh from
the strategy's parallel degrees, and distributed_optimizer returns a wrapper
that applies the Program-IR transpiles (grad allreduce / local sgd /
gradient merge / recompute) before minimize.
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax

from ..core import framework
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase
from .strategy import DistributedStrategy


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._mesh = None
        self._mesh_key = None
        self._inited = False

    # -- lifecycle (reference fleet_base.py:64 init) -----------------------

    def init(self, role_maker: Optional[RoleMakerBase] = None,
             is_collective: bool = True):
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective)
        n = self._role_maker.worker_num()
        if n > 1 and not jax.distributed.is_initialized():
            coord = self._role_maker.coordinator_address()
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=n,
                process_id=self._role_maker.worker_index())
        self._inited = True
        return self

    @property
    def inited(self) -> bool:
        return self._inited

    # -- identity ----------------------------------------------------------

    def is_first_worker(self) -> bool:
        return self._role_maker.is_first_worker()

    def worker_index(self) -> int:
        return self._role_maker.worker_index()

    def worker_num(self) -> int:
        return self._role_maker.worker_num()

    def is_worker(self) -> bool:
        return self._role_maker.is_worker()

    def is_server(self) -> bool:
        return self._role_maker.is_server()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        if jax.distributed.is_initialized() and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("fleet_barrier_worker")

    # -- mesh --------------------------------------------------------------

    def mesh(self, strategy: Optional[DistributedStrategy] = None):
        from .mesh import make_hybrid_mesh, make_mesh

        strategy = strategy or self._strategy or DistributedStrategy()
        cfg = strategy.mesh_config()
        key = tuple(sorted(cfg.resolve(len(jax.devices())).items()))
        if self._mesh is None or self._mesh_key != key:
            # multi-host (or the explicit hierarchical knob): DCN×ICI
            # factorized mesh so dp gradients reduce intra-host first
            if jax.process_count() > 1 or strategy.use_hierarchical_allreduce:
                self._mesh = make_hybrid_mesh(cfg)
            else:
                self._mesh = make_mesh(cfg)
            self._mesh_key = key
        return self._mesh

    # -- the optimizer wrapper (reference CollectiveOptimizer) -------------

    def distributed_optimizer(self, optimizer,
                              strategy: Optional[DistributedStrategy] = None):
        self._strategy = strategy or DistributedStrategy()
        return DistributedOptimizer(self, optimizer, self._strategy)

    # -- program accessors (reference fleet_base properties) ---------------

    @property
    def main_program(self):
        return framework.default_main_program()

    @property
    def startup_program(self):
        return framework.default_startup_program()

    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io

        if self.is_first_worker():
            io.save_persistables(executor, dirname, main_program)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None, **kw):
        from .. import io

        if self.is_first_worker():
            io.save_inference_model(dirname, feeded_var_names, target_vars,
                                    executor, main_program=main_program, **kw)


class DistributedOptimizer:
    """reference: incubate/fleet/collective/__init__.py:117
    CollectiveOptimizer — wraps a regular optimizer, applies distributed
    rewrites during minimize."""

    def __init__(self, fleet: Fleet, optimizer, strategy: DistributedStrategy):
        self._fleet = fleet
        self._inner = optimizer
        self._strategy = strategy

    def backward(self, loss, **kw):
        return self._inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .. import optimizer as opt_mod

        inner = self._inner
        st = self._strategy
        if st.use_dgc and not isinstance(inner, opt_mod.DGCMomentumOptimizer):
            raise ValueError(
                "use_dgc requires passing a DGCMomentumOptimizer as the "
                "inner optimizer (reference: fleet applies DGC through the "
                "optimizer, optimizer.py:868)")
        if st.use_amp:
            from ..amp import decorate as amp_decorate

            inner = amp_decorate(inner,
                                 init_loss_scaling=st.amp_loss_scale)
        if st.recompute:
            rc = opt_mod.RecomputeOptimizer(inner)
            rc._set_checkpoints(st.recompute_checkpoints or [])
            inner = rc
        if st.gradient_merge_k > 1:
            inner = opt_mod.GradientMergeOptimizer(
                inner, k_steps=st.gradient_merge_k)
        ops, p2g = inner.minimize(loss, startup_program, parameter_list,
                                  no_grad_set)

        # Explicit in-graph collectives only for the SPMDRunner execution
        # mode (reference collective-transpiler semantics); the default
        # CompiledProgram/GSPMD path derives the reduction from shardings.
        if st.use_graph_collectives:
            program = loss.block.program
            mesh = self._fleet.mesh(st)
            n = mesh.shape["dp"]
            if st.use_local_sgd:
                from .collective import LocalSGD

                LocalSGD(nranks=n, k_steps=st.local_sgd_steps).transpile(program)
            else:
                from .collective import GradAllReduce

                GradAllReduce(nranks=n).transpile(program)
        return ops, p2g


fleet = Fleet()
