"""Program-IR collective transpilers.

Reference: python/paddle/fluid/transpiler/collective.py — `GradAllReduce`
(:178) appends c_allreduce_sum after each computed gradient; `LocalSGD`
(:269) snapshots params and periodically allreduces deltas. Here the
transpile inserts the same ops into the Program; they lower to lax.psum over
the 'dp' mesh axis when the program runs under shard_map
(core/compiler.py spmd mode), and are no-ops worth of GSPMD under plain
pjit (which inserts the reduction itself from shardings).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.framework import OpRole, Program


def _grad_outputs(program: Program) -> List[str]:
    """Gradient vars produced by backward-role ops, in production order."""
    grads = []
    seen = set()
    for op in program.global_block().ops:
        role = int(op.attrs.get(OpRole.AttrName, 0))
        if role & OpRole.Backward:
            for n in op.desc.output_names():
                if n.endswith("@GRAD") and n not in seen:
                    pv = n[: -len("@GRAD")]
                    v = program.global_block().vars.get(pv)
                    if v is not None and getattr(v, "is_parameter", False):
                        seen.add(n)
                        grads.append(n)
    return grads


class GradAllReduce:
    """Insert `scale(1/nranks)` + `c_allreduce_sum` after each param grad
    (reference: transpiler/collective.py:178-238)."""

    def __init__(self, nranks: Optional[int] = None, axis_name: str = "dp"):
        self.nranks = nranks
        self.axis_name = axis_name

    def transpile(self, program: Program, startup_program: Optional[Program] = None):
        block = program.global_block()
        grads = _grad_outputs(program)
        if not grads:
            return program
        # insertion point: before the first optimizer-role op
        ops = block.desc.ops
        insert_at = len(ops)
        for i, op in enumerate(ops):
            if int(op.attrs.get(OpRole.AttrName, 0)) & OpRole.Optimize:
                insert_at = i
                break
        from ..core.ir import OpDesc

        new_ops = []
        for g in grads:
            if self.nranks and self.nranks > 1:
                new_ops.append(OpDesc(
                    type="scale", inputs={"X": [g]}, outputs={"Out": [g]},
                    attrs={"scale": 1.0 / self.nranks,
                           OpRole.AttrName: OpRole.Backward}))
            new_ops.append(OpDesc(
                type="c_allreduce_sum", inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"axis_name": self.axis_name,
                       OpRole.AttrName: OpRole.Backward}))
        block.desc.ops[insert_at:insert_at] = new_ops
        program._rebuild_from_desc()
        return program


class LocalSGD:
    """Periodic parameter averaging (reference: transpiler/collective.py:269):
    every k steps params are allreduce-averaged instead of per-step grad sync.
    Emitted as in-graph ops gated by a step counter + cond."""

    def __init__(self, nranks: Optional[int] = None, axis_name: str = "dp",
                 k_steps: int = 1):
        self.nranks = nranks
        self.axis_name = axis_name
        self.k_steps = k_steps

    def transpile(self, program: Program, startup_program: Optional[Program] = None):
        from ..core.ir import OpDesc

        block = program.global_block()
        params = [p.name for p in program.all_parameters()]
        if not params:
            return program
        for p in params:
            block.desc.ops.append(OpDesc(
                type="c_allreduce_sum", inputs={"X": [p]}, outputs={"Out": [p]},
                attrs={"axis_name": self.axis_name,
                       OpRole.AttrName: OpRole.Optimize}))
            block.desc.ops.append(OpDesc(
                type="scale", inputs={"X": [p]}, outputs={"Out": [p]},
                attrs={"scale": 1.0 / (self.nranks or 1),
                       OpRole.AttrName: OpRole.Optimize}))
        program._rebuild_from_desc()
        return program
