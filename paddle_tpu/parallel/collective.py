"""Program-IR collective transpilers.

Reference: python/paddle/fluid/transpiler/collective.py — `GradAllReduce`
(:178) appends c_allreduce_sum after each computed gradient; `LocalSGD`
(:269) snapshots params and periodically allreduces deltas. Here the
transpile inserts the same ops into the Program; they lower to lax.psum over
the 'dp' mesh axis when the program runs under shard_map
(core/compiler.py spmd mode), and are no-ops worth of GSPMD under plain
pjit (which inserts the reduction itself from shardings).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.framework import OpRole, Program


def _grad_outputs(program: Program) -> List[str]:
    """Gradient vars produced by backward-role ops, in production order."""
    grads = []
    seen = set()
    for op in program.global_block().ops:
        role = int(op.attrs.get(OpRole.AttrName, 0))
        if role & OpRole.Backward:
            for n in op.desc.output_names():
                if n.endswith("@GRAD") and n not in seen:
                    pv = n[: -len("@GRAD")]
                    v = program.global_block().vars.get(pv)
                    if v is not None and v.desc.is_parameter:
                        seen.add(n)
                        grads.append(n)
    return grads


class GradAllReduce:
    """Insert `scale(1/nranks)` + `c_allreduce_sum` after each param grad
    (reference: transpiler/collective.py:178-238)."""

    def __init__(self, nranks: Optional[int] = None, axis_name: str = "dp"):
        self.nranks = nranks
        self.axis_name = axis_name

    def transpile(self, program: Program, startup_program: Optional[Program] = None):
        block = program.global_block()
        grads = _grad_outputs(program)
        if not grads:
            return program
        # insertion point: before the first optimizer-role op
        ops = block.desc.ops
        insert_at = len(ops)
        for i, op in enumerate(ops):
            if int(op.attrs.get(OpRole.AttrName, 0)) & OpRole.Optimize:
                insert_at = i
                break
        from ..core.ir import OpDesc

        new_ops = []
        for g in grads:
            if self.nranks and self.nranks > 1:
                new_ops.append(OpDesc(
                    type="scale", inputs={"X": [g]}, outputs={"Out": [g]},
                    attrs={"scale": 1.0 / self.nranks,
                           OpRole.AttrName: OpRole.Backward}))
            new_ops.append(OpDesc(
                type="c_allreduce_sum", inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"axis_name": self.axis_name,
                       OpRole.AttrName: OpRole.Backward}))
        block.desc.ops[insert_at:insert_at] = new_ops
        program._rebuild_from_desc()
        return program


class LocalSGD:
    """Periodic parameter averaging (reference: transpiler/collective.py:269):
    every k steps params are allreduce-averaged instead of per-step grad
    sync, gated by a step counter inside a state-writing conditional
    (layers.cond_state)."""

    def __init__(self, nranks: Optional[int] = None, axis_name: str = "dp",
                 k_steps: int = 1):
        self.nranks = nranks
        self.axis_name = axis_name
        self.k_steps = max(1, int(k_steps))

    def transpile(self, program: Program, startup_program: Optional[Program] = None):
        from ..core.framework import program_guard, unique_name
        from ..core.ir import OpDesc
        from .. import layers as L
        from ..layers import control_flow, tensor as ltensor

        params = [p.name for p in program.all_parameters()]
        if not params:
            return program

        def _emit_averaging():
            block = program.current_block()
            for p in params:
                block.append_op(
                    type="c_allreduce_sum", inputs={"X": block.program.global_block().var(p)},
                    outputs={"Out": block.program.global_block().var(p)},
                    attrs={"axis_name": self.axis_name,
                           OpRole.AttrName: OpRole.Optimize})
                block.append_op(
                    type="scale", inputs={"X": block.program.global_block().var(p)},
                    outputs={"Out": block.program.global_block().var(p)},
                    attrs={"scale": 1.0 / (self.nranks or 1),
                           OpRole.AttrName: OpRole.Optimize})

        sp = startup_program
        from ..core import framework as fw

        guard_sp = sp if sp is not None else fw.default_startup_program()
        with program_guard(program, guard_sp):
            if self.k_steps == 1:
                _emit_averaging()
            else:
                step = ltensor.create_global_var(
                    [1], 0.0, "float32", persistable=True,
                    name=unique_name.generate("@LOCAL_SGD_STEP@"))
                program.global_block().append_op(
                    type="increment", inputs={"X": step},
                    outputs={"Out": step}, attrs={"step": 1.0})
                k = ltensor.fill_constant([1], "float32", float(self.k_steps))
                rem = program.global_block().create_var(
                    name=unique_name.generate("lsgd_rem"), shape=[1],
                    dtype="float32")
                program.global_block().append_op(
                    type="elementwise_mod", inputs={"X": step, "Y": k},
                    outputs={"Out": rem})
                pred = L.equal(rem, ltensor.fill_constant([1], "float32", 0.0))
                control_flow.cond_state(pred, _emit_averaging)
        return program
