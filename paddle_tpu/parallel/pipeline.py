"""Pipeline parallelism: GPipe schedule over the 'pp' mesh axis.

Reference: PipelineTrainer/SectionWorker (paddle/fluid/framework/trainer.h:113,
device_worker.h:267, section_worker.cc:141) — program sections run in
threads connected by blocking ScopeQueues, microbatches flowing through.

TPU-native: shard_map over 'pp' + lax.ppermute. Layer parameters are stacked
[S, ...] and sharded so each device holds one stage; a lax.scan runs
n_micro + S - 1 ticks, each tick computing the local stage on the activation
in flight and collective-permuting it to the next stage. Reverse-mode autodiff
through scan+ppermute gives the backward pipeline for free (the reference's
async pipeline needed hand-built section workers).

The schedule bubble is (S-1)/(n_micro + S - 1) — same as GPipe; raise
n_microbatches to amortize.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..observability import telemetry as _telemetry

# Last trace's stream dtype decision, recorded for evidence (VERDICT r5
# weak #5): the CPU SPMD partitioner shim below streams f32 where TPU
# would stream the native (possibly bf16) dtype, so the multichip
# dryrun prints this to make the divergence visible in MULTICHIP logs
# instead of a silent difference.
_last_stream = {"dtype": None, "cpu_f32_shim": False}


def last_stream_info():
    """{'dtype': str|None, 'cpu_f32_shim': bool} of the most recent
    pipeline_apply trace (None before any trace)."""
    return dict(_last_stream)


def pipeline_apply(
    stage_fn: Callable,          # (stage_params, x) -> y, stage-local
    stage_params,                # pytree, leaves stacked [S, ...]
    x,                           # [n_micro, mb, ...] microbatched input
    mesh: Mesh,
    axis: str = "pp",
    data_axis: str = "dp",
):
    """Run the GPipe pipeline; returns [n_micro, mb, ...] outputs.

    Call inside jit under the `mesh` context. 'pp' AND 'dp' are manualized
    (the microbatch dim is split over dp — data parallelism composes with
    the pipeline by construction; partial-manual regions with auto-dp
    consumers crash XLA's SPMD partitioner in this build). tp/sp act on the
    stage body only through the enclosing program's GSPMD shardings.
    """
    S = mesh.shape[axis]
    n_micro = x.shape[0]
    # Recorded at trace time (this runs under jit): schedule shape +
    # bubble, one PIPELINE_TRACES tick per retrace — a retrace in steady
    # state is itself a signal worth alerting on.
    _telemetry.record_pipeline_trace(axis, int(S), int(n_micro))
    _last_stream["dtype"] = str(x.dtype)
    _last_stream["cpu_f32_shim"] = False
    if S == 1:
        def body1(carry, xm):
            return carry, stage_fn(
                jax.tree.map(lambda p: p[0], stage_params), xm)
        _, ys = jax.lax.scan(body1, 0, x)
        return ys

    # XLA's CPU SPMD partitioner CHECK-fails resharding bf16 copies in
    # manual regions ("Invalid binary instruction opcode copy"); stream f32
    # there. TPU keeps the native dtype (half the ppermute ICI traffic).
    stream_dtype = x.dtype
    cpu_bf16_bug = (mesh.devices.flat[0].platform == "cpu"
                    and x.dtype == jnp.bfloat16)
    if cpu_bf16_bug:
        x = x.astype(jnp.float32)
    _last_stream["dtype"] = str(x.dtype)
    _last_stream["cpu_f32_shim"] = bool(cpu_bf16_bug)

    T = n_micro + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]
    stage_spec = jax.tree.map(lambda _: P(axis), stage_params)
    manual = {axis}
    stream_spec = P(None)
    if data_axis in mesh.axis_names and mesh.shape[data_axis] > 1 \
            and x.shape[1] % mesh.shape[data_axis] == 0:
        manual.add(data_axis)
        stream_spec = P(None, data_axis)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(stage_spec, stream_spec),
        out_specs=stream_spec,
        axis_names=manual,
        check_vma=False)
    def run(local_params, stream):
        lp = jax.tree.map(lambda p: p[0], local_params)
        idx = jax.lax.axis_index(axis)
        mb_shape = stream.shape[1:]
        is_first = (idx == 0)
        is_last = (idx == S - 1)

        def tick(carry, t):
            state, outputs = carry
            inject = jax.lax.dynamic_index_in_dim(stream, jnp.minimum(t, n_micro - 1),
                                                  keepdims=False)
            x_in = jnp.where(is_first, inject, state)
            y = stage_fn(lp, x_in)
            # last stage's result for microbatch (t - S + 1); writes for
            # t < S-1 land clamped on slot 0 and are overwritten by the
            # real slot-0 write at t = S-1 (time-ordered scan)
            out_t = jnp.clip(t - (S - 1), 0, n_micro - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(outputs, y, out_t, 0)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, outputs), None

        init_state = jnp.zeros(mb_shape, stream.dtype)
        outputs0 = jnp.zeros((n_micro,) + mb_shape, stream.dtype)
        (_, outputs), _ = jax.lax.scan(tick, (init_state, outputs0),
                                       jnp.arange(T))
        # only the last stage's buffer is meaningful — mask & sum-broadcast
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    out = run(stage_params, x)
    return out.astype(stream_dtype) if cpu_bf16_bug else out
