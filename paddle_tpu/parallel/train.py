"""Sharded train-step builder — the ParallelExecutor of the rebuild.

Reference: ParallelExecutor clones the graph per device and inserts NCCL
all-reduces (parallel_executor.cc, multi_devices_graph_pass.cc:454). Here ONE
jit over a Mesh with NamedShardings on params/optimizer state/batch does the
same: GSPMD partitions the computation and inserts the collectives. The
BuildStrategy knobs map to:

  reduce_strategy AllReduce ↔ optimizer state replicated over 'dp'
  reduce_strategy Reduce    ↔ optimizer state sharded over 'dp' (ZeRO-1)
  gradient merge / batch-merge pass ↔ accum_steps (lax.scan of microbatches)
  recompute ↔ jax.checkpoint on the loss fn
  AMP ↔ bf16 activations in the model + fp32 params here
"""

from __future__ import annotations

import dataclasses
import functools
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import precision as _precision
from ..models.common import Params, ParamAxes, is_trainable
from ..observability import memwatch as _memwatch
from .sharding import LogicalRules, current_rules, named_sharding_tree


@dataclasses.dataclass
class TrainStrategy:
    """The rebuild's BuildStrategy (details/build_strategy.h:37)."""

    shard_optimizer_states: bool = True   # Reduce/ZeRO-1 vs AllReduce
    accum_steps: int = 1                  # gradient merge (multi_batch_merge_pass)
    recompute: bool = False               # RecomputeOptimizer
    # Rematerialization policy when recompute=True (the reference's
    # RecomputeOptimizer(checkpoints=...) selects WHICH activations to
    # keep; here the jax.checkpoint policy does):
    #   None / "nothing"  - save nothing, recompute everything (blanket)
    #   "dots"            - save every matmul/einsum output (attention
    #                       scores and projections are NOT recomputed —
    #                       the long-sequence-friendly policy)
    #   "dots_no_batch"   - save contraction results with no batch dims
    #                       (weights-gradient reuse, smaller footprint)
    recompute_policy: Optional[str] = None
    clip_global_norm: Optional[float] = None


class TrainState:
    """params + opt state + step, all sharded.

    `loss_scale` is the dynamic loss-scaling state of a mixed-precision
    policy (core/precision.py init_loss_scale_state: scale, good_steps,
    cumulative overflow/growth counters) and None under f32/bf16 — a
    None subtree has no leaves, so checkpoints written before this
    field existed keep restoring unchanged, while mixed-precision
    checkpoints round-trip the scale bit-identically through
    CheckpointManager."""

    def __init__(self, params, opt_state, step, loss_scale=None):
        self.params = params
        self.opt_state = opt_state
        self.step = step
        self.loss_scale = loss_scale
        _live_states.add(self)

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step,
                self.loss_scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)

# HBM owner attribution (memwatch): every live TrainState volunteers its
# param and optimizer trees. Provider callables (not a one-time array
# registration) because the donated update loop replaces every buffer
# each step; registered once at import — memwatch rebuilds the id→owner
# map per sweep, so tree_unflatten'd tracer instances that land in the
# WeakSet during jit tracing are harmless (their leaf ids never match a
# live device array).
_live_states: "weakref.WeakSet[TrainState]" = weakref.WeakSet()


def _live_param_arrays():
    for st in list(_live_states):
        yield from jax.tree_util.tree_leaves(st.params)


def _live_opt_arrays():
    for st in list(_live_states):
        yield from jax.tree_util.tree_leaves(st.opt_state)


_memwatch.register_provider("params", _live_param_arrays)
_memwatch.register_provider("optimizer", _live_opt_arrays)


def param_shardings(mesh: Mesh, axes: ParamAxes,
                    rules: Optional[LogicalRules] = None) -> Dict[str, NamedSharding]:
    rules = rules or current_rules()
    return {k: NamedSharding(mesh, rules.spec(v)) for k, v in axes.items()}


def opt_state_sharding_like(opt_state, pspec_of_param, mesh: Mesh,
                            shard_over_dp: bool):
    """Optimizer moments inherit their param's spec; scalars replicated.
    With shard_over_dp (ZeRO-1), moments additionally shard their first
    unsharded axis over 'dp'."""

    def one(leaf_path_spec):
        return leaf_path_spec

    def spec_for(leaf, pspec: P):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return NamedSharding(mesh, P())
        spec = list(pspec) + [None] * (leaf.ndim - len(pspec))
        if shard_over_dp:
            # shard the largest unsharded dim over dp if divisible
            for i, s in enumerate(spec):
                if s is None and leaf.shape[i] % mesh.shape["dp"] == 0 and \
                        leaf.shape[i] >= mesh.shape["dp"]:
                    spec[i] = "dp"
                    break
        return NamedSharding(mesh, P(*spec))

    return spec_for


def make_train_step(
    loss_fn: Callable[[Params, Dict[str, jax.Array], jax.Array], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_axes: ParamAxes,
    rules: Optional[LogicalRules] = None,
    strategy: Optional[TrainStrategy] = None,
    batch_spec: Optional[P] = None,
    has_aux: bool = False,
    precision=None,
):
    """Returns (init_state_fn, step_fn).

    loss_fn(params, batch, rng) -> scalar loss. step_fn(state, batch, rng)
    -> (state, loss), jitted over `mesh` with full shardings.

    `precision` selects the core/precision.py policy (name or
    PrecisionPolicy; default resolves PADDLE_TPU_PRECISION, else f32):

      f32         — today's step, bit for bit.
      bf16        — params/opt state initialized AND computed in bf16.
      mixed_bf16  — f32 master params + optimizer state, loss/grads
                    computed with bf16-cast params and batch, plus
                    DYNAMIC LOSS SCALING: the scale/good-step state
                    lives in TrainState.loss_scale (checkpointed by
                    CheckpointManager), nonfinite grads skip the
                    update and shrink the scale, growth_interval clean
                    steps grow it, and cumulative overflow/growth
                    counters feed paddle_tpu_amp_total via
                    sync_loss_scale_metrics (train_loop calls it).
    """
    strategy = strategy or TrainStrategy()
    rules = rules or current_rules()
    policy = _precision.resolve(explicit=precision)
    p_shardings = param_shardings(mesh, param_axes, rules)
    batch_spec = batch_spec if batch_spec is not None else rules.spec(("batch", "seq"))
    repl = NamedSharding(mesh, P())

    policies = {
        None: None,
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_no_batch":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    if strategy.recompute_policy not in policies:
        raise ValueError(
            f"unknown recompute_policy {strategy.recompute_policy!r}; "
            f"choose from {sorted(k for k in policies if k)} or None")
    if strategy.recompute_policy is not None and not strategy.recompute:
        raise ValueError(
            "recompute_policy is set but recompute=False — enable "
            "recompute=True for the policy to take effect")
    if strategy.recompute:
        # policy=None is jax.checkpoint's own default (save nothing)
        loss_fn = jax.checkpoint(
            loss_fn, policy=policies[strategy.recompute_policy])

    tx = optimizer
    if strategy.clip_global_norm:
        tx = optax.chain(optax.clip_by_global_norm(strategy.clip_global_norm),
                         optimizer)

    def mask_fn(params):
        return {k: is_trainable(k) for k in params}

    tx = optax.masked(tx, mask_fn)

    def init_state(params: Params) -> TrainState:
        """Takes ownership of `params`: buffers may be aliased into the
        donated TrainState (the reference's overwrite-in-scope semantics,
        scope.h). Re-init or copy if the caller needs them afterwards."""
        if policy.cast_state:
            # pure low-precision: master weights themselves live at the
            # compute width (mixed policies keep f32 masters instead)
            params = {k: _precision.cast_floating(
                jnp.asarray(v), policy.compute_dtype)
                for k, v in params.items()}
        params = {
            k: jax.device_put(v, p_shardings[k]) for k, v in params.items()
        }
        opt_state = jax.jit(
            tx.init,
            out_shardings=_opt_shardings(tx, params, p_shardings))(params)
        step = jax.device_put(jnp.zeros((), jnp.int32), repl)
        loss_scale = _precision.init_loss_scale_state(policy)
        if loss_scale is not None:
            loss_scale = jax.device_put(loss_scale, repl)
        return TrainState(params, opt_state, step, loss_scale)

    def _opt_shardings(tx, params, p_shardings):
        shape = jax.eval_shape(tx.init, params)
        spec_for = opt_state_sharding_like(
            None, None, mesh, strategy.shard_optimizer_states)

        def leaf_sharding(path, leaf):
            # moments are dicts keyed like params → reuse param specs
            name = None
            for e in path:
                if hasattr(e, "key") and isinstance(getattr(e, "key"), str) \
                        and e.key in p_shardings:
                    name = e.key
            if name is not None:
                return spec_for(leaf, p_shardings[name].spec)
            return NamedSharding(mesh, P())

        return jax.tree_util.tree_map_with_path(leaf_sharding, shape)

    def microbatch_grads(fn, params, batch, rng):
        if strategy.accum_steps == 1:
            if has_aux:
                (loss, aux), grads = jax.value_and_grad(
                    fn, has_aux=True)(params, batch, rng)
                return loss, grads, aux
            loss, grads = jax.value_and_grad(fn)(params, batch, rng)
            return loss, grads, {}
        # gradient merge: scan over accum_steps microbatches
        # (reference: multi_batch_merge_pass.cc / gradient_merge)
        def mb(carry, xs):
            acc, loss_sum = carry
            mb_batch, mb_rng = xs
            if has_aux:
                (loss, aux), g = jax.value_and_grad(
                    fn, has_aux=True)(params, mb_batch, mb_rng)
            else:
                loss, g = jax.value_and_grad(fn)(params, mb_batch, mb_rng)
                aux = {}
            acc = jax.tree.map(jnp.add, acc, g)
            return (acc, loss_sum + loss), aux

        zero = jax.tree.map(jnp.zeros_like, params)
        n = strategy.accum_steps
        mb_batches = jax.tree.map(
            lambda x: x.reshape((n, x.shape[0] // n) + x.shape[1:]), batch)
        rngs = jax.random.split(rng, n)
        (grads, loss_sum), auxs = jax.lax.scan(mb, (zero, 0.0), (mb_batches, rngs))
        # state updates (BN stats): keep the last microbatch's values
        aux = jax.tree.map(lambda a: a[-1], auxs) if has_aux else {}
        inv = 1.0 / n
        return loss_sum * inv, jax.tree.map(lambda g: g * inv, grads), aux

    use_amp = policy.dynamic_loss_scale and policy.compute_dtype is not None

    def step_fn(state: TrainState, batch, rng):
        if policy.compute_dtype is not None:
            # compute-width batch: an already-bf16 input pipeline makes
            # this the identity; under jit the cast fuses either way
            batch = _precision.cast_tree(batch, policy.compute_dtype)
        if not use_amp:
            loss, grads, aux = microbatch_grads(loss_fn, state.params,
                                                batch, rng)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            # aux = non-trainable state updates keyed like params (BN stats)
            for k, v in aux.items():
                params[k] = v.astype(params[k].dtype)
            return TrainState(params, opt_state, state.step + 1,
                              state.loss_scale), loss

        # mixed policy: bf16/f16 compute against f32 master params +
        # dynamic loss scaling (reference: contrib/mixed_precision
        # check_finite_and_unscale / update_loss_scaling ops, rebuilt
        # jnp-natively with the scale state inside TrainState)
        ls = state.loss_scale
        scale = ls["scale"]

        def scaled_loss(p, b, r):
            pc = _precision.cast_tree(p, policy.compute_dtype)
            if has_aux:
                loss, aux = loss_fn(pc, b, r)
                return loss.astype(jnp.float32) * scale, aux
            return loss_fn(pc, b, r).astype(jnp.float32) * scale

        loss_s, grads_s, aux = microbatch_grads(scaled_loss, state.params,
                                                batch, rng)
        inv = 1.0 / scale
        # grads come back f32 (the param cast's transpose casts up);
        # astype guards exotic loss_fns that detach to compute dtype
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv,
                             grads_s)
        loss = loss_s * inv
        finite = jnp.isfinite(loss)
        for g in jax.tree.leaves(grads):
            finite = finite & jnp.all(jnp.isfinite(g))

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        for k, v in aux.items():
            new_params[k] = v.astype(new_params[k].dtype)
        # overflow skips the whole update: params AND optimizer state
        # keep their pre-step values (select, so the nonfinite updates
        # never propagate)
        params = jax.tree.map(lambda new, old: jnp.where(finite, new, old),
                              new_params, state.params)
        opt_state = jax.tree.map(
            lambda new, old: jnp.where(finite, new, old),
            new_opt, state.opt_state)

        good = ls["good_steps"] + 1
        grow = finite & (good >= policy.growth_interval)
        new_scale = jnp.where(
            finite,
            jnp.where(grow,
                      jnp.minimum(scale * policy.incr_ratio,
                                  policy.max_loss_scale),
                      scale),
            jnp.maximum(scale * policy.decr_ratio,
                        policy.min_loss_scale))
        new_ls = {
            "scale": new_scale.astype(jnp.float32),
            "good_steps": jnp.where(finite & ~grow, good,
                                    0).astype(jnp.int32),
            "overflows": ls["overflows"] + (~finite).astype(jnp.int32),
            "growths": ls["growths"] + grow.astype(jnp.int32),
        }
        return TrainState(params, opt_state, state.step + 1, new_ls), loss

    state_shardings_cache = {}

    def jitted_step(state: TrainState, batch, rng):
        key = id(mesh)
        if key not in state_shardings_cache:
            st_sh = TrainState(
                p_shardings,
                jax.tree.map(lambda x: x.sharding, state.opt_state),
                repl,
                jax.tree.map(lambda x: repl, state.loss_scale))
            def leaf_sharding(x):
                spec = []
                for i, ax in enumerate(tuple(batch_spec)[:x.ndim]):
                    if isinstance(ax, str) and x.shape[i] % mesh.shape[ax] == 0:
                        spec.append(ax)
                    else:
                        spec.append(None)  # indivisible dim stays replicated
                return NamedSharding(mesh, P(*spec))

            batch_shardings = jax.tree.map(leaf_sharding, batch)
            state_shardings_cache[key] = jax.jit(
                step_fn,
                in_shardings=(st_sh, batch_shardings, repl),
                out_shardings=(st_sh, repl),
                donate_argnums=(0,),
            )
        return state_shardings_cache[key](state, batch, rng)

    return init_state, jitted_step


def sync_loss_scale_metrics(state: TrainState,
                            last: Optional[Dict[str, Any]] = None
                            ) -> Optional[Dict[str, Any]]:
    """Diff TrainState.loss_scale's cumulative device counters against
    `last` (the previous return value) and tick
    paddle_tpu_amp_total{event=overflow|growth|skip} + the loss-scale
    gauge; overflows also land as `amp_overflow` events. Reads three
    device scalars, so callers sync at a cadence they already block at
    (train_loop: per step in sync mode, at drain in async mode).
    Returns the new cumulative snapshot (None loss_scale → `last`
    unchanged). `last=None` BASELINES without recording — a restored
    checkpoint's lifetime counters must not replay as fresh events."""
    from ..observability import telemetry as _telemetry

    ls = getattr(state, "loss_scale", None)
    if ls is None:
        return last
    cur = {"overflows": int(ls["overflows"]),
           "growths": int(ls["growths"]),
           "scale": float(ls["scale"])}
    _telemetry.AMP_LOSS_SCALE.set(cur["scale"])
    if last is None:
        return cur
    prev = last
    step = None
    try:
        step = int(state.step)
    except Exception:  # lint-exempt:swallow: step is optional telemetry on a diffed counter
        pass
    d_over = cur["overflows"] - int(prev.get("overflows", 0))
    d_grow = cur["growths"] - int(prev.get("growths", 0))
    _telemetry.record_amp("overflow", d_over, step=step,
                          scale=cur["scale"])
    _telemetry.record_amp("skip", d_over)
    _telemetry.record_amp("growth", d_grow, scale=cur["scale"])
    return cur


def train_loop(step_fn, state: TrainState, batches, *, rng=None,
               manager=None, save_every: Optional[int] = None,
               controller=None, max_steps: Optional[int] = None,
               fetch_window: Optional[int] = None,
               resize_check: Optional[Callable[[], bool]] = None):
    """Fault-tolerance-aware driver for a `make_train_step` step_fn.

    The step boundary is the only safe interruption point (no donated
    buffers in flight, device state consistent), so everything the
    resilience layer does hangs off this loop:

      - fault injection: `faults.check("step", step=N)` fires before
        each step — `PADDLE_TPU_FAULT_SPEC="step=N:crash"` kills the
        process exactly there, which is how the kill-and-resume tests
        provoke arbitrary-step deaths;
      - preemption: when a graceful stop was requested (SIGTERM with
        PADDLE_TPU_PREEMPT_SIGNALS set, or programmatically), the loop
        writes a final checkpoint via `manager` and returns
        stop="preempted" — the caller exits with PREEMPT_EXIT_CODE;
      - periodic checkpoints: every `save_every` completed steps,
        `manager.save(state)` (commit marker + retention inside);
      - recovery: a NumericsError from the post-step loss check (or a
        blown warn-anomaly budget) is routed to `controller.handle`,
        which skips the batch, rolls the state back to the last
        committed checkpoint, or aborts per its RecoveryPolicy.

    `batches` is either an iterable of batches or a callable
    `batch_fn(step) -> batch | None` (None stops the loop). The callable
    form keys data on the GLOBAL step number, which is what makes a
    resumed run replay the exact uninterrupted trajectory — and what a
    rollback needs to re-feed the steps it rewound over (an iterator
    cannot rewind; with one, a rollback continues on fresh batches).
    Per-step randomness is `jax.random.fold_in(rng, step)` for the same
    reason. Returns (state, losses, stop) where `losses` maps executed
    step number -> float loss and `stop` is
    "completed" | "preempted" | "exhausted" | "resize".

    `resize_check` is the elastic-membership hook
    (distributed.elastic): it is consulted immediately AFTER each
    periodic checkpoint commits — the only boundary where every
    surviving worker has identical durable state — and a True return
    stops the loop with stop="resize" so the driver can re-rendezvous,
    re-form the mesh for the new world size, and reshard the
    just-committed checkpoint onto it. It requires `manager` +
    `save_every`; without periodic checkpoints there is no safe
    boundary to re-form at.

    Loss fetching is ASYNC by default: `float(loss)` every step is a
    full host round trip that serializes the device on the host loop,
    so losses are parked as lazy FetchHandles and resolved only when
    `fetch_window` (default 2) of them are outstanding — the host runs
    ahead dispatching while the device computes, blocking only when it
    outruns the device by the window (recorded as host-blocked time).
    The trajectory is bit-identical to synchronous fetching: the same
    arrays are resolved, just later. A per-step loss CONSUMER forces
    fetch_window=1 automatically: health numerics checks and recovery
    controllers must see step N's loss before step N+1 dispatches.
    """
    import time as _time

    from collections import deque as _deque

    from ..core import async_exec as _async
    from ..observability import events as _events
    from ..observability import health as _health
    from ..ps import errors as _ps_errors
    from ..resilience import faults as _faults
    from ..resilience import preemption as _preempt

    _preempt.maybe_install_from_env()
    if resize_check is not None and (manager is None or not save_every):
        raise ValueError(
            "resize_check requires manager + save_every — without "
            "periodic checkpoints there is no boundary at which it is "
            "ever consulted")
    if controller is not None:
        controller.attach()
    if rng is None:
        rng = jax.random.key(0)
    get_batch = batches if callable(batches) else None
    batch_iter = iter(batches) if get_batch is None else None
    losses: Dict[int, float] = {}
    steps_done = 0
    stop = "completed"
    window = max(1, int(fetch_window or _async.DEFAULT_IN_FLIGHT))
    if controller is not None or _health.check_level():
        window = 1  # per-step loss consumers need the value NOW
    pending: "_deque[Tuple[int, Any]]" = _deque()

    def _resolve_oldest():
        step_i, h = pending.popleft()
        # backpressure keeping run-ahead bounded, not a pipeline stall
        losses[step_i] = float(np.asarray(
            h.result(stall=False)[0]).reshape(()))

    # async mode tracks the step number host-side: `int(state.step)` is
    # a device fetch of the step JUST dispatched, so deriving it every
    # iteration would re-serialize the loop the fetch window exists to
    # overlap. The counter is seeded from the (possibly restored) state
    # once and advances with each successful step — the sync/controller
    # paths keep reading the authoritative device value (rollback
    # rewinds it).
    host_step = int(state.step) if window > 1 else None
    amp_seen = sync_loss_scale_metrics(state) \
        if getattr(state, "loss_scale", None) is not None else None
    t0 = _time.perf_counter()
    try:
        while True:
            if max_steps is not None and steps_done >= max_steps:
                stop = "exhausted"
                break
            step_no = host_step if host_step is not None \
                else int(state.step)
            _faults.check("step", step=step_no)
            if _preempt.stop_requested():
                stop = "preempted"
                if manager is not None and not manager.is_committed(
                        manager.step_dir(step_no)):
                    manager.save(state)
                break
            if controller is not None and controller.should_act():
                action, state = controller.handle(None, state,
                                                  step=step_no)
                if action == "rollback":
                    continue  # step_no re-derives from the rewound state
            if get_batch is not None:
                batch = get_batch(step_no)
                if batch is None:
                    break
            else:
                batch = next(batch_iter, None)
                if batch is None:
                    break
            step_rng = jax.random.fold_in(rng, step_no)
            try:
                state, loss = step_fn(state, batch, step_rng)
                if window > 1:
                    # resolve-first: never more than `window` handles
                    # (and their device buffers) outstanding at once
                    while len(pending) >= window:
                        _resolve_oldest()
                    pending.append((step_no, _async.FetchHandle(
                        [loss], site="train_loop")))
                    host_step += 1
                else:
                    loss_val = float(loss)
                    if _health.check_level():
                        _health.check_numerics(
                            "trainer_loss", [("loss", loss_val)],
                            step=step_no)
                    losses[step_no] = loss_val
                    if amp_seen is not None:
                        # sync mode already blocked on the loss; the
                        # loss-scale counters ride the same sync so
                        # overflow events carry exact step attribution
                        amp_seen = sync_loss_scale_metrics(state,
                                                           amp_seen)
            except (_health.NumericsError, _ps_errors.PSUnavailableError) \
                    as e:
                # PSUnavailableError: a PS pull/push exhausted its
                # reconnect+retry budget mid-step (the resilient client
                # already rode out anything shorter). Routed through the
                # same RecoveryPolicy as a numerics anomaly: skip_batch
                # retries against the (possibly respawned) server next
                # step, rollback rewinds past any half-applied pushes,
                # abort propagates.
                if controller is None:
                    raise
                action, state = controller.handle(e, state, step=step_no)
                if action == "skip_batch":
                    steps_done += 1
                continue
            steps_done += 1
            completed = host_step if host_step is not None \
                else int(state.step)
            if (manager is not None and save_every
                    and completed % save_every == 0):
                manager.save(state)
                if resize_check is not None and resize_check():
                    # elastic membership changed: the checkpoint just
                    # committed IS the re-rendezvous boundary — hand
                    # control back so the driver can re-form the mesh
                    # and reshard (distributed.elastic)
                    stop = "resize"
                    break
    finally:
        while pending:  # drain: every executed step's loss lands
            _resolve_oldest()
        if amp_seen is not None:
            # async mode: aggregate outcome counts land at drain time
            amp_seen = sync_loss_scale_metrics(state, amp_seen)
        if controller is not None:
            controller.detach()
    seconds = _time.perf_counter() - t0
    _events.emit("step_summary", site="train_loop", steps=steps_done,
                 stop=stop, final_step=int(state.step),
                 seconds=round(seconds, 6))
    return state, losses, stop
