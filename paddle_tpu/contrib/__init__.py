"""fluid.contrib utility surface (reference: python/paddle/fluid/contrib/
memory_usage_calc.py, op_frequence.py, model_stat.py — the three
analysis helpers alongside the slim/AMP/quant toolkits, which live in
paddle_tpu.slim / paddle_tpu.amp here)."""

from .utils import memory_usage, op_freq_statistic, summary  # noqa: F401

__all__ = ["memory_usage", "op_freq_statistic", "summary"]
