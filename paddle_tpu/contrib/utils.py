"""Program analysis helpers.

Reference: contrib/memory_usage_calc.py:46 `memory_usage` (estimate a
program's memory band for a batch size), contrib/op_frequence.py:23
`op_freq_statistic` (single-op and adjacent-pair frequencies),
contrib/model_stat.py:40 `summary` (per-layer PARAMs/FLOPs table).
Reimplemented against this framework's Program IR; the memory band is
TPU-honest: the lower bound assumes XLA's buffer reuse collapses
non-persistable intermediates (the fusion/buffer-sharing the reference's
estimator cannot assume), the upper bound holds every var live at once.
"""

from __future__ import annotations

from collections import Counter, OrderedDict
from typing import Dict, List, Tuple

import numpy as np

from ..core.framework import Program
from ..core.ir import normalize_dtype

_DTYPE_BYTES = {"float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
                "int8": 1, "uint8": 1, "int16": 2, "int32": 4, "int64": 8,
                "bool": 1}


def _var_bytes(var, batch_size: int) -> int:
    shape = var.shape or ()
    numel = 1
    for s in shape:
        numel *= batch_size if s in (-1, None) else int(s)
    return numel * _DTYPE_BYTES.get(normalize_dtype(var.dtype), 4)


def memory_usage(program: Program, batch_size: int
                 ) -> Tuple[float, float, str]:
    """Estimate the program's device-memory band at `batch_size`.

    Returns (lower, upper, unit): lower = parameters/persistables plus
    the single largest transient var (XLA reuses intermediate buffers);
    upper = every var in the program live simultaneously (no reuse —
    the worst case a pathological schedule could need).
    """
    if not isinstance(program, Program):
        raise TypeError(f"memory_usage expects a Program, got "
                        f"{type(program).__name__}")
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    persist = transient = largest_transient = 0
    for block in program.blocks:
        for var in block.vars.values():
            b = _var_bytes(var.desc, batch_size)
            if var.desc.persistable:
                persist += b
            else:
                transient += b
                largest_transient = max(largest_transient, b)
    lower, upper = persist + largest_transient, persist + transient
    for unit, scale in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if upper >= scale:
            return lower / scale, upper / scale, unit
    return float(lower), float(upper), "B"


def op_freq_statistic(program: Program
                      ) -> Tuple[List[Tuple[str, int]],
                                 List[Tuple[str, int]]]:
    """Single-op and adjacent-pair frequencies, most-frequent first
    (reference: op_frequence.py:23; adjacency = an op consuming another
    op's output, the producer->consumer edges of the graph)."""
    if not isinstance(program, Program):
        raise TypeError(f"op_freq_statistic expects a Program, got "
                        f"{type(program).__name__}")
    uni: Counter = Counter()
    adj: Counter = Counter()
    for block in program.blocks:
        producer: Dict[str, str] = {}
        for op in block.desc.ops:
            uni[op.type] += 1
            for name in op.input_names():
                if name in producer:
                    adj[f"{producer[name]},{op.type}"] += 1
            for name in op.output_names():
                producer[name] = op.type
    return (sorted(uni.items(), key=lambda kv: -kv[1]),
            sorted(adj.items(), key=lambda kv: -kv[1]))


_SUMMARY_OPS = {"conv2d", "depthwise_conv2d", "conv2d_transpose", "mul",
                "matmul", "fc", "pool2d", "batch_norm", "layer_norm",
                "lookup_table", "lookup_table_v2", "softmax", "relu"}


def _op_stat(op, vars_, batch_size):
    """(params, flops) for one op from its var descs (MACs x2 = FLOPs)."""

    def shape_of(slot):
        names = op.inputs.get(slot) or op.outputs.get(slot) or []
        if not names or names[0] not in vars_:
            return None
        s = vars_[names[0]].shape or ()
        return tuple(batch_size if d in (-1, None) else int(d) for d in s)

    def numel(s):
        n = 1
        for d in s:
            n *= d
        return n

    t = op.type
    if t in ("conv2d", "depthwise_conv2d", "conv2d_transpose"):
        w = shape_of("Filter")
        out = shape_of("Output")
        if w is None or out is None:
            return 0, 0
        params = numel(w)
        # out numel x (cin/groups x kh x kw) MACs x2; the filter's dim 1
        # is ALREADY cin/groups (layers/nn.py builds
        # [num_filters, num_channels // groups, kh, kw])
        if t != "conv2d_transpose":
            flops = 2 * numel(out) * w[1] * w[2] * w[3]
        else:
            flops = 2 * numel(shape_of("Input") or out) * w[1] * w[2] * w[3]
        return params, flops
    if t in ("mul", "matmul", "fc"):
        wslot = "Y" if (op.inputs.get("Y") or [None])[0] else "W"
        w = shape_of(wslot)
        out = shape_of("Out")
        if w is None or out is None or len(w) < 2:
            return 0, 0
        # reduction dim: last two dims of Y, honoring transpose_Y
        k = w[-1] if op.attrs.get("transpose_Y") else w[-2]
        # PARAMs only for true parameters — attention-style matmuls
        # between activations must not count Y as weights
        wnames = op.inputs.get(wslot, [])
        wvar = vars_.get(wnames[0]) if wnames else None
        is_param = bool(wvar is not None and
                        (getattr(wvar, "is_parameter", False) or
                         wvar.persistable))
        return (numel(w) if is_param else 0), 2 * numel(out) * k
    if t in ("batch_norm", "layer_norm"):
        sc = shape_of("Scale")
        return (2 * numel(sc) if sc else 0), 0
    if t in ("lookup_table", "lookup_table_v2"):
        w = shape_of("W")
        return (numel(w) if w else 0), 0
    return 0, 0


def summary(main_prog: Program, batch_size: int = 1):
    """Per-op PARAMs/FLOPs table + totals (reference: model_stat.py:40).
    Prints the table; returns (total_params, total_flops)."""
    if not isinstance(main_prog, Program):
        raise TypeError(f"summary expects a Program, got "
                        f"{type(main_prog).__name__}")
    rows = []
    total_p = total_f = 0
    for block in main_prog.blocks:
        vars_ = block.desc.vars
        for op in block.desc.ops:
            if op.type not in _SUMMARY_OPS:
                continue
            p, f = _op_stat(op, vars_, batch_size)
            total_p += p
            total_f += f
            rows.append((op.type, p, f))
    print(f"{'No.':>4} {'TYPE':>18} {'PARAMs':>12} {'FLOPs':>14}")
    for i, (t, p, f) in enumerate(rows):
        print(f"{i:>4} {t:>18} {p:>12} {f:>14}")
    print(f"Total PARAMs: {total_p} ({total_p / 1e6:.4f}M)")
    print(f"Total FLOPs: {total_f} ({total_f / 1e9:.2f}G)")
    return total_p, total_f
