"""Transformer NMT (encoder-decoder) with beam-search decoding.

Reference capability: Transformer-big NMT is the reference's flagship NMT
benchmark (test_dist_transformer.py; beam_search_op.cc +
beam_search_decode_op.cc run decoding over LoD beams). TPU-first: static
shapes end to end — padded batches with length masks instead of LoD, and
beam search as a lax.scan over fixed max_len with a [batch, beam] state
(the reference's dynamic-LoD beam bookkeeping has no XLA equivalent;
masking + log-prob -inf freezing of finished beams reproduces the
semantics).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.sharding import shard
from .common import ParamStore, Params, dense, gelu, layer_norm


@dataclasses.dataclass
class TransformerConfig:
    src_vocab: int = 32000
    tgt_vocab: int = 32000
    hidden: int = 512
    enc_layers: int = 6
    dec_layers: int = 6
    heads: int = 8
    mlp_dim: int = 2048
    max_len: int = 256
    dropout: float = 0.1
    dtype: str = "bfloat16"
    bos_id: int = 0
    eos_id: int = 1

    @staticmethod
    def big() -> "TransformerConfig":
        return TransformerConfig(hidden=1024, heads=16, mlp_dim=4096)

    @staticmethod
    def tiny() -> "TransformerConfig":
        return TransformerConfig(src_vocab=128, tgt_vocab=128, hidden=32,
                                 enc_layers=2, dec_layers=2, heads=2,
                                 mlp_dim=64, max_len=32, dropout=0.0)

    @property
    def head_dim(self):
        return self.hidden // self.heads

    def train_flops_per_seq(self, src_T: int, tgt_T: int) -> float:
        """Training FLOPs per (src, tgt) pair: 3x forward; forward = 2*T*
        matmul params + attention quadratic terms + logits projection
        (same accounting as BertConfig.train_flops_per_seq)."""
        H, M = self.hidden, self.mlp_dim
        enc_params = self.enc_layers * (4 * H * H + 2 * H * M)
        # decoder: self-attn qkvo (4H^2) + cross-attn q/out (2H^2) + mlp run
        # over tgt_T tokens; cross-attn k/v (2H^2) run over the src_T
        # encoder outputs
        dec_tgt_params = self.dec_layers * (6 * H * H + 2 * H * M)
        dec_src_params = self.dec_layers * (2 * H * H)
        fwd = (2 * src_T * enc_params
               + self.enc_layers * 4 * src_T * src_T * H
               + 2 * tgt_T * dec_tgt_params
               + 2 * src_T * dec_src_params
               + self.dec_layers * 4 * (tgt_T * tgt_T + tgt_T * src_T) * H
               + 2 * tgt_T * H * self.tgt_vocab)
        return 3 * fwd


def init(rng: jax.Array, cfg: TransformerConfig) -> Tuple[Params, Dict]:
    s = ParamStore(rng, jnp.float32)
    s.embedding("src_emb", cfg.src_vocab, cfg.hidden, axes=("vocab", "embed"))
    s.embedding("tgt_emb", cfg.tgt_vocab, cfg.hidden, axes=("vocab", "embed"))
    s.embedding("pos", cfg.max_len, cfg.hidden, axes=(None, "embed"))

    def attn(prefix):
        s.dense(f"{prefix}.q", cfg.hidden, cfg.hidden, axes=("embed", "heads"))
        s.dense(f"{prefix}.k", cfg.hidden, cfg.hidden, axes=("embed", "heads"))
        s.dense(f"{prefix}.v", cfg.hidden, cfg.hidden, axes=("embed", "heads"))
        s.dense(f"{prefix}.o", cfg.hidden, cfg.hidden, axes=("heads", "embed"))
        s.layer_norm(f"{prefix}.ln", cfg.hidden)

    def mlp(prefix):
        s.dense(f"{prefix}.up", cfg.hidden, cfg.mlp_dim, axes=("embed", "mlp"))
        s.dense(f"{prefix}.down", cfg.mlp_dim, cfg.hidden, axes=("mlp", "embed"))
        s.layer_norm(f"{prefix}.ln", cfg.hidden)

    for i in range(cfg.enc_layers):
        attn(f"enc{i}.self")
        mlp(f"enc{i}.mlp")
    for i in range(cfg.dec_layers):
        attn(f"dec{i}.self")
        attn(f"dec{i}.cross")
        mlp(f"dec{i}.mlp")
    s.layer_norm("enc_ln", cfg.hidden)
    s.layer_norm("dec_ln", cfg.hidden)
    return s.params, s.axes


def _mha(params, prefix, q_in, kv_in, cfg, mask=None, causal=False):
    from ..ops.pallas import attention as pa

    B, Tq, H = q_in.shape
    Tk = kv_in.shape[1]
    nh, hd = cfg.heads, cfg.head_dim
    q = dense(params, f"{prefix}.q", q_in).reshape(B, Tq, nh, hd)
    k = dense(params, f"{prefix}.k", kv_in).reshape(B, Tk, nh, hd)
    v = dense(params, f"{prefix}.v", kv_in).reshape(B, Tk, nh, hd)
    ctx = pa.mha(q, k, v, mask=mask, causal=causal,
                 scale=1.0 / math.sqrt(hd))
    return dense(params, f"{prefix}.o", ctx.reshape(B, Tq, H))


def _pad_mask(lengths, T, dtype=jnp.float32):
    """[B] lengths -> additive [B,1,1,T] mask."""
    m = jnp.arange(T)[None, :] < lengths[:, None]
    return jnp.where(m, 0.0, -1e9)[:, None, None, :].astype(dtype)


def encode(params: Params, cfg: TransformerConfig, src_ids, src_len=None):
    B, T = src_ids.shape
    adt = jnp.dtype(cfg.dtype)
    x = (params["src_emb.w"][src_ids] * math.sqrt(cfg.hidden)
         + params["pos.w"][:T][None]).astype(adt)
    x = shard(x, ("batch", "seq", "embed"))
    mask = _pad_mask(src_len, T) if src_len is not None else None
    for i in range(cfg.enc_layers):
        p = f"enc{i}"
        a = _mha(params, f"{p}.self", x, x, cfg, mask=mask)
        x = layer_norm(params, f"{p}.self.ln", x + a)
        h = dense(params, f"{p}.mlp.up", x, act=gelu)
        h = dense(params, f"{p}.mlp.down", h)
        x = layer_norm(params, f"{p}.mlp.ln", x + h)
    return layer_norm(params, "enc_ln", x)


def decode(params: Params, cfg: TransformerConfig, tgt_ids, memory,
           src_len=None):
    B, T = tgt_ids.shape
    adt = jnp.dtype(cfg.dtype)
    x = (params["tgt_emb.w"][tgt_ids] * math.sqrt(cfg.hidden)
         + params["pos.w"][:T][None]).astype(adt)
    cross_mask = (_pad_mask(src_len, memory.shape[1]) if src_len is not None
                  else None)
    for i in range(cfg.dec_layers):
        p = f"dec{i}"
        a = _mha(params, f"{p}.self", x, x, cfg, causal=True)
        x = layer_norm(params, f"{p}.self.ln", x + a)
        c = _mha(params, f"{p}.cross", x, memory, cfg, mask=cross_mask)
        x = layer_norm(params, f"{p}.cross.ln", x + c)
        h = dense(params, f"{p}.mlp.up", x, act=gelu)
        h = dense(params, f"{p}.mlp.down", h)
        x = layer_norm(params, f"{p}.mlp.ln", x + h)
    x = layer_norm(params, "dec_ln", x)
    return x @ params["tgt_emb.w"].T.astype(x.dtype)


def nmt_loss(params: Params, cfg: TransformerConfig, batch, rng=None,
             label_smoothing: float = 0.1):
    """batch: src_ids [B,S], tgt_ids [B,T+1] (bos...eos), src_len, tgt_len."""
    memory = encode(params, cfg, batch["src_ids"], batch.get("src_len"))
    logits = decode(params, cfg, batch["tgt_ids"][:, :-1], memory,
                    batch.get("src_len")).astype(jnp.float32)
    targets = batch["tgt_ids"][:, 1:]
    T = targets.shape[1]
    if "tgt_len" in batch:
        valid = (jnp.arange(T)[None, :] < batch["tgt_len"][:, None] - 1)
    else:
        valid = jnp.ones(targets.shape, bool)
    logp = jax.nn.log_softmax(logits, -1)
    V = cfg.tgt_vocab
    eps = label_smoothing
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    smooth = -logp.mean(-1)
    tok_loss = (1 - eps) * nll + eps * smooth
    return (tok_loss * valid).sum() / jnp.maximum(valid.sum(), 1)


def beam_search(params: Params, cfg: TransformerConfig, src_ids,
                src_len=None, beam_size: int = 4, max_len: int = 32,
                length_penalty: float = 0.6):
    """Static-shape beam search (reference: beam_search_op.cc semantics —
    top-k expansion, finished-beam freezing, length-normalized selection).
    Returns (tokens [B, beam, max_len], scores [B, beam]). No KV cache in
    round 1 — the decoder re-runs per step inside lax.scan (O(L²) but
    MXU-friendly)."""
    B, S = src_ids.shape
    K = beam_size
    V = cfg.tgt_vocab
    memory = encode(params, cfg, src_ids, src_len)
    H = memory.shape[-1]
    mem_k = jnp.repeat(memory, K, axis=0)             # [B*K, S, H]
    src_len_k = jnp.repeat(src_len, K, axis=0) if src_len is not None else None

    tokens0 = jnp.full((B, K, max_len + 1), cfg.eos_id, jnp.int32)
    tokens0 = tokens0.at[:, :, 0].set(cfg.bos_id)
    # only beam 0 is live initially (all beams identical → dedup by -inf)
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, -1e9) \
        .astype(jnp.float32) * jnp.ones((B, K), jnp.float32)
    finished0 = jnp.zeros((B, K), bool)

    def step(state, t):
        tokens, scores, finished = state
        flat = tokens.reshape(B * K, max_len + 1)[:, :max_len]
        logits = decode(params, cfg, flat, mem_k, src_len_k)
        logits = logits.astype(jnp.float32)
        step_logits = jnp.take_along_axis(
            logits, jnp.full((B * K, 1, 1), 0, jnp.int32) + t, axis=1
        )[:, 0].reshape(B, K, V)
        logp = jax.nn.log_softmax(step_logits, -1)
        # finished beams only extend with eos at zero cost
        eos_only = jnp.full((B, K, V), -1e9).at[:, :, cfg.eos_id].set(0.0)
        logp = jnp.where(finished[..., None], eos_only, logp)
        cand = scores[..., None] + logp                   # [B, K, V]
        flat_cand = cand.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(flat_cand, K)
        beam_idx = top_idx // V
        tok_idx = top_idx % V
        new_tokens = jnp.take_along_axis(
            tokens, beam_idx[..., None], axis=1)
        new_tokens = new_tokens.at[:, :, t + 1].set(tok_idx)
        new_finished = jnp.take_along_axis(finished, beam_idx, axis=1) | \
            (tok_idx == cfg.eos_id)
        return (new_tokens, top_scores, new_finished), None

    (tokens, scores, finished), _ = jax.lax.scan(
        step, (tokens0, scores0, finished0), jnp.arange(max_len))
    # length-penalty-normalized final ranking (GNMT style)
    lengths = (tokens[:, :, 1:] != cfg.eos_id).sum(-1) + 1
    lp = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** length_penalty
    norm = scores / lp
    order = jnp.argsort(-norm, axis=1)
    tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
    norm = jnp.take_along_axis(norm, order, axis=1)
    return tokens[:, :, 1:], norm


def greedy_decode(params, cfg, src_ids, src_len=None, max_len: int = 32):
    toks, scores = beam_search(params, cfg, src_ids, src_len, beam_size=1,
                               max_len=max_len)
    return toks[:, 0]


def make_batch(rng: jax.Array, cfg: TransformerConfig, batch_size: int,
               src_T: int = 16, tgt_T: int = 16):
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    src = jax.random.randint(k1, (batch_size, src_T), 2, cfg.src_vocab)
    tgt = jax.random.randint(k2, (batch_size, tgt_T + 1), 2, cfg.tgt_vocab)
    tgt = tgt.at[:, 0].set(cfg.bos_id)
    return {
        "src_ids": src,
        "tgt_ids": tgt,
        "src_len": jax.random.randint(k3, (batch_size,), src_T // 2, src_T + 1),
        "tgt_len": jax.random.randint(k4, (batch_size,), tgt_T // 2, tgt_T + 1),
    }
