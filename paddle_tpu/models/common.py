"""Shared building blocks for the JAX-native model zoo.

Params are flat dicts {name: array}; logical sharding axes are returned
alongside as {name: (logical axes...)} consumed by
parallel.sharding.shard_params_spec. This mirrors how the reference keeps
parameters in a Scope keyed by name (framework/scope.h) rather than nested
module trees — and keeps checkpoint compatibility with the Program path
trivial (same flat names).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, jax.Array]
ParamAxes = Dict[str, Tuple[Optional[str], ...]]


class ParamStore:
    """Accumulates params + logical axes during init."""

    def __init__(self, rng: jax.Array, dtype=jnp.float32):
        self.rng = rng
        self.dtype = dtype
        self.params: Params = {}
        self.axes: ParamAxes = {}

    def next_rng(self) -> jax.Array:
        self.rng, k = jax.random.split(self.rng)
        return k

    def add(self, name: str, value: jax.Array, axes: Tuple[Optional[str], ...]):
        assert name not in self.params, f"duplicate param {name}"
        assert value.ndim == len(axes), (name, value.shape, axes)
        self.params[name] = value
        self.axes[name] = axes
        return value

    def dense(self, name: str, d_in: int, d_out: int,
              axes=("embed", "mlp"), bias: bool = True,
              init_scale: Optional[float] = None):
        scale = init_scale if init_scale is not None else math.sqrt(2.0 / (d_in + d_out))
        w = jax.random.normal(self.next_rng(), (d_in, d_out), self.dtype) * scale
        self.add(f"{name}.w", w, axes)
        if bias:
            self.add(f"{name}.b", jnp.zeros((d_out,), self.dtype), (axes[1],))

    def layer_norm(self, name: str, dim: int, axis: Optional[str] = None):
        self.add(f"{name}.scale", jnp.ones((dim,), self.dtype), (axis,))
        self.add(f"{name}.bias", jnp.zeros((dim,), self.dtype), (axis,))

    def embedding(self, name: str, vocab: int, dim: int,
                  axes=("vocab", "embed"), scale: float = 0.02):
        w = jax.random.normal(self.next_rng(), (vocab, dim), self.dtype) * scale
        self.add(f"{name}.w", w, axes)

    def conv(self, name: str, kh: int, kw: int, cin: int, cout: int,
             axes=(None, None, None, "conv_out")):
        fan_in = kh * kw * cin
        w = jax.random.normal(self.next_rng(), (kh, kw, cin, cout),
                              self.dtype) * math.sqrt(2.0 / fan_in)
        self.add(f"{name}.w", w, axes)

    def bn(self, name: str, dim: int):
        self.add(f"{name}.scale", jnp.ones((dim,), self.dtype), (None,))
        self.add(f"{name}.bias", jnp.zeros((dim,), self.dtype), (None,))
        # running stats are non-trainable state, kept in the same dict with
        # a marker prefix (filtered out of the optimizer by is_trainable)
        self.add(f"{name}.mean", jnp.zeros((dim,), jnp.float32), (None,))
        self.add(f"{name}.var", jnp.ones((dim,), jnp.float32), (None,))


def is_trainable(name: str) -> bool:
    return not (name.endswith(".mean") or name.endswith(".var"))


def dense(params: Params, name: str, x: jax.Array, act=None) -> jax.Array:
    w = params[f"{name}.w"]
    y = x @ w.astype(x.dtype)
    b = params.get(f"{name}.b")
    if b is not None:
        y = y + b.astype(y.dtype)
    if act is not None:
        y = act(y)
    return y


def raw_layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
                   eps: float = 1e-12) -> jax.Array:
    # compute in fp32 for stability under bf16 activations
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def layer_norm(params: Params, name: str, x: jax.Array, eps=1e-12) -> jax.Array:
    return raw_layer_norm(x, params[f"{name}.scale"], params[f"{name}.bias"],
                          eps)


def gelu(x):
    return jax.nn.gelu(x, approximate=True)


def dropout(rng: Optional[jax.Array], x: jax.Array, rate: float,
            deterministic: bool) -> jax.Array:
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype)


def conv2d_nhwc(x, w, stride=1, padding="SAME"):
    """NHWC conv with HWIO weights — the shared TPU-native conv layout
    (resnet/lenet carry local variants pending consolidation)."""
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def maxpool2x2_nhwc(x):
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                                 (1, 2, 2, 1), (1, 2, 2, 1), "VALID")


# -- INT8 serving path (reference capability: contrib/float16's low-
#    precision inference + mkldnn INT8 kernels; TPU-native form: int8
#    MXU convs with per-output-channel weight scales + dynamic per-
#    tensor activation scales) ---------------------------------------------


def quantize_conv_weights_int8(params: Params) -> Params:
    """Per-output-channel symmetric int8 for every 4-D HWIO conv weight
    '*.w'; adds '<k>@scale' [O] and leaves everything else untouched.
    The result feeds the same model apply(): conv helpers dispatch on
    the weight dtype."""
    out = dict(params)
    for k, v in params.items():
        if k.endswith(".w") and getattr(v, "ndim", 0) == 4:
            w = jnp.asarray(v, jnp.float32)
            amax = jnp.max(jnp.abs(w), axis=(0, 1, 2))
            scale = jnp.where(amax > 0, amax / 127.0, 1.0)
            out[k] = jnp.clip(jnp.round(w / scale), -127,
                              127).astype(jnp.int8)
            out[k + "@scale"] = scale.astype(jnp.float32)
    return out


def conv2d_nhwc_int8(x, wq, w_scale, stride=1, padding="SAME"):
    """int8 x int8 -> int32 MXU conv; activation quantized dynamically
    (per-tensor abs-max), dequantized per output channel. Returns f32."""
    xf = x.astype(jnp.float32)
    xs = jnp.maximum(jnp.max(jnp.abs(xf)), 1e-8) / 127.0
    xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
    acc = jax.lax.conv_general_dilated(
        xq, wq, (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (xs * w_scale.reshape(1, 1, 1, -1))


def conv2d_nhwc_auto(params: Params, name: str, x, stride=1,
                     padding="SAME"):
    """The dtype-dispatching conv the model zoo shares: int8 weights
    (from quantize_conv_weights_int8) take the int8 MXU path, anything
    else the plain bf16/f32 conv. Output in x.dtype either way."""
    w = params[f"{name}.w"]
    if w.dtype == jnp.int8:
        return conv2d_nhwc_int8(
            x, w, params[f"{name}.w@scale"], stride, padding
        ).astype(x.dtype)
    return conv2d_nhwc(x, w.astype(x.dtype), stride, padding)
