"""GPT (decoder-only transformer), optionally Mixture-of-Experts.

No single reference counterpart (the reference predates LLMs) but composes
reference capabilities the TPU way: stacked per-layer params scanned by
lax.scan (fast compiles), causal flash/ring attention (ops/pallas), GPipe
pipeline over 'pp' (parallel/pipeline.py — the reference's PipelineTrainer),
Switch-style top-1 MoE sharded over 'ep'. This is the model that exercises
ALL five mesh axes (dp/tp/pp/sp/ep) in __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.sharding import shard
from .common import ParamStore, Params, layer_norm as _ln_named, gelu


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 1024
    n_experts: int = 0          # 0 = dense MLP; >0 = Switch top-1 MoE
    capacity_factor: float = 1.25
    dtype: str = "bfloat16"

    @staticmethod
    def tiny(n_experts: int = 0) -> "GPTConfig":
        return GPTConfig(vocab_size=512, hidden=64, layers=4, heads=4,
                         mlp_dim=128, max_len=128, n_experts=n_experts)

    @property
    def head_dim(self):
        return self.hidden // self.heads

    def train_flops_per_token(self, seq_len: int) -> float:
        H, M, L = self.hidden, self.mlp_dim, self.layers
        # top-1 MoE routes each token through exactly one expert, so its
        # per-token matmul FLOPs equal the dense MLP (router cost omitted)
        mlp = 2 * H * M
        per_layer = 4 * H * H + mlp + 2 * seq_len * H  # qkvo + mlp + attn
        return 3 * 2 * (L * per_layer + self.vocab_size * H)


def init(rng: jax.Array, cfg: GPTConfig) -> Tuple[Params, Dict]:
    """Layer params are STACKED on a leading [L] axis (scan/pipeline)."""
    s = ParamStore(rng, jnp.float32)
    s.embedding("wte", cfg.vocab_size, cfg.hidden, axes=("vocab", "embed"))
    s.embedding("wpe", cfg.max_len, cfg.hidden, axes=(None, "embed"))

    L, H, M = cfg.layers, cfg.hidden, cfg.mlp_dim

    def stacked(key, shape, scale, axes):
        s.add(key, jax.random.normal(s.next_rng(), (L,) + shape,
                                     jnp.float32) * scale, ("layer",) + axes)

    a = math.sqrt(2.0 / (H + H))
    stacked("blk.ln1.scale", (H,), 0.0, (None,))
    s.params["blk.ln1.scale"] += 1.0
    stacked("blk.ln1.bias", (H,), 0.0, (None,))
    stacked("blk.wqkv", (H, 3 * H), a, ("embed", "heads"))
    stacked("blk.bqkv", (3 * H,), 0.0, ("heads",))
    stacked("blk.wo", (H, H), a / math.sqrt(2 * L), ("heads", "embed"))
    stacked("blk.bo", (H,), 0.0, (None,))
    stacked("blk.ln2.scale", (H,), 0.0, (None,))
    s.params["blk.ln2.scale"] += 1.0
    stacked("blk.ln2.bias", (H,), 0.0, (None,))
    am = math.sqrt(2.0 / (H + M))
    if cfg.n_experts:
        E = cfg.n_experts
        stacked("blk.router", (H, E), 0.02, ("embed", None))
        stacked("blk.w1", (E, H, M), am, ("expert", "embed", "mlp"))
        stacked("blk.w2", (E, M, H), am / math.sqrt(2 * L), ("expert", "mlp", "embed"))
    else:
        stacked("blk.w1", (H, M), am, ("embed", "mlp"))
        stacked("blk.b1", (M,), 0.0, ("mlp",))
        stacked("blk.w2", (M, H), am / math.sqrt(2 * L), ("mlp", "embed"))
        stacked("blk.b2", (H,), 0.0, (None,))
    s.layer_norm("ln_f", H)
    return s.params, s.axes


def _ln(x, scale, bias, eps=1e-5):
    from .common import raw_layer_norm

    return raw_layer_norm(x, scale, bias, eps)


def _attention(lp, x, cfg: GPTConfig, mesh=None):
    B, T, H = x.shape
    nh, hd = cfg.heads, cfg.head_dim
    qkv = x @ lp["blk.wqkv"].astype(x.dtype) + lp["blk.bqkv"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, nh, hd)
    k = k.reshape(B, T, nh, hd)
    v = v.reshape(B, T, nh, hd)
    from ..parallel.mesh import current_mesh
    from ..ops.pallas import attention as pa
    from ..ops.pallas import ring_attention as ra

    from ..parallel.sharding import in_manual_region

    mesh = mesh or current_mesh()
    # explicit ring attention over 'sp' — except inside an already-manual
    # region (the 'pp' pipeline): XLA cannot nest manual subregions, so
    # there GSPMD shards the sequence from the shard() constraints instead
    if mesh is not None and mesh.shape.get("sp", 1) > 1 \
            and not in_manual_region():
        ctx = ra.ring_attention(q, k, v, mesh, axis="sp", causal=True)
    else:
        ctx = pa.mha(q, k, v, causal=True, scale=1.0 / math.sqrt(hd))
    ctx = ctx.reshape(B, T, H)
    return ctx @ lp["blk.wo"].astype(x.dtype) + lp["blk.bo"].astype(x.dtype)


def _moe_mlp(lp, x, cfg: GPTConfig):
    """Switch-style top-1 routing with capacity (dispatch/combine einsums);
    expert weights sharded over 'ep'."""
    B, T, H = x.shape
    G = B * T
    E = cfg.n_experts
    C = max(1, int(cfg.capacity_factor * G / E))
    xt = x.reshape(G, H)
    logits = (xt @ lp["blk.router"].astype(x.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, -1)
    gate, idx = probs.max(-1), probs.argmax(-1)           # [G]
    eo = jax.nn.one_hot(idx, E, dtype=jnp.float32)        # [G, E]
    pos = (jnp.cumsum(eo, axis=0) - 1.0) * eo             # position in expert
    within = (pos < C) * eo                               # keep under capacity
    po = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), C,
                        dtype=jnp.float32) * within.sum(-1, keepdims=True)
    dispatch = jnp.einsum("ge,gc->gec", within, po)       # [G, E, C]
    combine = dispatch * gate[:, None, None]
    ein = jnp.einsum("gec,gh->ech", dispatch.astype(x.dtype), xt)
    ein = shard(ein, ("expert", None, "embed"))
    h = gelu(jnp.einsum("ech,ehm->ecm", ein, lp["blk.w1"].astype(x.dtype)))
    h = shard(h, ("expert", None, "mlp"))
    out = jnp.einsum("ecm,emh->ech", h, lp["blk.w2"].astype(x.dtype))
    y = jnp.einsum("gec,ech->gh", combine.astype(x.dtype), out)
    return y.reshape(B, T, H)


def _block(lp, x, cfg: GPTConfig, mesh=None):
    """One transformer block with this layer's (unstacked) params."""
    h = _ln(x, lp["blk.ln1.scale"], lp["blk.ln1.bias"])
    x = x + _attention(lp, h, cfg, mesh)
    x = shard(x, ("batch", "seq", "embed"))
    h = _ln(x, lp["blk.ln2.scale"], lp["blk.ln2.bias"])
    if cfg.n_experts:
        x = x + _moe_mlp(lp, h, cfg)
    else:
        h = gelu(h @ lp["blk.w1"].astype(x.dtype) + lp["blk.b1"].astype(x.dtype))
        h = shard(h, ("batch", "seq", "mlp"))
        x = x + (h @ lp["blk.w2"].astype(x.dtype) + lp["blk.b2"].astype(x.dtype))
    return shard(x, ("batch", "seq", "embed"))


def _layer_params(params: Params):
    return {k: v for k, v in params.items() if k.startswith("blk.")}


def apply(params: Params, cfg: GPTConfig, ids: jax.Array,
          n_microbatches: int = 0) -> jax.Array:
    """ids [B, T] -> logits [B, T, vocab].

    n_microbatches > 0 runs the block stack through the GPipe pipeline over
    the 'pp' mesh axis (parallel/pipeline.py); 0 = lax.scan over layers.
    """
    from ..parallel.mesh import current_mesh

    B, T = ids.shape
    adt = jnp.dtype(cfg.dtype)
    x = (params["wte.w"][ids] + params["wpe.w"][:T][None]).astype(adt)
    x = shard(x, ("batch", "seq", "embed"))
    lp_stacked = _layer_params(params)
    mesh = current_mesh()

    if n_microbatches and mesh is not None and mesh.shape.get("pp", 1) > 1:
        from ..parallel.pipeline import pipeline_apply

        S = mesh.shape["pp"]
        L = cfg.layers
        assert L % S == 0, f"layers {L} not divisible by pp {S}"
        # restack [L, ...] -> [S, L//S, ...]
        sp = jax.tree.map(
            lambda p: p.reshape((S, L // S) + p.shape[1:]), lp_stacked)
        assert B % n_microbatches == 0
        xm = x.reshape((n_microbatches, B // n_microbatches) + x.shape[1:])

        def stage_fn(stage_lp, xmb):
            def layer_body(h, lp):
                return _block(lp, h, cfg, mesh), None
            h, _ = jax.lax.scan(layer_body, xmb, stage_lp)
            return h

        x = pipeline_apply(stage_fn, sp, xm, mesh)
        x = x.reshape((B,) + x.shape[2:])
    else:
        def layer_body(h, lp):
            return _block(lp, h, cfg, mesh), None

        x, _ = jax.lax.scan(layer_body, x, lp_stacked)

    x = _ln_named(params, "ln_f", x)
    logits = x @ params["wte.w"].T.astype(x.dtype)
    return shard(logits, ("batch", "seq", "vocab"))


# ---------------------------------------------------------------------------
# Decode path (serving/decode.py): paged-KV prefill + single-token steps.
#
# `apply` above recomputes the full [B, T] forward per call — fine for
# training/scoring, quadratic waste for token-by-token generation. The
# decode path splits generation into the two serving phases:
#
#   apply_prefill      one prompt ([1, T_bucket]) through full causal
#                      attention, writing every position's K/V into the
#                      sequence's pool blocks and sampling the first
#                      new token from the last real position;
#   apply_decode_step  one token per resident sequence ([S] slots),
#                      position-indexed attention over each sequence's
#                      own blocks via its block table — the executable
#                      every generated token after the first rides.
#
# Both take and return the pool arrays (donated at the jit boundary by
# the engine) and sample through ops/beam.beam_search with beam_size=1:
# greedy selection with the beam op's finished-freeze semantics, so a
# slot whose previous token is end_id keeps emitting end_id without any
# host-side branching. MoE configs are refused by the engine (expert
# dispatch needs its own decode kernel — ROADMAP item 4).
# ---------------------------------------------------------------------------


def _beam_top1(prev_ids: jax.Array, logits: jax.Array,
               eos_id: int) -> jax.Array:
    """Greedy next-token selection through the beam_search op (K=1).
    prev_ids [S] int32, logits [S, vocab] → [S] int32."""
    from ..ops.beam import beam_search

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    out = beam_search(
        {"pre_ids": [prev_ids[:, None].astype(jnp.int32)],
         "pre_scores": [jnp.zeros((logp.shape[0], 1), jnp.float32)],
         "scores": [logp[:, None, :]]},
        {"beam_size": 1, "end_id": int(eos_id), "is_accumulated": True},
        None)
    return out["selected_ids"][:, 0].astype(jnp.int32)


def _decode_mlp(lp, x):
    h = gelu(x @ lp["blk.w1"].astype(x.dtype) + lp["blk.b1"].astype(x.dtype))
    return h @ lp["blk.w2"].astype(x.dtype) + lp["blk.b2"].astype(x.dtype)


def apply_prefill(params: Params, cfg: GPTConfig, ids: jax.Array,
                  length: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                  block_table: jax.Array, *, block_size: int,
                  eos_id: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One prompt through the stack, filling its KV blocks.

    ids [1, T] (edge-padded to the prefill bucket T), length = true
    prompt length, block_table [MB] (the sequence's row). Returns
    (first sampled token [1], k_pool, v_pool). Padded tail positions
    write to the null block / soon-overwritten slots (see
    kv_cache.write_prefill_kv) and, being causally AFTER every real
    position, never contribute to the last real position's logits.
    """
    from ..ops.pallas import attention as pa
    from ..serving import kv_cache as kvc

    B, T = ids.shape
    nh, hd = cfg.heads, cfg.head_dim
    adt = k_pool.dtype
    x = (params["wte.w"][ids] + params["wpe.w"][:T][None]).astype(adt)

    lp_stacked = _layer_params(params)

    def layer_body(h, per_layer):
        lp, kp, vp = per_layer
        y = _ln(h, lp["blk.ln1.scale"], lp["blk.ln1.bias"])
        qkv = y @ lp["blk.wqkv"].astype(y.dtype) + \
            lp["blk.bqkv"].astype(y.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, nh, hd)
        k = k.reshape(B, T, nh, hd)
        v = v.reshape(B, T, nh, hd)
        kp = kvc.write_prefill_kv(kp, k[0], block_table, block_size)
        vp = kvc.write_prefill_kv(vp, v[0], block_table, block_size)
        ctx = pa.mha(q, k, v, causal=True, scale=1.0 / math.sqrt(hd))
        ctx = ctx.reshape(B, T, cfg.hidden)
        h = h + ctx @ lp["blk.wo"].astype(h.dtype) + \
            lp["blk.bo"].astype(h.dtype)
        y = _ln(h, lp["blk.ln2.scale"], lp["blk.ln2.bias"])
        h = h + _decode_mlp(lp, y)
        return h, (kp, vp)

    x, (k_pool, v_pool) = jax.lax.scan(
        layer_body, x, (lp_stacked, k_pool, v_pool))
    x = _ln_named(params, "ln_f", x)
    last = jnp.maximum(length, 1) - 1
    x_last = x[0, last]                                   # [H]
    logits = (x_last @ params["wte.w"].T.astype(x.dtype))[None]
    prev = ids[0, last][None].astype(jnp.int32)
    tok = _beam_top1(prev, logits, eos_id)
    return tok, k_pool, v_pool


def apply_decode_step(params: Params, cfg: GPTConfig, ids: jax.Array,
                      positions: jax.Array, k_pool: jax.Array,
                      v_pool: jax.Array, block_tables: jax.Array, *,
                      block_size: int, eos_id: int
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for S resident slots.

    ids [S] (each slot's previous token), positions [S] (where this
    token's K/V lands = current sequence length), block_tables [S, MB].
    Every row's math touches only that row's activations and its own
    blocks, so a slot's tokens are bit-identical whatever else shares
    the batch — the property test_decode's admit-mid-decode test pins.
    Returns (next tokens [S], k_pool, v_pool)."""
    from ..serving import kv_cache as kvc

    S = ids.shape[0]
    nh, hd = cfg.heads, cfg.head_dim
    adt = k_pool.dtype
    x = (params["wte.w"][ids] + params["wpe.w"][positions]).astype(adt)

    lp_stacked = _layer_params(params)
    scale = 1.0 / math.sqrt(hd)

    def layer_body(h, per_layer):
        lp, kp, vp = per_layer
        y = _ln(h, lp["blk.ln1.scale"], lp["blk.ln1.bias"])
        qkv = y @ lp["blk.wqkv"].astype(y.dtype) + \
            lp["blk.bqkv"].astype(y.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, nh, hd)
        k = k.reshape(S, nh, hd)
        v = v.reshape(S, nh, hd)
        kp = kvc.write_token_kv(kp, k, block_tables, positions, block_size)
        vp = kvc.write_token_kv(vp, v, block_tables, positions, block_size)
        keys = kvc.gather_kv(kp, block_tables)        # [S, M, nh, hd]
        vals = kvc.gather_kv(vp, block_tables)
        scores = jnp.einsum("snd,smnd->snm", q, keys) * scale
        m = keys.shape[1]
        mask = jnp.arange(m, dtype=jnp.int32)[None, :] <= positions[:, None]
        scores = jnp.where(mask[:, None, :], scores, -1e9)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        ctx = jnp.einsum("snm,smnd->snd", att.astype(adt), vals)
        ctx = ctx.reshape(S, cfg.hidden)
        h = h + ctx @ lp["blk.wo"].astype(h.dtype) + \
            lp["blk.bo"].astype(h.dtype)
        y = _ln(h, lp["blk.ln2.scale"], lp["blk.ln2.bias"])
        h = h + _decode_mlp(lp, y)
        return h, (kp, vp)

    x, (k_pool, v_pool) = jax.lax.scan(
        layer_body, x, (lp_stacked, k_pool, v_pool))
    x = _ln_named(params, "ln_f", x)
    logits = x @ params["wte.w"].T.astype(x.dtype)         # [S, vocab]
    tok = _beam_top1(ids.astype(jnp.int32), logits, eos_id)
    return tok, k_pool, v_pool


def apply_prefill_chunk(params: Params, cfg: GPTConfig, ids: jax.Array,
                        start: jax.Array, length: jax.Array,
                        k_pool: jax.Array, v_pool: jax.Array,
                        block_table: jax.Array, *, block_size: int,
                        eos_id: int
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fixed-size SLICE of a prompt through the stack (chunked
    prefill — serving/kv_reuse.py).

    ids [1, C] = the tokens at positions start..start+C-1 (edge-padded
    past `length`), start = the slice's first position, length = the
    true prompt length. Writes the slice's K/V into the sequence's
    blocks and attends gather-style over the block table with mask
    `key_pos <= start + i`, so earlier slices' — and prefix-cache
    reused blocks' — K/V participate exactly as in a whole-prompt
    prefill. Per-position results are independent of where the chunk
    boundaries fall (each row's math reads only pool state + its own
    activations), which is what makes chunked == whole prefill and
    reused == recomputed prefixes hold at the token level. Returns
    (tok [1], k_pool, v_pool); tok is meaningful only on the slice
    containing position length-1 (the scheduler ignores it earlier).
    """
    from ..serving import kv_cache as kvc

    _, C = ids.shape
    nh, hd = cfg.heads, cfg.head_dim
    adt = k_pool.dtype
    pos = start + jnp.arange(C, dtype=jnp.int32)
    # the final slice's padded tail can run past the positional table;
    # clamp (those rows' outputs are never consumed, their KV lands in
    # the null block / overwritten slots)
    x = (params["wte.w"][ids[0]] +
         params["wpe.w"][jnp.minimum(pos, cfg.max_len - 1)]).astype(adt)

    lp_stacked = _layer_params(params)
    scale = 1.0 / math.sqrt(hd)

    def layer_body(h, per_layer):
        lp, kp, vp = per_layer
        y = _ln(h, lp["blk.ln1.scale"], lp["blk.ln1.bias"])
        qkv = y @ lp["blk.wqkv"].astype(y.dtype) + \
            lp["blk.bqkv"].astype(y.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(C, nh, hd)
        k = k.reshape(C, nh, hd)
        v = v.reshape(C, nh, hd)
        kp = kvc.write_chunk_kv(kp, k, block_table, start, block_size)
        vp = kvc.write_chunk_kv(vp, v, block_table, start, block_size)
        keys = kvc.gather_kv(kp, block_table[None])[0]  # [M, nh, hd]
        vals = kvc.gather_kv(vp, block_table[None])[0]
        scores = jnp.einsum("cnd,mnd->cnm", q, keys) * scale
        m = keys.shape[0]
        mask = jnp.arange(m, dtype=jnp.int32)[None, :] <= pos[:, None]
        scores = jnp.where(mask[:, None, :], scores, -1e9)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        ctx = jnp.einsum("cnm,mnd->cnd", att.astype(adt), vals)
        ctx = ctx.reshape(C, cfg.hidden)
        h = h + ctx @ lp["blk.wo"].astype(h.dtype) + \
            lp["blk.bo"].astype(h.dtype)
        y = _ln(h, lp["blk.ln2.scale"], lp["blk.ln2.bias"])
        h = h + _decode_mlp(lp, y)
        return h, (kp, vp)

    x, (k_pool, v_pool) = jax.lax.scan(
        layer_body, x, (lp_stacked, k_pool, v_pool))
    x = _ln_named(params, "ln_f", x)
    last = jnp.clip(length - 1 - start, 0, C - 1)
    logits = (x[last] @ params["wte.w"].T.astype(x.dtype))[None]
    prev = ids[0, last][None].astype(jnp.int32)
    tok = _beam_top1(prev, logits, eos_id)
    return tok, k_pool, v_pool


def apply_verify_step(params: Params, cfg: GPTConfig, ids: jax.Array,
                      positions: jax.Array, k_pool: jax.Array,
                      v_pool: jax.Array, block_tables: jax.Array, *,
                      block_size: int, eos_id: int
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative verification: W = k+1 tokens per slot in ONE step
    (serving/kv_reuse.py).

    ids [S, W] = each slot's [last_token, d_1..d_k] (the previous real
    token followed by the draft model's k proposals), positions [S] =
    each slot's next KV write position. Row j writes its K/V at
    position positions+j and attends `key_pos <= positions + j`, so
    output j is bit-identical to the token a plain apply_decode_step
    sequence would produce after feeding ids[:, :j+1] one at a time —
    the exact greedy accept/reject in kv_reuse.accept_length compares
    drafts against these outputs. Rejected positions' K/V stays in the
    pool but is overwritten by the next real write before any mask
    lets it be read (the standard paged-decode invariant). Sampling
    routes through the same beam_search op as decode, so an eos in the
    fed window freezes the remaining outputs to eos. Returns
    (tokens [S, W], k_pool, v_pool)."""
    from ..serving import kv_cache as kvc

    S, W = ids.shape
    nh, hd = cfg.heads, cfg.head_dim
    adt = k_pool.dtype
    pos = positions[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    x = (params["wte.w"][ids] +
         params["wpe.w"][jnp.minimum(pos, cfg.max_len - 1)]).astype(adt)

    lp_stacked = _layer_params(params)
    scale = 1.0 / math.sqrt(hd)

    def layer_body(h, per_layer):
        lp, kp, vp = per_layer
        y = _ln(h, lp["blk.ln1.scale"], lp["blk.ln1.bias"])
        qkv = y @ lp["blk.wqkv"].astype(y.dtype) + \
            lp["blk.bqkv"].astype(y.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, W, nh, hd)
        k = k.reshape(S, W, nh, hd)
        v = v.reshape(S, W, nh, hd)
        kp = kvc.write_span_kv(kp, k, block_tables, positions,
                               block_size)
        vp = kvc.write_span_kv(vp, v, block_tables, positions,
                               block_size)
        keys = kvc.gather_kv(kp, block_tables)        # [S, M, nh, hd]
        vals = kvc.gather_kv(vp, block_tables)
        scores = jnp.einsum("swnd,smnd->swnm", q, keys) * scale
        m = keys.shape[1]
        mask = jnp.arange(m, dtype=jnp.int32)[None, None, :] \
            <= pos[:, :, None]
        scores = jnp.where(mask[:, :, None, :], scores, -1e9)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        ctx = jnp.einsum("swnm,smnd->swnd", att.astype(adt), vals)
        ctx = ctx.reshape(S, W, cfg.hidden)
        h = h + ctx @ lp["blk.wo"].astype(h.dtype) + \
            lp["blk.bo"].astype(h.dtype)
        y = _ln(h, lp["blk.ln2.scale"], lp["blk.ln2.bias"])
        h = h + _decode_mlp(lp, y)
        return h, (kp, vp)

    x, (k_pool, v_pool) = jax.lax.scan(
        layer_body, x, (lp_stacked, k_pool, v_pool))
    x = _ln_named(params, "ln_f", x)
    logits = x @ params["wte.w"].T.astype(x.dtype)     # [S, W, vocab]
    tok = _beam_top1(ids.reshape(S * W).astype(jnp.int32),
                     logits.reshape(S * W, -1), eos_id).reshape(S, W)
    return tok, k_pool, v_pool


def lm_loss(params: Params, cfg: GPTConfig, batch: Dict[str, jax.Array],
            rng=None, n_microbatches: int = 0) -> jax.Array:
    """Next-token cross entropy; batch = {"ids": [B, T+1]}."""
    ids = batch["ids"]
    logits = apply(params, cfg, ids[:, :-1], n_microbatches).astype(jnp.float32)
    targets = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, -1)
    ll = jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    return -ll.mean()


def make_batch(rng: jax.Array, cfg: GPTConfig, batch_size: int,
               seq_len: Optional[int] = None):
    T = seq_len or cfg.max_len
    return {"ids": jax.random.randint(rng, (batch_size, T + 1), 0,
                                      cfg.vocab_size)}
