"""VGG-16 — the reference's headline float16 inference benchmark model
(paddle/contrib/float16/float16_benchmark.md: VGG16 ImageNet fp16 mb=1
3.32 ms, mb=64 60.23 ms on V100; float16_inference_demo.py builds the
net). TPU-first: NHWC convs in bf16, biases folded into the conv
epilogue, fc head in bf16 with f32 logits.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .common import (ParamStore, Params, conv2d_nhwc_auto, dense,
                     maxpool2x2_nhwc)

# channels per conv block (VGG-16: 2-2-3-3-3 convs)
BLOCKS = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]


@dataclasses.dataclass
class VGGConfig:
    n_classes: int = 1000
    dtype: str = "bfloat16"
    width_mult: float = 1.0     # channel scale (tiny testing configs)
    image_hw: int = 224         # fc1's fan-in is fixed by the input size

    @staticmethod
    def vgg16():
        return VGGConfig()

    @staticmethod
    def tiny():
        return VGGConfig(n_classes=10, width_mult=0.125, image_hw=32)

    def channels(self, c):
        return max(8, int(c * self.width_mult))


def init(rng: jax.Array, cfg: VGGConfig) -> Tuple[Params, Dict]:
    s = ParamStore(rng)
    cin = 3
    for bi, (n_convs, cout) in enumerate(BLOCKS):
        cout = cfg.channels(cout)
        for ci in range(n_convs):
            s.conv(f"b{bi}.c{ci}", 3, 3, cin, cout)
            s.add(f"b{bi}.c{ci}.b", jnp.zeros((cout,), jnp.float32),
                  (None,))
            cin = cout
    feat_hw = cfg.image_hw // 32        # 5 stride-2 pools
    fc_dim = max(64, int(4096 * cfg.width_mult))
    s.dense("fc1", cin * feat_hw * feat_hw, fc_dim,
            axes=("embed", "mlp"))
    s.dense("fc2", fc_dim, fc_dim, axes=("mlp", "mlp"))
    s.dense("head", fc_dim, cfg.n_classes, axes=("mlp", "vocab"))
    return s.params, s.axes


def apply(params: Params, cfg: VGGConfig, img: jax.Array) -> jax.Array:
    """img [B, 3, cfg.image_hw, cfg.image_hw] (reference NCHW interface)
    -> logits [B, C]. The input size is fixed by fc1's fan-in."""
    assert img.shape[2] == img.shape[3] == cfg.image_hw, (
        f"VGG built for {cfg.image_hw}x{cfg.image_hw} inputs, got "
        f"{img.shape[2]}x{img.shape[3]} (fc1 fan-in is size-bound)")
    adt = jnp.dtype(cfg.dtype)
    x = img.transpose(0, 2, 3, 1).astype(adt)     # NHWC
    for bi, (n_convs, _) in enumerate(BLOCKS):
        for ci in range(n_convs):
            x = conv2d_nhwc_auto(params, f"b{bi}.c{ci}", x)
            x = jax.nn.relu(x + params[f"b{bi}.c{ci}.b"].astype(adt))
        x = maxpool2x2_nhwc(x)
    b = x.shape[0]
    x = x.reshape(b, -1)
    x = jax.nn.relu(dense(params, "fc1", x))
    x = jax.nn.relu(dense(params, "fc2", x))
    return dense(params, "head", x.astype(jnp.float32))
