"""LeNet / MNIST — the minimum end-to-end slice (BASELINE.json config 1;
reference: python/paddle/fluid/tests/book/test_recognize_digits.py).

Provides BOTH API levels: `build_program` constructs the fluid-style static
graph (exercising the Program IR path end-to-end), and init/apply give the
JAX-native path.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .common import ParamStore, Params, dense


def build_program(pt, img_shape=(1, 28, 28), n_classes=10, lr=0.01):
    """Static-graph LeNet (conv_pool x2 + fc ladder) via paddle_tpu.layers.
    Returns (main, startup, feeds, loss, acc)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = pt.layers.data(name="img", shape=list(img_shape), dtype="float32")
        label = pt.layers.data(name="label", shape=[1], dtype="int64")
        c1 = pt.layers.conv2d(input=img, num_filters=20, filter_size=5, act="relu")
        p1 = pt.layers.pool2d(input=c1, pool_size=2, pool_stride=2, pool_type="max")
        c2 = pt.layers.conv2d(input=p1, num_filters=50, filter_size=5, act="relu")
        p2 = pt.layers.pool2d(input=c2, pool_size=2, pool_stride=2, pool_type="max")
        fc1 = pt.layers.fc(input=p2, size=500, act="relu")
        logits = pt.layers.fc(input=fc1, size=n_classes)
        loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        acc = pt.layers.accuracy(input=pt.layers.softmax(logits), label=label)
        pt.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, ("img", "label"), loss, acc


def init(rng: jax.Array, n_classes: int = 10) -> Tuple[Params, Dict]:
    s = ParamStore(rng)
    s.conv("conv1", 5, 5, 1, 20)
    s.conv("conv2", 5, 5, 20, 50)
    s.dense("fc1", 4 * 4 * 50, 500)
    s.dense("fc2", 500, n_classes, axes=("embed", None))
    return s.params, s.axes


def apply(params: Params, img: jax.Array) -> jax.Array:
    """img: [B, 1, 28, 28] -> logits [B, 10]."""
    x = img.transpose(0, 2, 3, 1)  # NHWC for TPU conv
    for name in ("conv1", "conv2"):
        x = jax.lax.conv_general_dilated(
            x, params[f"{name}.w"], (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x)
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 2, 2, 1),
                                  (1, 2, 2, 1), "VALID")
    x = x.reshape(x.shape[0], -1)
    x = dense(params, "fc1", x, act=jax.nn.relu)
    return dense(params, "fc2", x)


def loss_fn(params: Params, batch, rng=None) -> jax.Array:
    logits = apply(params, batch["img"]).astype(jnp.float32)
    labels = batch["label"].reshape(-1)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, labels[:, None], 1).mean()
