"""BERT (encoder-only transformer) — the flagship benchmark model.

Reference capability: BERT-base served/trained by the reference via its
op zoo (matmul/softmax/layer_norm + Adam; inference/tests/api/
analyzer_bert_tester.cc exercises the graph). Rebuilt TPU-first:

- bf16 activations, fp32 params/LN stats → MXU-friendly
- attention as one fused einsum chain; Pallas flash-attention kernel is used
  when available (ops/pallas), falling back to the XLA softmax path
- logical sharding axes: batch→dp, seq→sp, heads/mlp/vocab→tp — megatron TP
  + sequence parallelism come from the rule table, no model change
  (parallel/sharding.py)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.sharding import shard
from .common import (ParamStore, Params, dense, dropout, gelu, layer_norm)


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    dropout: float = 0.1
    dtype: str = "bfloat16"  # activation dtype

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny() -> "BertConfig":
        return BertConfig(vocab_size=1024, hidden=64, layers=2, heads=4,
                          mlp_dim=128, max_len=64, dropout=0.0)

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    def flops_per_token(self) -> float:
        """Training FLOPs/token with dense-MLM (legacy MFU accounting)."""
        return self.train_flops_per_seq(self.max_len, self.max_len) / self.max_len

    def train_flops_per_seq(self, seq_len: int, n_masked: int) -> float:
        """Training FLOPs per sequence: 3x forward; forward = 2*T*matmul
        params + attention quadratic term + masked-only vocab projection."""
        H, M, L = self.hidden, self.mlp_dim, self.layers
        matmul_params = L * (4 * H * H + 2 * H * M) + 2 * H * H  # + mlm/pooler
        fwd = (2 * seq_len * matmul_params
               + L * 4 * seq_len * seq_len * H
               + 2 * n_masked * self.vocab_size * H)
        return 3 * fwd


def init(rng: jax.Array, cfg: BertConfig) -> Tuple[Params, Dict]:
    s = ParamStore(rng, jnp.float32)
    s.embedding("embeddings.word", cfg.vocab_size, cfg.hidden,
                axes=("vocab", "embed"))
    s.embedding("embeddings.position", cfg.max_len, cfg.hidden,
                axes=(None, "embed"))
    s.embedding("embeddings.type", cfg.type_vocab, cfg.hidden,
                axes=(None, "embed"))
    s.layer_norm("embeddings.ln", cfg.hidden)
    for i in range(cfg.layers):
        p = f"layer{i}"
        s.dense(f"{p}.attn.q", cfg.hidden, cfg.hidden, axes=("embed", "heads"))
        s.dense(f"{p}.attn.k", cfg.hidden, cfg.hidden, axes=("embed", "heads"))
        s.dense(f"{p}.attn.v", cfg.hidden, cfg.hidden, axes=("embed", "heads"))
        s.dense(f"{p}.attn.o", cfg.hidden, cfg.hidden, axes=("heads", "embed"))
        s.layer_norm(f"{p}.attn.ln", cfg.hidden)
        s.dense(f"{p}.mlp.up", cfg.hidden, cfg.mlp_dim, axes=("embed", "mlp"))
        s.dense(f"{p}.mlp.down", cfg.mlp_dim, cfg.hidden, axes=("mlp", "embed"))
        s.layer_norm(f"{p}.mlp.ln", cfg.hidden)
    s.dense("pooler", cfg.hidden, cfg.hidden, axes=("embed", "embed"))
    # MLM head: transform + tied-embedding output bias
    s.dense("mlm.transform", cfg.hidden, cfg.hidden, axes=("embed", "embed"))
    s.layer_norm("mlm.ln", cfg.hidden)
    s.add("mlm.bias", jnp.zeros((cfg.vocab_size,), jnp.float32), ("vocab",))
    s.dense("nsp", cfg.hidden, 2, axes=("embed", None))
    return s.params, s.axes


def _attention(params: Params, prefix: str, x: jax.Array, mask: jax.Array,
               cfg: BertConfig, rng, deterministic: bool) -> jax.Array:
    B, T, H = x.shape
    nh, hd = cfg.heads, cfg.head_dim
    q = dense(params, f"{prefix}.q", x).reshape(B, T, nh, hd)
    k = dense(params, f"{prefix}.k", x).reshape(B, T, nh, hd)
    v = dense(params, f"{prefix}.v", x).reshape(B, T, nh, hd)
    q = shard(q, ("batch", "seq", "heads", None))
    k = shard(k, ("batch", "seq", "heads", None))
    v = shard(v, ("batch", "seq", "heads", None))

    from ..ops.pallas import attention as pallas_attention

    ctx = pallas_attention.mha(q, k, v, mask=mask, scale=1.0 / math.sqrt(hd))
    ctx = ctx.reshape(B, T, H)
    out = dense(params, f"{prefix}.o", ctx)
    return dropout(rng, out, cfg.dropout, deterministic)


def encode(params: Params, cfg: BertConfig, input_ids: jax.Array,
           token_type_ids: Optional[jax.Array] = None,
           attention_mask: Optional[jax.Array] = None,
           rng: Optional[jax.Array] = None,
           deterministic: bool = True) -> jax.Array:
    """Returns [B, T, H] sequence output (activations in cfg.dtype)."""
    B, T = input_ids.shape
    adt = jnp.dtype(cfg.dtype)
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)

    emb = (params["embeddings.word.w"][input_ids]
           + params["embeddings.position.w"][:T][None, :, :]
           + params["embeddings.type.w"][token_type_ids])
    x = layer_norm(params, "embeddings.ln", emb).astype(adt)
    x = shard(x, ("batch", "seq", "embed"))
    rngs = (jax.random.split(rng, cfg.layers * 2)
            if rng is not None else [None] * (cfg.layers * 2))
    # additive mask [B, 1, 1, T]; None = padding-free (no mask buffer at all,
    # which keeps the flash-attention path O(T) in memory)
    if attention_mask is None:
        amask = None
    else:
        neg = jnp.asarray(-1e9 if adt == jnp.float32 else -3e4, jnp.float32)
        amask = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)

    for i in range(cfg.layers):
        p = f"layer{i}"
        a = _attention(params, f"{p}.attn", x, amask, cfg, rngs[2 * i],
                       deterministic)
        x = layer_norm(params, f"{p}.attn.ln", x + a)
        x = shard(x, ("batch", "seq", "embed"))
        h = dense(params, f"{p}.mlp.up", x, act=gelu)
        h = shard(h, ("batch", "seq", "mlp"))
        h = dense(params, f"{p}.mlp.down", h)
        h = dropout(rngs[2 * i + 1], h, cfg.dropout, deterministic)
        x = layer_norm(params, f"{p}.mlp.ln", x + h)
        x = shard(x, ("batch", "seq", "embed"))
    return x


def mlm_logits(params: Params, cfg: BertConfig, seq_out: jax.Array) -> jax.Array:
    h = dense(params, "mlm.transform", seq_out, act=gelu)
    h = layer_norm(params, "mlm.ln", h)
    logits = h @ params["embeddings.word.w"].T.astype(h.dtype)
    logits = logits + params["mlm.bias"].astype(h.dtype)
    return shard(logits, ("batch", "seq", "vocab"))


def pretrain_loss(params: Params, cfg: BertConfig, batch: Dict[str, jax.Array],
                  rng: Optional[jax.Array] = None,
                  deterministic: bool = False) -> jax.Array:
    """Masked-LM + next-sentence loss (the BERT-base pretrain objective).

    Two MLM batch formats:
    - gathered (preferred, what BERT's max_predictions_per_seq does):
      "masked_positions" [B, P] + "masked_labels" [B, P] (-100 = pad slot) —
      only P positions hit the vocab projection.
    - dense: "mlm_labels" [B, T] with -100 for unmasked positions.
    """
    seq = encode(params, cfg, batch["input_ids"],
                 batch.get("token_type_ids"), batch.get("attention_mask"),
                 rng=rng, deterministic=deterministic)
    if "masked_positions" in batch:
        pos = batch["masked_positions"]  # [B, P]
        labels = batch["masked_labels"]
        gathered = jnp.take_along_axis(
            seq, pos[..., None].astype(jnp.int32), axis=1)  # [B, P, H]
        logits = mlm_logits(params, cfg, gathered).astype(jnp.float32)
    else:
        labels = batch["mlm_labels"]  # [B, T], -100 = unmasked
        logits = mlm_logits(params, cfg, seq).astype(jnp.float32)
    valid = labels >= 0
    lab = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tok_ll = jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
    mlm = -(tok_ll * valid).sum() / jnp.maximum(valid.sum(), 1)

    if "nsp_labels" in batch:
        cls = jnp.tanh(dense(params, "pooler", seq[:, 0]).astype(jnp.float32))
        nsp_logits = dense(params, "nsp", cls.astype(seq.dtype)).astype(jnp.float32)
        nsp_lp = jax.nn.log_softmax(nsp_logits, axis=-1)
        nsp = -jnp.take_along_axis(nsp_lp, batch["nsp_labels"][:, None], 1).mean()
        return mlm + nsp
    return mlm


def make_batch(rng: jax.Array, cfg: BertConfig, batch_size: int,
               seq_len: Optional[int] = None,
               max_predictions: Optional[int] = None) -> Dict[str, jax.Array]:
    """Synthetic pretraining batch in the gathered format (benchmark input).
    max_predictions defaults to ceil(0.15 * T) like BERT's
    max_predictions_per_seq."""
    T = seq_len or cfg.max_len
    P = max_predictions or max(1, int(0.15 * T) + 1)
    k1, k2, k3 = jax.random.split(rng, 3)
    ids = jax.random.randint(k1, (batch_size, T), 0, cfg.vocab_size)
    # first P positions of a random permutation are masked
    perm = jax.vmap(lambda k: jax.random.permutation(k, T))(
        jax.random.split(k2, batch_size))
    pos = jnp.sort(perm[:, :P], axis=-1)
    labels = jnp.take_along_axis(ids, pos, axis=1)
    masked_ids = jax.vmap(lambda row, p: row.at[p].set(103))(ids, pos)
    # no attention_mask: benchmark batches are padding-free, and its absence
    # selects the maskless flash-attention path
    return {
        "input_ids": masked_ids,
        "token_type_ids": jnp.zeros((batch_size, T), jnp.int32),
        "masked_positions": pos,
        "masked_labels": labels,
        "nsp_labels": jax.random.randint(k3, (batch_size,), 0, 2),
    }
