"""Model zoo (the BASELINE.json config ladder).

Two API levels:
- JAX-native functional models (this package): pytree params with logical
  sharding axes (paddle_tpu.parallel.sharding), pure apply fns — the
  performance path used by bench.py and __graft_entry__.py.
- Static-graph builders via paddle_tpu.layers for fluid-API parity live in
  each model file as `build_program_*` where applicable.

Models follow the reference's zoo: LeNet/MNIST (tests/book/
test_recognize_digits.py), ResNet-50 (test_dist_se_resnext lineage),
BERT-base (inference/tests/api/analyzer_bert_tester.cc), Transformer NMT
(test_dist_transformer.py).
"""

from . import bert, lenet, resnet, vgg  # noqa: F401
