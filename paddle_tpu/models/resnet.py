"""ResNet v1.5 (50/101/152) — ImageNet CNN config of the ladder.

Reference capability: ResNet-50 is the reference's flagship CV benchmark
(contrib/float16/float16_benchmark.md:40; test_dist_se_resnext lineage).
TPU-first design: NHWC layout (TPU conv native), bf16 activations, fused
batch-norm as explicit scale/shift math (XLA fuses into the conv), batch
stats via masked mean (sync-BN over 'dp' comes from GSPMD when the batch is
sharded — BuildStrategy.sync_batch_norm for free).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.sharding import shard
from .common import ParamStore, Params, dense

DEPTHS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


@dataclasses.dataclass
class ResNetConfig:
    depth: int = 50
    n_classes: int = 1000
    width: int = 64
    dtype: str = "bfloat16"
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    # Pallas fused matmul+BN for the bottleneck 1x1 convs
    # (ops/pallas/fused_dense_bn.py): conv1/conv3 run as matmuls with BN
    # stats in the epilogue and the preceding BN-apply+relu in conv3's
    # prologue — the byte-floor attack scoped by tools/rn50_bytes_table.py.
    # Default OFF (the XLA path is the settled baseline); training-mode,
    # single-device-or-manual-region only (pallas has no GSPMD rule).
    fused_1x1: bool = False

    @staticmethod
    def resnet50():
        return ResNetConfig(50)

    @staticmethod
    def tiny():
        return ResNetConfig(depth=50, n_classes=10, width=8)

    def flops_per_image(self, hw: int = 224) -> float:
        # RN50@224 fwd = 4.089 G multiply-accumulates = 8.18 GFLOPs (the
        # often-quoted "4.1 GFLOPs" counts MACs; exact conv+head MAC sum
        # in tools/rn50_roofline.py / PROFILE.md). x3 for training
        # (fwd + dgrad + wgrad). Width/resolution scale quadratically.
        base = 8.18e9 * (self.width / 64) ** 2 * (hw / 224) ** 2
        return 3 * base * (1 if self.depth == 50 else self.depth / 50)


def _bn_init(s: ParamStore, name: str, dim: int):
    s.bn(name, dim)


def init(rng: jax.Array, cfg: ResNetConfig) -> Tuple[Params, Dict]:
    s = ParamStore(rng)
    w = cfg.width
    s.conv("stem", 7, 7, 3, w)
    _bn_init(s, "stem.bn", w)
    cin = w
    for gi, blocks in enumerate(DEPTHS[cfg.depth]):
        mid = w * (2 ** gi)
        cout = mid * 4
        for bi in range(blocks):
            p = f"g{gi}.b{bi}"
            s.conv(f"{p}.conv1", 1, 1, cin, mid)
            _bn_init(s, f"{p}.bn1", mid)
            s.conv(f"{p}.conv2", 3, 3, mid, mid)
            _bn_init(s, f"{p}.bn2", mid)
            s.conv(f"{p}.conv3", 1, 1, mid, cout)
            _bn_init(s, f"{p}.bn3", cout)
            if bi == 0:
                s.conv(f"{p}.proj", 1, 1, cin, cout)
                _bn_init(s, f"{p}.proj.bn", cout)
            cin = cout
    s.dense("head", cin, cfg.n_classes, axes=("embed", "vocab"))
    return s.params, s.axes


def _conv(params, name, x, stride=1, padding="SAME"):
    from .common import conv2d_nhwc_auto

    return conv2d_nhwc_auto(params, name, x, stride, padding)


def _bn_ema(params, state_updates, name, mean, var, cfg):
    """Write the running-stat EMA updates for batch stats (mean, var)."""
    m = cfg.bn_momentum
    state_updates[f"{name}.mean"] = m * params[f"{name}.mean"] + (1 - m) * mean
    state_updates[f"{name}.var"] = m * params[f"{name}.var"] + (1 - m) * var


def _bn_stats(x):
    """One-pass batch stats: E[x] and E[x^2] fuse into a single read of
    the activations (jnp.var's (x-mean)^2 forces a second pass; measured
    116->105 ms fwd+bwd for RN50 bs=256 — PROFILE.md). Promoted (f32, or
    f64 under x64 rigs) accumulation keeps the cancellation benign (the
    cudnn approach). Shared by _bn and the fused-1x1 path so stats
    semantics cannot diverge."""
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    mean = xf.mean((0, 1, 2))
    var = jnp.maximum((xf * xf).mean((0, 1, 2)) - mean * mean, 0.0)
    return mean, var


def _fused_1x1_ok(params, p, cfg, train: bool) -> bool:
    """Gate for the pallas fused-1x1 path: opt-in, training mode, fp
    weights (the int8 serving path must keep conv2d_nhwc_auto's scale
    dispatch), and a context where a pallas_call is legal (single
    device / manual region)."""
    if not (cfg.fused_1x1 and train):
        return False
    if params[f"{p}.conv1.w"].dtype == jnp.int8 or \
            params[f"{p}.conv3.w"].dtype == jnp.int8:
        return False
    from ..parallel.mesh import current_mesh

    m = current_mesh()
    return m is None or m.devices.size == 1


def _fused_block_tail(params, upd, p, x, cfg):
    """conv1+bn1-stats, relu; conv2(3x3) unchanged via XLA; bn2-apply+
    relu fused into conv3's prologue with bn3 stats in its epilogue.
    Only the stride-1 non-proj shape runs fused (stride lives on conv2).
    Returns the block's pre-residual output h (bn3-normalized)."""
    from ..ops.pallas import fused_dense_bn as F

    B, H, W, C = x.shape
    w1 = params[f"{p}.conv1.w"].astype(x.dtype).reshape(C, -1)
    h1, m1, v1 = F.matmul_stats(x.reshape(-1, C), w1)
    _bn_ema(params, upd, f"{p}.bn1", m1, v1, cfg)
    s1, b1 = F.fold_bn(m1, v1, params[f"{p}.bn1.scale"],
                       params[f"{p}.bn1.bias"], cfg.bn_eps)
    h1 = jnp.maximum(h1.astype(s1.dtype) * s1 + b1, 0.0).astype(x.dtype)
    return h1.reshape(B, H, W, -1)


def _fused_conv3(params, upd, p, h2raw, cfg):
    """bn2-apply+relu (prologue) -> conv3 1x1 (matmul) -> bn3 stats
    (epilogue), one kernel; h2raw is conv2's RAW output."""
    from ..ops.pallas import fused_dense_bn as F

    B, H, W, C = h2raw.shape
    m2, v2 = _bn_stats(h2raw)
    _bn_ema(params, upd, f"{p}.bn2", m2, v2, cfg)
    s2, b2 = F.fold_bn(m2, v2, params[f"{p}.bn2.scale"],
                       params[f"{p}.bn2.bias"], cfg.bn_eps)
    w3 = params[f"{p}.conv3.w"].astype(h2raw.dtype).reshape(C, -1)
    h3, m3, v3 = F.bn_act_matmul_stats(h2raw.reshape(-1, C), s2, b2, w3,
                                       relu=True)
    _bn_ema(params, upd, f"{p}.bn3", m3, v3, cfg)
    s3, b3 = F.fold_bn(m3, v3, params[f"{p}.bn3.scale"],
                       params[f"{p}.bn3.bias"], cfg.bn_eps)
    h3 = (h3.astype(s3.dtype) * s3 + b3).astype(h2raw.dtype)
    return h3.reshape(B, H, W, -1)


def _bn(params, state_updates, name, x, cfg, train: bool):
    """BN in fp32; updates running stats into state_updates when training.
    When the batch axis is sharded over 'dp', XLA computes the mean/var with
    a cross-device reduction — sync-BN semantics by construction.

    Stats promote to f64 for f64 activations (x64 test runs): the one-pass
    E[x^2]-E[x]^2 form has f32 cancellation noise that changes with shard
    summation order, which would mask dp-vs-single parity checks."""
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    if train:
        mean, var = _bn_stats(x)
        _bn_ema(params, state_updates, name, mean, var, cfg)
    else:
        mean = params[f"{name}.mean"]
        var = params[f"{name}.var"]
    inv = jax.lax.rsqrt(var + cfg.bn_eps) * params[f"{name}.scale"]
    y = (xf - mean) * inv + params[f"{name}.bias"]
    return y.astype(x.dtype)


def apply(params: Params, cfg: ResNetConfig, img: jax.Array,
          train: bool = False,
          data_format: str = "NCHW") -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """img -> (logits, bn_updates).

    data_format="NHWC" is the native TPU path; "NCHW" is an API-parity
    shim for reference-style [B,3,H,W] feeds whose in-graph transpose
    XLA folds into the stem conv (measured neutral at bs=256 on v5e —
    PROFILE.md round 3). Benches feed NHWC anyway: it is what a real TPU
    input pipeline delivers."""
    adt = jnp.dtype(cfg.dtype)
    if data_format == "NCHW":
        x = img.transpose(0, 2, 3, 1).astype(adt)  # NHWC
    else:
        assert data_format == "NHWC", data_format
        x = img.astype(adt)
    x = shard(x, ("batch", None, None, None))
    upd: Dict[str, jax.Array] = {}
    x = _conv(params, "stem", x, stride=2)
    x = jax.nn.relu(_bn(params, upd, "stem.bn", x, cfg, train))
    x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), constant_values=-jnp.inf if False else 0)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "VALID")
    for gi, blocks in enumerate(DEPTHS[cfg.depth]):
        for bi in range(blocks):
            p = f"g{gi}.b{bi}"
            stride = 2 if (bi == 0 and gi > 0) else 1
            sc = x
            if bi == 0:
                sc = _conv(params, f"{p}.proj", x, stride=stride)
                sc = _bn(params, upd, f"{p}.proj.bn", sc, cfg, train)
            if _fused_1x1_ok(params, p, cfg, train):
                # pallas fused 1x1 path (byte-floor attack): conv1 with
                # bn1 stats in its epilogue; bn2-apply+relu in conv3's
                # prologue with bn3 stats in its epilogue
                h = _fused_block_tail(params, upd, p, x, cfg)
                h2raw = _conv(params, f"{p}.conv2", h, stride=stride)
                h = _fused_conv3(params, upd, p, h2raw, cfg)
            else:
                h = jax.nn.relu(_bn(params, upd, f"{p}.bn1",
                                    _conv(params, f"{p}.conv1", x), cfg,
                                    train))
                h = jax.nn.relu(_bn(params, upd, f"{p}.bn2",
                                    _conv(params, f"{p}.conv2", h,
                                          stride=stride),
                                    cfg, train))
                h = _bn(params, upd, f"{p}.bn3",
                        _conv(params, f"{p}.conv3", h), cfg, train)
            x = jax.nn.relu(h + sc)
    x = x.mean((1, 2))  # global avg pool
    logits = dense(params, "head", x.astype(jnp.float32))
    return logits, upd


def loss_fn(params: Params, cfg: ResNetConfig, batch, rng=None,
            train: bool = True, data_format: str = "NCHW"):
    logits, upd = apply(params, cfg, batch["img"], train=train,
                        data_format=data_format)
    labels = batch["label"].reshape(-1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    loss = -jnp.take_along_axis(logp, labels[:, None], 1).mean()
    return loss, upd


def make_batch(rng: jax.Array, cfg: ResNetConfig, batch_size: int,
               hw: int = 224, data_format: str = "NCHW"):
    k1, k2 = jax.random.split(rng)
    assert data_format in ("NCHW", "NHWC"), data_format
    shape = (batch_size, 3, hw, hw) if data_format == "NCHW" \
        else (batch_size, hw, hw, 3)
    return {
        "img": jax.random.normal(k1, shape, jnp.float32),
        "label": jax.random.randint(k2, (batch_size,), 0, cfg.n_classes),
    }
