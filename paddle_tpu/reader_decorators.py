"""Reader decorators (reference: python/paddle/reader/decorator.py —
map_readers, buffered, compose, chain, shuffle, firstn, cache, batch)."""

from __future__ import annotations

import itertools
import queue
import random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "cache", "batch"]


def map_readers(func, *readers):
    def reader():
        for vals in zip(*[r() for r in readers]):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        rng = random.Random(0)
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment=True):
    def reader():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return reader


def buffered(reader, size):
    def buffered_reader():
        q: "queue.Queue" = queue.Queue(maxsize=size)
        end = object()

        def worker():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(end)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e

    return buffered_reader


def firstn(reader, n):
    def reader_n():
        yield from itertools.islice(reader(), n)

    return reader_n


def cache(reader):
    all_data = None

    def cached():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        yield from all_data

    return cached


def batch(reader, batch_size, drop_last=False):
    def batched():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (reference:
    reader/decorator.py xmap_readers; threads instead of processes — the
    mappers here are numpy transforms that release the GIL)."""
    import queue as _queue
    import threading

    end = object()

    class _Err:
        def __init__(self, exc):
            self.exc = exc

    def data_reader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            # sentinel in finally: a dying producer must never leave the
            # consumer blocked (the buffered() pattern above)
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:
                out_q.put(_Err(e))
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        return
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as e:
                out_q.put(_Err(e))
            finally:
                out_q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _Err):
                raise item.exc
            if order:
                i, mapped = item
                pending[i] = mapped
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
            else:
                yield item[1]

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers on threads (reference:
    decorator.py multiprocess_reader; thread-backed here — the use case is
    overlapping IO-bound readers)."""
    import queue as _queue
    import threading

    end = object()

    class _Err:
        def __init__(self, exc):
            self.exc = exc

    def data_reader():
        q = _queue.Queue(queue_size)

        def pump(r):
            try:
                for sample in r():
                    q.put(sample)
            except BaseException as e:
                q.put(_Err(e))
            finally:
                q.put(end)

        for r in readers:
            threading.Thread(target=pump, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _Err):
                raise item.exc
            yield item

    return data_reader
