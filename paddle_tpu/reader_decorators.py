"""Reader decorators (reference: python/paddle/reader/decorator.py —
map_readers, buffered, compose, chain, shuffle, firstn, cache, batch)."""

from __future__ import annotations

import itertools
import queue
import random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "cache", "batch"]


def map_readers(func, *readers):
    def reader():
        for vals in zip(*[r() for r in readers]):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        rng = random.Random(0)
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment=True):
    def reader():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return reader


def buffered(reader, size):
    def buffered_reader():
        q: "queue.Queue" = queue.Queue(maxsize=size)
        end = object()

        def worker():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(end)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e

    return buffered_reader


def firstn(reader, n):
    def reader_n():
        yield from itertools.islice(reader(), n)

    return reader_n


def cache(reader):
    all_data = None

    def cached():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        yield from all_data

    return cached


def batch(reader, batch_size, drop_last=False):
    def batched():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched
