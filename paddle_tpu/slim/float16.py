"""Half-precision inference transpiler.

Reference: paddle/contrib/float16/float16_transpiler.py — rewrite a saved
inference program so weights and compute run in fp16, with boundary casts
at feeds and fetches (the reference's float16_benchmark.md numbers come
from this path).

TPU-native: bfloat16 is the hardware's half type (MXU-native, no loss
scaling needed), so the default target is bf16; fp16 remains available.
The rewrite is: cast persistable params in the scope, retag their
VarDescs, and insert boundary `cast` ops after each feed and before each
fetch target — everything between runs in half via JAX type promotion
inside the one compiled XLA computation.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.framework import Program
from ..core.ir import OpDesc, VarDesc


def float16_transpile(program: Program, scope,
                      target_vars: Optional[Sequence[str]] = None,
                      dtype: str = "bfloat16") -> Program:
    """In-place: half-precision weights + boundary casts. `target_vars`
    are the fetch targets cast back to float32 (defaults to the program's
    recorded fetch_names)."""
    import jax.numpy as jnp

    assert dtype in ("bfloat16", "float16")
    desc = program.global_block().desc
    fetches = list(target_vars or program._attrs.get("fetch_names", []))
    feeds = list(program._attrs.get("feed_names", []))

    # 1. cast persistable float32 params in the scope + retag descs
    for name, vd in desc.vars.items():
        if not vd.persistable or vd.dtype != "float32":
            continue
        val = scope.find_var(name)
        if val is not None:
            scope.set_var(name, jnp.asarray(np.asarray(val), dtype))
        vd.dtype = dtype

    # 2. boundary casts: feed fp32 -> half at the top, fetch half -> fp32
    cast_in_ops = []
    rename = {}
    for fname in feeds:
        # integer feeds (token ids) must stay integer — only float inputs
        # are cast (the reference transpiler does the same)
        if fname not in desc.vars or desc.vars[fname].dtype != "float32":
            continue
        half = f"{fname}.cast_fp16"
        src = desc.vars[fname]
        desc.vars[half] = VarDesc(name=half, shape=src.shape, dtype=dtype,
                                  stop_gradient=True)
        cast_in_ops.append(OpDesc(
            type="cast", inputs={"X": [fname]}, outputs={"Out": [half]},
            attrs={"in_dtype": "float32", "out_dtype": dtype}))
        rename[fname] = half
    for op in desc.ops:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(n, n) for n in names]
    cast_out_ops = []
    for tname in fetches:
        if tname not in desc.vars or \
                desc.vars[tname].dtype not in ("float32", dtype):
            continue
        half = f"{tname}.fp16_out"
        # the producing ops now emit half values into a renamed var; the
        # original name becomes the cast-back output so fetch_names and
        # downstream consumers keep working
        desc.vars[half] = VarDesc(name=half,
                                  shape=desc.vars[tname].shape,
                                  dtype=dtype, stop_gradient=True)
        for op in desc.ops:
            for slot, names in op.outputs.items():
                op.outputs[slot] = [half if n == tname else n
                                    for n in names]
            for slot, names in op.inputs.items():
                op.inputs[slot] = [half if n == tname else n
                                   for n in names]
        cast_out_ops.append(OpDesc(
            type="cast", inputs={"X": [half]}, outputs={"Out": [tname]},
            attrs={"in_dtype": dtype, "out_dtype": "float32"}))
    desc.ops = cast_in_ops + desc.ops + cast_out_ops
    program._rebuild_from_desc()
    return program
