"""Model compression (reference: python/paddle/fluid/contrib/slim — the
quantization/pruning/NAS/distillation toolkit, SURVEY §2.4). Round-1 scope:
post-training quantization for inference."""

from .quantization import (  # noqa: F401
    quantize_inference_model, PostTrainingQuantization,
)
