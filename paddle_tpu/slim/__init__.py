"""Model compression (reference: python/paddle/fluid/contrib/slim — the
quantization/pruning/NAS/distillation toolkit, SURVEY §2.4): post-training
INT8 quantization, quantization-aware training (QAT transform + freeze
passes), magnitude pruning with sensitivity analysis, knowledge
distillation (soft-label/L2/FSP), and simulated-annealing NAS with a TCP
controller server."""

from .quantization import (  # noqa: F401
    quantize_inference_model, PostTrainingQuantization,
)
from .qat import (  # noqa: F401
    QuantizationFreezePass, QuantizationTransformPass,
)
from .prune import Pruner, SensitivePruneStrategy  # noqa: F401
from . import distillation  # noqa: F401
from .nas import ControllerServer, SAController, SearchAgent  # noqa: F401
from .float16 import float16_transpile  # noqa: F401
