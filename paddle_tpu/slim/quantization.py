"""Post-training quantization.

Reference: contrib/slim/quantization (QuantizationTranspiler / post-training
INT8, cpu_quantize_pass.cc). TPU-native round-1 scope: weight-only INT8 —
matmul/conv weights are stored as int8 with per-output-channel scales and
dequantized on load. This quarters checkpoint size and HBM weight traffic;
activations stay bf16/fp32 (TPU matmuls are bf16-native, so weight-only is
the usual win; int8 activation quant needs calibration and is round-2).

The quantized model keeps the SAME program: `<w>` is replaced on disk by
`<w>@INT8` + `<w>@SCALE`, and load_quantized_vars rebuilds the float weight.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import events as _events
from ..observability import metrics as _m

QUANT_META_FILE = "__quant_meta__.json"

# Calibration/quantization visibility (ISSUE 7 satellite): the passes
# used to run silently — a degenerate scale (a dead activation, a
# near-zero weight channel) was invisible until accuracy fell over.
# Every computed scale now lands in a histogram, per-var counts in a
# counter, and each pass appends a `quantize` event to the JSONL log.
QUANT_SCALE = _m.histogram(
    "paddle_tpu_quant_scale",
    "Quantization scales computed by slim passes (kind=weight is one "
    "sample per output channel, kind=activation one per calibrated "
    "tensor); a spike at the 1.0 fallback bucket means all-zero "
    "tensors were calibrated",
    labelnames=("kind",),
    buckets=_m.exponential_buckets(1e-8, 10.0, 12))
QUANT_VARS = _m.counter(
    "paddle_tpu_quant_vars_total",
    "Tensors quantized/calibrated by slim passes",
    labelnames=("kind",))
QUANT_BYTES_SAVED = _m.counter(
    "paddle_tpu_quant_bytes_saved_total",
    "fp32 bytes minus int8+scale bytes across quantized weights")
QUANT_OPS = {"mul": "Y", "matmul": "Y", "matmul_v2": "Y",
             "conv2d": "Filter", "depthwise_conv2d": "Filter",
             "conv3d": "Filter", "lookup_table": "W"}


def _fname(name: str, suffix: str = "") -> str:
    # io.save_vars mangles '/' the same way
    from ..io import var_filename

    return var_filename(name) + suffix + ".npy"


def _quantize_array(w: np.ndarray, axis: int = -1):
    """Symmetric per-channel int8 quant along `axis` (output channels)."""
    w = np.asarray(w, np.float32)
    red = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    amax = np.abs(w).max(axis=red, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale


def _dequantize_array(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return q.astype(np.float32) * scale


class PostTrainingQuantization:
    """reference: contrib/slim post-training quantizer driver. Weight-only:
    `quantize()` rewrites the saved inference model in place (or to
    `save_model_path`)."""

    def __init__(self, model_dir: str, save_model_path: Optional[str] = None,
                 quantizable_op_type: Optional[Sequence[str]] = None,
                 quantizable_var_names: Optional[Sequence[str]] = None):
        """quantizable_var_names: when given, quantize ONLY these weight
        vars (callers that rewrite a subset of ops — calibrate_and_
        quantize — must restrict the pass to the weights they rewrite;
        quantizing a weight a skipped op still reads deletes the fp32
        .npy that op needs in the native predictor)."""
        self.model_dir = model_dir
        self.save_path = save_model_path or model_dir
        self.op_types = set(quantizable_op_type or QUANT_OPS)
        self.var_names = (None if quantizable_var_names is None
                          else set(quantizable_var_names))

    def quantize(self) -> Dict[str, float]:
        """Returns {var_name: compression_ratio}."""
        from ..core.ir import ProgramDesc

        with open(os.path.join(self.model_dir, "__model__")) as f:
            payload = json.load(f)
        desc = ProgramDesc.from_dict(payload["program"])

        # weight vars = persistable inputs of quantizable ops
        targets: Dict[str, str] = {}
        for b in desc.blocks:
            for op in b.ops:
                slot = QUANT_OPS.get(op.type)
                if op.type not in self.op_types or slot is None:
                    continue
                for n in op.inputs.get(slot, []):
                    if self.var_names is not None and n not in self.var_names:
                        continue
                    v = b.vars.get(n)
                    if v is not None and v.persistable:
                        targets[n] = op.type

        os.makedirs(self.save_path, exist_ok=True)
        if os.path.abspath(self.save_path) != os.path.abspath(self.model_dir):
            from ..resilience.atomic import write_bytes

            # atomic copy (was shutil.copy): a crash mid-copy must not
            # leave a half-written __model__/weight file that a later
            # boot would happily load
            for fn in os.listdir(self.model_dir):
                with open(os.path.join(self.model_dir, fn), "rb") as f:
                    write_bytes(os.path.join(self.save_path, fn),
                                f.read())

        # merge with any existing meta (re-quantizing an already-quantized
        # model must not clobber it)
        meta_path = os.path.join(self.save_path, QUANT_META_FILE)
        meta = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)

        ratios = {}
        missing = []
        for name, op_type in targets.items():
            if name in meta:
                continue  # already quantized
            path = os.path.join(self.save_path, _fname(name))
            if not os.path.exists(path):
                missing.append(name)
                continue
            w = np.load(path)
            # per-output-channel: conv filters quantize along dim 0
            axis = 0 if "conv" in op_type else -1
            q, scale = _quantize_array(w, axis=axis)
            from ..resilience import atomic as _atomic

            _atomic.np_save(
                os.path.join(self.save_path, _fname(name, "@INT8")), q)
            _atomic.np_save(
                os.path.join(self.save_path, _fname(name, "@SCALE")), scale)
            os.remove(path)
            meta[name] = {"axis": axis, "dtype": str(w.dtype)}
            ratios[name] = float(w.nbytes) / (q.nbytes + scale.nbytes)
            for s in np.asarray(scale, np.float32).ravel():
                QUANT_SCALE.observe(float(s), kind="weight")
            QUANT_VARS.inc(kind="weight")
            QUANT_BYTES_SAVED.inc(
                max(0, int(w.nbytes) - int(q.nbytes + scale.nbytes)))
        if missing and not ratios and not meta:
            raise ValueError(
                f"no per-var .npy weight files found for {missing} — models "
                f"saved with a combined params_filename are not supported; "
                f"re-save without params_filename")
        if meta:
            from ..resilience.atomic import json_dump

            json_dump(meta, meta_path)
        if ratios:
            _events.emit(
                "quantize", action="weights", dir=self.save_path,
                vars=len(ratios),
                mean_compression=round(
                    sum(ratios.values()) / len(ratios), 3))
        return ratios


def load_quantized_vars(dirname: str,
                        names: Optional[Sequence[str]] = None
                        ) -> Dict[str, np.ndarray]:
    """Dequantize `<w>@INT8` + `<w>@SCALE` pairs back to float weights
    (called by io.load_* when __quant_meta__.json is present); `names`
    restricts dequantization to the requested vars."""
    meta_path = os.path.join(dirname, QUANT_META_FILE)
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        meta = json.load(f)
    out = {}
    for name, info in meta.items():
        if names is not None and name not in names:
            continue
        q = np.load(os.path.join(dirname, _fname(name, "@INT8")))
        scale = np.load(os.path.join(dirname, _fname(name, "@SCALE")))
        out[name] = _dequantize_array(q, scale).astype(info.get("dtype",
                                                               "float32"))
    return out


def quantize_inference_model(model_dir: str,
                             save_model_path: Optional[str] = None):
    """One-call weight-only INT8 quantization of a saved inference model."""
    return PostTrainingQuantization(model_dir, save_model_path).quantize()


# ---------------------------------------------------------------------------
# Calibration-based INT8 runtime (reference: inference/api/
# mkldnn_quantizer.cc — run calibration batches, collect per-activation
# scales, rewrite the graph to INT8 kernels via cpu_quantize_pass.cc)
# ---------------------------------------------------------------------------

_INT8_REWRITE = {"mul": ("quantized_mul", "Y", "X"),
                 "matmul": ("quantized_matmul", "Y", "X"),
                 "conv2d": ("quantized_conv2d", "Filter", "Input")}


def calibrate_and_quantize(model_dir: str, calibration_reader,
                           save_model_path: Optional[str] = None,
                           quantizable_op_type: Optional[Sequence[str]] = None
                           ) -> Dict[str, float]:
    """Full INT8 pipeline over a saved fp32 inference model:

    1. run `calibration_reader` batches (iterable of feed dicts) through
       the fp32 model, recording each quantizable op's activation-input
       abs-max -> per-tensor activation scale (amax / 127);
    2. quantize the weights (per-output-channel int8, existing PTQ);
    3. REWRITE the saved program: mul/matmul/conv2d become
       quantized_mul/quantized_matmul/quantized_conv2d consuming the int8
       weight + scale vars with the calibrated x_scale attr.

    The result is a model dir that both engines execute with true int8
    matmul/conv compute (int32 accumulation): the XLA Predictor via
    ops/quant.py's quantized_* kernels, the native C++ predictor via its
    int8 gemm/conv kernels. Returns {activation_var: scale}."""
    from ..core.executor import Executor, Scope, scope_guard
    from ..core.ir import ProgramDesc, VarDesc
    from ..core.places import CPUPlace
    from .. import io as pt_io

    op_types = set(quantizable_op_type or _INT8_REWRITE)
    save_path = save_model_path or model_dir

    # -- 1. calibration on the fp32 model ----------------------------------
    exe = Executor(CPUPlace())
    scope = Scope()
    with scope_guard(scope):
        program, feed_names, _ = pt_io.load_inference_model(model_dir, exe)
        targets = []          # (op_idx, act_var, weight_var, op_type)
        desc0 = program.desc.blocks[0]
        for i, op in enumerate(desc0.ops):
            if op.type not in op_types or op.type not in _INT8_REWRITE:
                continue
            _, wslot, xslot = _INT8_REWRITE[op.type]
            wnames = op.inputs.get(wslot, [])
            xnames = op.inputs.get(xslot, [])
            if not wnames or not xnames:
                continue
            wv = desc0.vars.get(wnames[0])
            if wv is None or not wv.persistable:
                continue
            if op.type == "matmul":
                # quantized_matmul handles plain 2-D X @ W only — leave
                # transposed/scaled/batched matmuls in fp32
                xv = desc0.vars.get(xnames[0])
                if (op.attrs.get("transpose_X") or
                        op.attrs.get("transpose_Y") or
                        float(op.attrs.get("alpha", 1.0)) != 1.0 or
                        (xv is not None and xv.shape is not None
                         and len(xv.shape) != 2)):
                    continue
            if op.type == "conv2d":
                # quantized_conv2d covers the vanilla case both engines
                # execute identically; grouped/dilated/auto-padded convs
                # stay fp32 (the native int8 kernel rejects them)
                pads = [int(p) for p in op.attrs.get("paddings", [0, 0])]
                if (int(op.attrs.get("groups", 1) or 1) > 1 or
                        any(int(d) != 1
                            for d in op.attrs.get("dilations", [1, 1])) or
                        op.attrs.get("padding_algorithm",
                                     "EXPLICIT") != "EXPLICIT" or
                        (len(pads) == 4 and (pads[0] != pads[1]
                                             or pads[2] != pads[3]))):
                    continue
            targets.append((i, xnames[0], wnames[0], op.type))
        act_names = sorted({t[1] for t in targets})
        amax = {n: 0.0 for n in act_names}
        n_batches = 0
        for feed in calibration_reader():
            outs = exe.run(program, feed=feed, fetch_list=act_names)
            for n, v in zip(act_names, outs):
                amax[n] = max(amax[n], float(np.abs(np.asarray(v)).max()))
            n_batches += 1
        if n_batches == 0:
            raise ValueError("calibration reader yielded no batches")
    act_scales = {n: (m / 127.0 if m > 0 else 1.0)
                  for n, m in amax.items()}
    for s in act_scales.values():
        QUANT_SCALE.observe(float(s), kind="activation")
        QUANT_VARS.inc(kind="activation")
    _events.emit("quantize", action="calibrate", dir=save_path,
                 activations=len(act_scales), batches=n_batches)

    # -- 2. weight quantization --------------------------------------------
    # A weight read by any op OUTSIDE the rewrite set (a skipped
    # quantizable op — grouped/dilated conv, transposed/non-2D matmul —
    # or a non-quantizable consumer) must stay fp32 end to end: the
    # native predictor loads persistables strictly from '<name>.npy',
    # so quantizing it would delete the file that op still needs.
    rewrite_idx = {t[0] for t in targets}
    weight_of = {t[0]: t[2] for t in targets}
    fp32_needed = set()
    # scan ALL blocks: the rewrite touches block 0 only, so an op in a
    # control-flow sub-block reading a shared weight also pins it fp32
    for bi, blk in enumerate(program.desc.blocks):
        for j, op in enumerate(blk.ops):
            rewritten = bi == 0 and j in rewrite_idx
            for slot, ns in op.inputs.items():
                for n in ns:
                    if not rewritten or n != weight_of.get(j):
                        fp32_needed.add(n)
    targets = [t for t in targets if t[2] not in fp32_needed]
    PostTrainingQuantization(
        model_dir, save_path,
        quantizable_op_type=[t for t in op_types],
        quantizable_var_names=[t[2] for t in targets]).quantize()

    # -- 3. program rewrite -------------------------------------------------
    model_path = os.path.join(save_path, "__model__")
    with open(model_path) as f:
        payload = json.load(f)
    desc = ProgramDesc.from_dict(payload["program"])
    meta_path = os.path.join(save_path, QUANT_META_FILE)
    with open(meta_path) as f:
        meta = json.load(f)
    b0 = desc.blocks[0]
    for i, xname, wname, op_type in targets:
        if wname not in meta:
            continue
        op = b0.ops[i]
        new_type, wslot, _ = _INT8_REWRITE[op_type]
        q = np.load(os.path.join(save_path, _fname(wname, "@INT8")))
        s = np.load(os.path.join(save_path, _fname(wname, "@SCALE")))
        b0.vars[wname + "@INT8"] = VarDesc(
            name=wname + "@INT8", shape=tuple(q.shape), dtype="int8",
            persistable=True, stop_gradient=True)
        b0.vars[wname + "@SCALE"] = VarDesc(
            name=wname + "@SCALE", shape=tuple(s.shape), dtype="float32",
            persistable=True, stop_gradient=True)
        op.type = new_type
        op.inputs[wslot] = [wname + "@INT8"]
        op.inputs["Scale"] = [wname + "@SCALE"]
        op.attrs["x_scale"] = float(act_scales[xname])
        # drop the fp32 weight desc ONLY if no remaining (skipped/fp32)
        # op still reads it — a shared weight with a non-rewritten
        # consumer must keep loading the float values
        still_used = any(n == wname for o2 in b0.ops
                         for ns in o2.inputs.values() for n in ns)
        if not still_used:
            b0.vars.pop(wname, None)
    payload["program"] = desc.to_dict()
    payload["act_scales"] = act_scales
    from ..resilience.atomic import json_dump

    json_dump(payload, model_path)
    return act_scales
