"""Magnitude pruning.

Reference: contrib/slim/prune/pruner.py (RatioPruner: zero the
smallest-magnitude weights per parameter) and prune_strategy.py
(SensitivePruneStrategy: per-parameter sensitivity = eval-metric drop as
a function of prune ratio, used to pick per-layer ratios under a global
budget).

TPU-native: pruning is a scope-level weight rewrite plus persistent 0/1
mask parameters; `apply_masks` appends an elementwise multiply with the
mask after each optimizer step so pruned weights stay zero while the
dense XLA matmuls run unchanged (sparsity on TPU is a memory/BW win at
export, not a compute win — same as the reference's dense-mask design).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import numpy as np

from ..core.framework import Program
from ..core.ir import OpDesc, VarDesc


class Pruner:
    """Unstructured (ratio) or structured (filter-L1) magnitude pruning."""

    def __init__(self, mode: str = "ratio"):
        assert mode in ("ratio", "filter_l1")
        self.mode = mode

    def prune(self, scope, params: Sequence[str],
              ratios: Dict[str, float]) -> Dict[str, np.ndarray]:
        """Zero weights in-place; returns the binary keep-masks."""
        masks = {}
        for name in params:
            val = scope.find_var(name)
            if val is None:
                continue
            w = np.asarray(val)
            ratio = float(ratios.get(name, ratios.get("*", 0.0)))
            if ratio <= 0:
                masks[name] = np.ones_like(w)
                continue
            if self.mode == "filter_l1" and w.ndim >= 2:
                # structured: prune whole output filters by L1 norm.
                # Output axis: 0 for conv [O,I,H,W], last for fc [In,Out]
                # (same convention as qat.py channel-wise quantization)
                out_axis = 0 if w.ndim == 4 else w.ndim - 1
                axes = tuple(i for i in range(w.ndim) if i != out_axis)
                norms = np.abs(w).sum(axis=axes)
                k = int(len(norms) * ratio)
                mask = np.ones_like(w)
                if k > 0:
                    drop = np.argsort(norms)[:k]
                    idx = [slice(None)] * w.ndim
                    idx[out_axis] = drop
                    mask[tuple(idx)] = 0.0
            else:
                flat = np.abs(w).ravel()
                k = int(flat.size * ratio)
                mask = np.ones(flat.size, w.dtype)
                if k > 0:
                    thresh_idx = np.argsort(flat)[:k]
                    mask[thresh_idx] = 0.0
                mask = mask.reshape(w.shape)
            scope.set_var(name, (w * mask).astype(w.dtype))
            masks[name] = mask
        return masks

    def apply_masks(self, program: Program, scope,
                    masks: Dict[str, np.ndarray]):
        """Register masks as persistable vars and append `p = p * mask`
        after the optimizer ops, keeping pruned entries at zero during
        continued training."""
        block = program.global_block()
        desc = block.desc
        for name, mask in masks.items():
            mname = f"{name}.prune_mask"
            desc.vars[mname] = VarDesc(name=mname, shape=tuple(mask.shape),
                                       dtype="float32", persistable=True,
                                       stop_gradient=True)
            scope.set_var(mname, mask.astype("float32"))
            desc.ops.append(OpDesc(
                type="elementwise_mul",
                inputs={"X": [name], "Y": [mname]},
                outputs={"Out": [name]},
                attrs={"axis": -1}))
        program._rebuild_from_desc()
        return program


class SensitivePruneStrategy:
    """Measure sensitivity: eval-metric vs prune ratio per parameter
    (reference: prune_strategy.py SensitivePruneStrategy.metric drop).
    `eval_fn()` returns the current metric (higher = better)."""

    def __init__(self, pruner: Optional[Pruner] = None,
                 ratios: Sequence[float] = (0.1, 0.3, 0.5, 0.7)):
        self.pruner = pruner or Pruner()
        self.ratios = list(ratios)

    def sensitivity(self, scope, params: Sequence[str],
                    eval_fn: Callable[[], float]) -> Dict[str, Dict[float, float]]:
        base = eval_fn()
        result: Dict[str, Dict[float, float]] = {}
        for name in params:
            if scope.find_var(name) is None:
                continue
            keep = np.asarray(scope.find_var(name)).copy()
            result[name] = {}
            for r in self.ratios:
                self.pruner.prune(scope, [name], {name: r})
                result[name][r] = base - eval_fn()   # metric drop
                scope.set_var(name, keep)
        return result

    def pick_ratios(self, sensitivities: Dict[str, Dict[float, float]],
                    max_drop: float) -> Dict[str, float]:
        """Largest per-param ratio whose measured drop stays under
        max_drop."""
        out = {}
        for name, curve in sensitivities.items():
            best = 0.0
            for r, drop in sorted(curve.items()):
                if drop <= max_drop:
                    best = r
            out[name] = best
        return out
