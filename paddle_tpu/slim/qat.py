"""Quantization-aware training passes.

Reference: contrib/slim/quantization/quantization_pass.py —
QuantizationTransformPass (insert fake-quant ops on the weights and
activations of quantizable ops) and QuantizationFreezePass (convert the
trained program to an int8 inference model).

TPU-native: the transform is a Program rewrite (no ir::Graph needed — the
Program IR is the graph); fake quant ops simulate the int8 grid in fp32
with a straight-through estimator so the QAT step stays one XLA
computation. Freezing reuses the post-training weight quantizer on the
QAT-trained weights and strips the fake ops.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.framework import Program
from ..core.ir import OpDesc, VarDesc

# ops whose weight/activation inputs get quantized (reference
# quantization_pass.py _quantizable_op_type)
QUANTIZABLE_OPS: Dict[str, Dict[str, str]] = {
    # op type -> {weight slot: activation slot}
    "conv2d": {"weight": "Filter", "act": "Input"},
    "depthwise_conv2d": {"weight": "Filter", "act": "Input"},
    "mul": {"weight": "Y", "act": "X"},
    "matmul": {"weight": "Y", "act": "X"},
}


class QuantizationTransformPass:
    """Insert fake quant-dequant ops ahead of quantizable ops.

    weight_quantize_type: 'abs_max' | 'channel_wise_abs_max'
    activation_quantize_type: 'moving_average_abs_max' | 'abs_max'
    """

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 weight_quantize_type: str = "channel_wise_abs_max",
                 activation_quantize_type: str = "moving_average_abs_max",
                 moving_rate: float = 0.9,
                 quantizable_op_type: Optional[Sequence[str]] = None):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type
        self.moving_rate = moving_rate
        self.op_types = set(quantizable_op_type or QUANTIZABLE_OPS)

    def apply(self, program: Program,
              startup_program: Optional[Program] = None) -> Program:
        block = program.global_block()
        desc = block.desc
        quantized: Dict[str, str] = {}   # original var -> quantized var
        new_ops: List[OpDesc] = []
        params = {name for name, v in desc.vars.items() if v.is_parameter}

        for op in desc.ops:
            if op.type in self.op_types:
                spec = QUANTIZABLE_OPS.get(op.type)
                if spec:
                    for role in ("weight", "act"):
                        slot = spec[role]
                        names = op.inputs.get(slot, [])
                        if not names or not names[0]:
                            continue
                        name = names[0]
                        if name not in quantized:
                            qname = self._insert_quant(
                                desc, new_ops, name,
                                is_weight=name in params)
                            quantized[name] = qname
                        op.inputs[slot] = [quantized[name]]
            new_ops.append(op)
        desc.ops = new_ops
        program._rebuild_from_desc()
        if startup_program is not None:
            self.init_scales(program, startup_program)
        return program

    def _mkvar(self, desc, name, shape, persistable=False):
        desc.vars[name] = VarDesc(name=name, shape=tuple(shape),
                                  dtype="float32",
                                  persistable=persistable,
                                  stop_gradient=False)
        return name

    def _insert_quant(self, desc, new_ops, name, is_weight):
        src = desc.vars.get(name)
        shape = src.shape if src is not None and src.shape else (1,)
        qname = f"{name}.quantized"
        self._mkvar(desc, qname, shape)
        bits = self.weight_bits if is_weight else self.activation_bits
        if is_weight:
            if self.weight_quantize_type == "channel_wise_abs_max":
                op_type = "fake_channel_wise_quantize_dequantize_abs_max"
                # conv weights [O,I,H,W] quantize per O (axis 0); fc
                # weights [In, Out] per Out (last axis)
                axis = 0 if len(shape) == 4 else len(shape) - 1
                attrs = {"bit_length": bits, "quant_axis": axis}
                scale_shape = (shape[axis],)
            else:
                op_type = "fake_quantize_dequantize_abs_max"
                attrs = {"bit_length": bits}
                scale_shape = (1,)
            scale = self._mkvar(desc, f"{name}.quant_scale",
                                scale_shape, persistable=False)
            new_ops.append(OpDesc(type=op_type, inputs={"X": [name]},
                                  outputs={"Out": [qname],
                                           "OutScale": [scale]},
                                  attrs=attrs))
        else:
            if self.activation_quantize_type == "moving_average_abs_max":
                op_type = "fake_quantize_dequantize_moving_average_abs_max"
                in_scale = self._mkvar(desc, f"{name}.quant_in_scale", (1,),
                                       persistable=True)
                state = self._mkvar(desc, f"{name}.quant_state", (1,),
                                    persistable=True)
                accum = self._mkvar(desc, f"{name}.quant_accum", (1,),
                                    persistable=True)
                new_ops.append(OpDesc(
                    type=op_type,
                    inputs={"X": [name], "InScale": [in_scale],
                            "InState": [state], "InAccum": [accum]},
                    # state vars update in place (persistable round trip)
                    outputs={"Out": [qname], "OutScale": [in_scale],
                             "OutState": [state], "OutAccum": [accum]},
                    attrs={"bit_length": bits,
                           "moving_rate": self.moving_rate}))
            else:
                op_type = "fake_quantize_dequantize_abs_max"
                scale = self._mkvar(desc, f"{name}.quant_scale", (1,))
                new_ops.append(OpDesc(type=op_type, inputs={"X": [name]},
                                      outputs={"Out": [qname],
                                               "OutScale": [scale]},
                                      attrs={"bit_length": bits}))
        return qname

    def init_scales(self, program: Program, startup_program: Program):
        """Emit fill_constant init ops in the startup program for every
        quant state var the transform created."""
        desc = program.global_block().desc
        sdesc = startup_program.global_block().desc
        for name, var in desc.vars.items():
            if name.endswith((".quant_in_scale", ".quant_state",
                              ".quant_accum")):
                if name not in sdesc.vars:
                    sdesc.vars[name] = VarDesc(
                        name=name, shape=(1,), dtype="float32",
                        persistable=True)
                    val = 1.0 if not name.endswith(".quant_accum") else 0.001
                    if name.endswith(".quant_in_scale"):
                        val = 0.001
                    sdesc.ops.append(OpDesc(
                        type="fill_constant", inputs={},
                        outputs={"Out": [name]},
                        attrs={"shape": [1], "dtype": "float32",
                               "value": val}))
        startup_program._rebuild_from_desc()


class QuantizationFreezePass:
    """Strip fake-quant ops and bake int8 weights for inference
    (reference: QuantizationFreezePass). Returns the frozen program; the
    scope's quantized weights are rounded to the int8 grid so inference
    matches QAT numerics."""

    def __init__(self, weight_bits: int = 8):
        self.weight_bits = weight_bits

    def apply(self, program: Program, scope) -> Program:
        from .quantization import _dequantize_array, _quantize_array

        block = program.global_block()
        desc = block.desc
        new_ops = []
        rewrites: Dict[str, str] = {}
        params = {n for n, v in desc.vars.items() if v.is_parameter}
        for op in desc.ops:
            if op.type.startswith("fake_") and "quantize" in op.type:
                x = op.inputs["X"][0]
                out = op.outputs["Out"][0]
                rewrites[out] = x
                if x in params:
                    val = scope.find_var(x)
                    if val is not None:
                        w = np.asarray(val)
                        # one quantization grid for the whole toolkit:
                        # reuse the post-training quantizer round trip
                        if op.type.startswith("fake_channel_wise"):
                            axis = int(op.attrs.get("quant_axis", 0))
                            q, sc = _quantize_array(w, axis=axis)
                            dq = _dequantize_array(q, sc)
                        else:  # per-tensor: flatten → one channel
                            q, sc = _quantize_array(w.reshape(1, -1),
                                                    axis=0)
                            dq = _dequantize_array(q, sc).reshape(w.shape)
                        scope.set_var(x, dq.astype(w.dtype))
                continue
            # rewire any input that referenced a fake-quant output
            for slot, names in op.inputs.items():
                op.inputs[slot] = [rewrites.get(n, n) for n in names]
            new_ops.append(op)
        desc.ops = new_ops
        program._rebuild_from_desc()
        return program
