"""Config-driven compression orchestration.

Reference: contrib/slim/core/compressor.py:236 `Compressor` — the YAML-
driven driver that owns the train/eval loops and schedules compression
strategies (quantization / sensitivity pruning / distillation) across
epochs via on_compression_begin / on_epoch_begin / on_epoch_end /
on_compression_end hooks (strategy base: contrib/slim/core/strategy.py).

Same shape here: `Compressor(place, scope, train_program, ...)` +
`.config(yaml_or_dict)` + `.run()`. Strategies wrap the existing slim
primitives (qat.QuantizationTransformPass, prune.Pruner/
SensitivePruneStrategy, distillation soft-label loss) with epoch
scheduling; the YAML schema mirrors the reference's
`strategies:` / `compressor:` sections.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.framework import Program


def _strip_training_ops(train_program: Program) -> Program:
    """Forward-only clone for evaluation: drop backward / optimizer /
    lr-schedule ops so an eval pass can NEVER mutate parameters or
    optimizer state (the reference Compressor takes a separate
    eval_program for the same reason, compressor.py:236)."""
    from ..core.framework import OpRole

    p = train_program.clone(for_test=True)
    drop = OpRole.Backward | OpRole.Optimize | OpRole.LRSched
    for b in p.desc.blocks:
        b.ops = [op for op in b.ops
                 if not int(op.attrs.get(OpRole.AttrName, 0)) & drop]
    p._rebuild_from_desc()
    return p


class CompressionContext:
    """What strategies see: the live training state.

    train_program is the PERSISTENT student program — mutating
    strategies (QAT insertion, mask application) always target it.
    active_program is what the train loop executes THIS epoch; the loop
    resets it to train_program at every epoch start, so a strategy that
    swaps it (distillation) holds the swap exactly for the epochs its
    hooks run — restoration is automatic, including when the range
    covers the final epoch."""

    def __init__(self, place, scope, train_program, startup_program,
                 executor, eval_fn, epoch=0, has_eval=False,
                 distill_program=None):
        self.place = place
        self.scope = scope
        self.train_program = train_program
        self.active_program = train_program
        self.distill_program = distill_program
        self.startup_program = startup_program
        self.executor = executor
        self.eval_fn = eval_fn
        self.has_eval = has_eval
        self.epoch = epoch
        self.eval_history: List[float] = []


class Strategy:
    """Hook base (reference: contrib/slim/core/strategy.py)."""

    start_epoch = 0
    end_epoch = 10 ** 9

    def on_compression_begin(self, ctx: CompressionContext):
        pass

    def on_epoch_begin(self, ctx: CompressionContext):
        pass

    def on_epoch_end(self, ctx: CompressionContext):
        pass

    def on_compression_end(self, ctx: CompressionContext):
        pass


class QuantizationStrategy(Strategy):
    """Schedule QAT: insert fake-quant ops at start_epoch (reference:
    slim/quantization/quantization_strategy.py)."""

    def __init__(self, start_epoch: int = 0, end_epoch: int = 10 ** 9,
                 weight_bits: int = 8,
                 activation_bits: int = 8,
                 weight_quantize_type: str = "channel_wise_abs_max",
                 activation_quantize_type: str = "moving_average_abs_max"):
        self.start_epoch = int(start_epoch)
        self.end_epoch = int(end_epoch)
        self.kw = dict(weight_bits=weight_bits,
                       activation_bits=activation_bits,
                       weight_quantize_type=weight_quantize_type,
                       activation_quantize_type=activation_quantize_type)
        self.applied = False

    def on_epoch_begin(self, ctx):
        if self.applied or ctx.epoch < self.start_epoch:
            return
        from .qat import QuantizationTransformPass

        QuantizationTransformPass(**self.kw).apply(
            ctx.train_program, ctx.startup_program)
        # the startup program already ran (compression begin); seed the
        # freshly-created quant state vars straight into the live scope
        # with the same values init_scales emits
        desc = ctx.train_program.global_block().desc
        for name in desc.vars:
            if not name.endswith((".quant_in_scale", ".quant_state",
                                  ".quant_accum")):
                continue
            if ctx.scope.find_var(name) is None:
                val = 1.0 if name.endswith(".quant_state") else 0.001
                ctx.scope.set_var(name, np.full((1,), val, np.float32))
        self.applied = True


class SensitivePruneStrategyScheduled(Strategy):
    """Sensitivity-driven pruning at start_epoch (reference:
    slim/prune/prune_strategy.py:241 SensitivePruneStrategy): measure the
    eval-metric drop per (param, ratio), pick the largest per-param ratio
    under `max_metric_drop`, prune, and pin masks through the remaining
    epochs."""

    def __init__(self, pruned_params: Sequence[str],
                 start_epoch: int = 0, end_epoch: int = 10 ** 9,
                 max_metric_drop: float = 0.05,
                 sensitivity_ratios: Sequence[float] = (0.1, 0.3, 0.5, 0.7),
                 mode: str = "ratio"):
        self.start_epoch = int(start_epoch)
        self.end_epoch = int(end_epoch)
        self.params = list(pruned_params)
        self.max_drop = float(max_metric_drop)
        self.ratios = list(sensitivity_ratios)
        self.mode = mode
        self.applied = False
        self.chosen: Dict[str, float] = {}

    def on_epoch_begin(self, ctx):
        if self.applied or ctx.epoch < self.start_epoch:
            return
        if not ctx.has_eval:
            raise ValueError(
                "SensitivePruneStrategy needs the Compressor's eval_func: "
                "without a metric every prune ratio shows zero drop and "
                "the maximum candidate ratio would be chosen blindly")
        from .prune import Pruner, SensitivePruneStrategy

        pruner = Pruner(self.mode)
        strat = SensitivePruneStrategy(pruner, self.ratios)
        sens = strat.sensitivity(ctx.scope, self.params, ctx.eval_fn)
        self.chosen = strat.pick_ratios(sens, self.max_drop)
        masks = pruner.prune(ctx.scope, self.params, self.chosen)
        pruner.apply_masks(ctx.train_program, ctx.scope, masks)
        self.applied = True


class UniformPruneStrategy(Strategy):
    """Fixed-ratio magnitude pruning at start_epoch (reference:
    slim/prune/prune_strategy.py UniformPruneStrategy)."""

    def __init__(self, pruned_params: Sequence[str], ratio: float = 0.5,
                 start_epoch: int = 0, end_epoch: int = 10 ** 9,
                 mode: str = "ratio"):
        self.start_epoch = int(start_epoch)
        self.end_epoch = int(end_epoch)
        self.params = list(pruned_params)
        self.ratio = float(ratio)
        self.mode = mode
        self.applied = False

    def on_epoch_begin(self, ctx):
        if self.applied or ctx.epoch < self.start_epoch:
            return
        from .prune import Pruner

        pruner = Pruner(self.mode)
        masks = pruner.prune(ctx.scope, self.params,
                             {"*": self.ratio})
        pruner.apply_masks(ctx.train_program, ctx.scope, masks)
        self.applied = True


class DistillationStrategy(Strategy):
    """Schedule knowledge distillation for an epoch range (reference:
    slim/distillation/distillation_strategy.py — trains on the
    distillation graph within [start_epoch, end_epoch] and on the plain
    student graph outside it). The distill program (student + spliced
    teacher + distill loss + optimizer, built with
    slim.distillation.merge) comes from the Compressor's
    `distill_program` argument — YAML cannot carry a Program. Since the
    run loop resets active_program every epoch, no restore bookkeeping
    is needed; hooks only fire inside the range."""

    def __init__(self, start_epoch: int = 0, end_epoch: int = 10 ** 9):
        self.start_epoch = int(start_epoch)
        self.end_epoch = int(end_epoch)
        self.distilled_epochs: List[int] = []

    def on_epoch_begin(self, ctx):
        if ctx.distill_program is None:
            raise ValueError(
                "DistillationStrategy needs Compressor(distill_program=...) "
                "— build it with slim.distillation.merge + a distill loss")
        ctx.active_program = ctx.distill_program
        self.distilled_epochs.append(ctx.epoch)


_STRATEGY_TYPES = {
    "QuantizationStrategy": QuantizationStrategy,
    "SensitivePruneStrategy": SensitivePruneStrategyScheduled,
    "UniformPruneStrategy": UniformPruneStrategy,
    "DistillationStrategy": DistillationStrategy,
}


class Compressor:
    """reference: contrib/slim/core/compressor.py:236.

    train_reader: callable -> iterable of feed dicts (one epoch).
    eval_func: callable(program, executor, scope) -> float metric
               (higher = better), or None to skip eval.
    """

    def __init__(self, place, scope, train_program: Program,
                 startup_program: Optional[Program] = None,
                 train_reader: Optional[Callable] = None,
                 train_fetch_list: Optional[Sequence] = None,
                 eval_func: Optional[Callable] = None,
                 distill_program: Optional[Program] = None,
                 epoch: int = 1):
        from ..core.executor import Executor

        self.place = place
        self.scope = scope
        self.train_program = train_program
        self.startup_program = startup_program
        self.train_reader = train_reader
        self.train_fetch_list = list(train_fetch_list or [])
        self.eval_func = eval_func
        # student + spliced teacher + distill loss (+ optimizer), for
        # DistillationStrategy epochs (reference: teacher_programs arg)
        self.distill_program = distill_program
        self.epoch = int(epoch)
        self.strategies: List[Strategy] = []
        self.executor = Executor(place)
        # eval runs on a forward-only clone of the PERSISTENT student
        # program (never the distill graph — the student params live in
        # the shared scope, so evaluating the student is both correct
        # and teacher-free) so an eval or sensitivity probe can never
        # take an optimizer step; regenerated when a strategy mutates
        # the program, keeping only the latest version's clone
        self._eval_prog = None
        self._eval_prog_version = None

    def _eval_program(self) -> Program:
        ver = getattr(self.train_program, "_version", None)
        if self._eval_prog is None or self._eval_prog_version != ver:
            self._eval_prog = _strip_training_ops(self.train_program)
            self._eval_prog_version = ver
        return self._eval_prog

    # -- configuration (YAML path / YAML string / dict) ----------------------

    def config(self, config) -> "Compressor":
        if isinstance(config, str):
            import os

            import yaml

            if os.path.exists(config):
                text = open(config).read()
            elif "\n" in config or ":" in config:
                text = config        # inline YAML
            else:
                raise FileNotFoundError(
                    f"compressor config file not found: {config!r}")
            config = yaml.safe_load(text)
            if not isinstance(config, dict):
                raise ValueError(
                    "compressor config must parse to a mapping with "
                    "'strategies'/'compressor' sections")
        strategies = config.get("strategies", {}) or {}
        for name, spec in strategies.items():
            spec = dict(spec or {})
            cls_name = spec.pop("class", None) or name
            cls = _STRATEGY_TYPES.get(cls_name)
            if cls is None:
                raise ValueError(
                    f"unknown compression strategy '{cls_name}' "
                    f"(known: {sorted(_STRATEGY_TYPES)})")
            if cls is DistillationStrategy and self.distill_program is None:
                raise ValueError(
                    "DistillationStrategy configured but the Compressor "
                    "was built without distill_program= — fail now, not "
                    "after training up to its start_epoch")
            self.strategies.append(cls(**spec))
        comp = config.get("compressor", {}) or {}
        if "epoch" in comp:
            self.epoch = int(comp["epoch"])
        return self

    # -- the driver loop -----------------------------------------------------

    def _eval(self, ctx) -> Optional[float]:
        if self.eval_func is None:
            return None
        m = float(self.eval_func(self._eval_program(), self.executor,
                                 self.scope))
        ctx.eval_history.append(m)
        return m

    def run(self) -> CompressionContext:
        from ..core.executor import scope_guard

        ctx = CompressionContext(
            self.place, self.scope, self.train_program,
            self.startup_program, self.executor,
            eval_fn=lambda: (self.eval_func(self._eval_program(),
                                            self.executor, self.scope)
                             if self.eval_func else 0.0),
            has_eval=self.eval_func is not None,
            distill_program=self.distill_program)
        with scope_guard(self.scope):
            if self.startup_program is not None:
                self.executor.run(self.startup_program)
            for s in self.strategies:
                s.on_compression_begin(ctx)
            for e in range(self.epoch):
                ctx.epoch = e
                # reset each epoch: a swap (distillation) lasts exactly
                # as long as its strategy's hooks keep setting it
                ctx.active_program = ctx.train_program
                for s in self.strategies:
                    if s.start_epoch <= e <= s.end_epoch:
                        s.on_epoch_begin(ctx)
                if self.train_reader is not None:
                    for feed in self.train_reader():
                        self.executor.run(ctx.active_program, feed=feed,
                                          fetch_list=self.train_fetch_list)
                for s in self.strategies:
                    if s.start_epoch <= e <= s.end_epoch:
                        s.on_epoch_end(ctx)
                self._eval(ctx)
            # a swap covering the final epoch must not leak out of run():
            # the returned ctx and on_compression_end always see the
            # persistent student program as active
            ctx.active_program = ctx.train_program
            for s in self.strategies:
                s.on_compression_end(ctx)
        return ctx
