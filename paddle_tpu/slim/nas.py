"""Network architecture search: simulated-annealing controller + TCP
controller server / search agent.

Reference: contrib/slim/searcher/controller.py:59 SAController,
contrib/slim/nas/controller_server.py (socket server speaking
"tokens,...\\treward" lines) and nas/search_agent.py (client:
`update(tokens, reward)` → next tokens), light_nas_strategy.py wires them
into training. The same roles here: the server owns the SAController, N
distributed trainers pull candidate token vectors, train/eval them, and
report rewards.
"""

from __future__ import annotations

import math
import random
import socket
import threading
from typing import List, Optional, Sequence


class SAController:
    """Simulated annealing over integer token vectors
    (reference: controller.py:59 — reduce_rate, init_temperature)."""

    def __init__(self, range_table: Sequence[int],
                 reduce_rate: float = 0.85,
                 init_temperature: float = 1024.0,
                 max_iter_number: int = 300,
                 seed: Optional[int] = None):
        self.range_table = list(range_table)
        self.reduce_rate = reduce_rate
        self.init_temperature = init_temperature
        self.max_iter_number = max_iter_number
        self._rng = random.Random(seed)
        self._iter = 0
        self.tokens = [self._rng.randrange(r) for r in self.range_table]
        self.reward = -float("inf")
        self.best_tokens = list(self.tokens)
        self.best_reward = -float("inf")

    def next_tokens(self) -> List[int]:
        """Propose a neighbor of the current accepted tokens."""
        cand = list(self.tokens)
        idx = self._rng.randrange(len(cand))
        cand[idx] = self._rng.randrange(self.range_table[idx])
        return cand

    @property
    def is_finished(self) -> bool:
        return self._iter >= self.max_iter_number

    def update(self, tokens: Sequence[int], reward: float) -> bool:
        """Metropolis accept/reject; returns True if accepted. After
        max_iter_number updates the search is finished and further
        rewards are recorded for `best` only."""
        if self.is_finished:
            if reward > self.best_reward:
                self.best_reward = reward
                self.best_tokens = list(tokens)
            return False
        self._iter += 1
        temperature = self.init_temperature * \
            self.reduce_rate ** self._iter
        if reward > self.best_reward:
            self.best_reward = reward
            self.best_tokens = list(tokens)
        delta = reward - self.reward
        accept = delta > 0 or self._rng.random() < math.exp(
            min(delta / max(temperature, 1e-9), 0.0))
        if accept:
            self.tokens = list(tokens)
            self.reward = reward
        return accept


class ControllerServer:
    """TCP server owning a controller (reference:
    controller_server.py:28). Protocol (line per request):
      'next_tokens'              -> 'tok1,tok2,...'
      'update\\ttok1,...\\treward' -> 'ok <accepted> <best_reward>'
      'best'                     -> 'tok1,...\\tbest_reward'
      'close'                    -> shuts the server down
    """

    def __init__(self, controller: SAController, address=("127.0.0.1", 0),
                 max_client_num: int = 10):
        self._controller = controller
        self._socket = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._socket.bind(address)
        self._socket.listen(max_client_num)
        self._port = self._socket.getsockname()[1]
        self._ip = self._socket.getsockname()[0]
        self._closed = False
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def ip(self) -> str:
        return self._ip

    @property
    def port(self) -> int:
        return self._port

    def start(self):
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def run(self):
        while not self._closed:
            try:
                conn, _ = self._socket.accept()
            except OSError:
                break
            # one bad client must never kill the accept loop
            try:
                with conn:
                    chunks = []
                    while True:
                        b = conn.recv(65536)
                        if not b:
                            break
                        chunks.append(b)
                    try:
                        data = b"".join(chunks).decode("utf-8").strip()
                        resp = self._handle(data)
                    except Exception as e:  # malformed/non-UTF-8 request
                        resp = f"error {type(e).__name__}: {e}"
                    conn.sendall(resp.encode("utf-8"))
            except OSError:
                continue

    def _handle(self, data: str) -> str:
        with self._lock:
            if data == "next_tokens":
                return ",".join(map(str, self._controller.next_tokens()))
            if data == "best":
                return ",".join(map(str, self._controller.best_tokens)) + \
                    "\t" + repr(self._controller.best_reward)
            if data.startswith("update\t"):
                _, toks, reward = data.split("\t")
                tokens = [int(t) for t in toks.split(",")]
                accepted = self._controller.update(tokens, float(reward))
                return f"ok {int(accepted)} {self._controller.best_reward!r}"
            if data == "close":
                self.close()
                return "closed"
            return "error unknown request"

    def close(self):
        self._closed = True
        try:
            self._socket.close()
        except OSError:
            pass
        # join the accept loop so close() returning means the port is
        # actually released — EXCEPT when close() is called from the
        # serve thread itself (the "close" request arrives through
        # _handle, which runs ON self._thread; joining would self-wait)
        t = self._thread
        if t is not None and t is not threading.current_thread() \
                and t.is_alive():
            t.join(timeout=5.0)


class SearchAgent:
    """Client side (reference: search_agent.py:25)."""

    def __init__(self, server_ip: str, server_port: int):
        self.server_ip = server_ip
        self.server_port = server_port

    def _request(self, msg: str) -> str:
        with socket.create_connection((self.server_ip, self.server_port),
                                      timeout=30) as s:
            s.sendall(msg.encode("utf-8"))
            s.shutdown(socket.SHUT_WR)
            chunks = []
            while True:
                b = s.recv(65536)
                if not b:
                    break
                chunks.append(b)
        return b"".join(chunks).decode("utf-8")

    def next_tokens(self) -> List[int]:
        return [int(t) for t in self._request("next_tokens").split(",")]

    def update(self, tokens: Sequence[int], reward: float) -> bool:
        resp = self._request(
            "update\t" + ",".join(map(str, tokens)) + f"\t{reward!r}")
        return resp.startswith("ok 1")

    def best(self):
        toks, reward = self._request("best").split("\t")
        return [int(t) for t in toks.split(",")], float(reward)

    def close_server(self):
        self._request("close")
