"""Knowledge distillation.

Reference: contrib/slim/distillation/distiller.py — FSPDistiller (flow of
solution procedure matrices between feature-map pairs), L2Distiller
(feature L2), SoftLabelDistiller (temperature-softened KL), and
distillation_strategy.py (merge the teacher program into the student's so
one executor step computes both).

TPU-native: `merge` is a Program splice with a name prefix (one XLA
computation covers student+teacher — the compiler dedups shared input
loads); the teacher subgraph is marked stop_gradient so autodiff never
enters it.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

from ..core.framework import Program
from .. import layers


def merge(teacher_program: Program, student_program: Program,
          data_names: Optional[List[str]] = None,
          name_prefix: str = "teacher_") -> Dict[str, str]:
    """Splice the teacher's ops/vars into the student program under a
    prefix. Feed vars (data_names) are shared unprefixed. Returns the
    teacher var name map. Teacher vars are stop_gradient."""
    data_names = set(data_names or [])
    t_desc = teacher_program.global_block().desc
    s_desc = student_program.global_block().desc
    rename: Dict[str, str] = {}
    for name, var in t_desc.vars.items():
        if name in data_names:
            rename[name] = name
            continue
        new = name_prefix + name
        rename[name] = new
        v = copy.deepcopy(var)
        v.name = new
        v.stop_gradient = True
        s_desc.vars[new] = v
    for op in t_desc.ops:
        if op.type in ("feed", "fetch"):
            continue
        new_op = copy.deepcopy(op)
        new_op.inputs = {k: [rename.get(n, n) for n in v]
                         for k, v in op.inputs.items()}
        new_op.outputs = {k: [rename.get(n, n) for n in v]
                          for k, v in op.outputs.items()}
        s_desc.ops.append(new_op)
    student_program._rebuild_from_desc()
    return rename


def soft_label_loss(teacher_logits, student_logits,
                    teacher_temperature: float = 1.0,
                    student_temperature: float = 1.0):
    """KL(teacher softmax^T || student softmax^T) as cross entropy
    (reference: SoftLabelDistiller)."""
    t = layers.softmax(layers.scale(teacher_logits,
                                    scale=1.0 / teacher_temperature))
    s = layers.log_softmax(layers.scale(student_logits,
                                        scale=1.0 / student_temperature))
    neg = layers.scale(layers.elementwise_mul(t, s), scale=-1.0)
    return layers.mean(layers.reduce_sum(neg, dim=-1))


def l2_loss(teacher_feature, student_feature):
    """Feature-map L2 (reference: L2Distiller)."""
    diff = layers.elementwise_sub(student_feature, teacher_feature)
    return layers.mean(layers.elementwise_mul(diff, diff))


def _fsp_matrix(a, b):
    """FSP matrix of two feature maps [N, C1, H, W] x [N, C2, H, W] →
    [N, C1, C2] (reference: fsp op semantics — mean over spatial)."""
    c1 = int(a.shape[1])
    c2 = int(b.shape[1])
    h, w = int(a.shape[2]), int(a.shape[3])
    af = layers.reshape(a, [-1, c1, h * w])
    bf = layers.reshape(b, [-1, c2, h * w])
    prod = layers.matmul(af, layers.transpose(bf, perm=[0, 2, 1]))
    return layers.scale(prod, scale=1.0 / (h * w))


def fsp_loss(teacher_var1, teacher_var2, student_var1, student_var2):
    """L2 between teacher and student FSP matrices (reference:
    FSPDistiller)."""
    tm = _fsp_matrix(teacher_var1, teacher_var2)
    sm = _fsp_matrix(student_var1, student_var2)
    return l2_loss(tm, sm)


def init_teacher_scope(scope, rename: Dict[str, str]):
    """Copy the teacher's initialized variables to their prefixed names in
    `scope` (reference: DistillationStrategy merges the teacher scope into
    the student's on_compression_begin)."""
    for orig, new in rename.items():
        if orig == new:
            continue
        val = scope.find_var(orig)
        if val is not None:
            scope.set_var(new, val)
