"""Module alias: `paddle_tpu.backward` mirrors the reference's
python/paddle/fluid/backward.py public surface."""

from .core.backward import append_backward, gradients  # noqa: F401

__all__ = ["append_backward", "gradients"]
