"""DatasetLoader (reference: reader.py:990 DatasetLoader — iterate a
Dataset's batches through the loader interface)."""

from __future__ import annotations


class DatasetLoader:
    def __init__(self, dataset, places=None, drop_last=True):
        self._dataset = dataset
        self._drop_last = drop_last

    def __iter__(self):
        yield from self._dataset._iter_batches()
