"""DatasetLoader (reference: reader.py:990 DatasetLoader — iterate a
Dataset's batches through the loader interface)."""

from __future__ import annotations


class DatasetLoader:
    """With `use_double_buffer`, batches are staged ahead of the
    consumer by a bounded background thread (core/async_exec): a
    `jax.device_put` stage (sharded over the active SPMD mesh) when
    `async_exec.device_prefetch_wanted` says so — accelerator places,
    or a PADDLE_TPU_DEVICE_PREFETCH=1 override, the same gate
    GeneratorLoader applies — and a host-side stage otherwise, so CPU
    consumers keep getting mutable numpy without a transfer that has
    nothing to hide."""

    def __init__(self, dataset, places=None, drop_last=True,
                 use_double_buffer=False, prefetch_depth=2):
        self._dataset = dataset
        self._places = places
        self._drop_last = drop_last
        self._use_double_buffer = bool(use_double_buffer)
        self._prefetch_depth = max(1, int(prefetch_depth))

    def __iter__(self):
        from .core.async_exec import (DevicePrefetcher, Prefetcher,
                                      device_prefetch_wanted)

        want_device = device_prefetch_wanted(self._places,
                                             self._use_double_buffer)
        if not (self._use_double_buffer or want_device):
            yield from self._dataset._iter_batches()
            return
        src = self._dataset._iter_batches()
        pf = DevicePrefetcher(src, depth=self._prefetch_depth) \
            if want_device \
            else Prefetcher(src, depth=self._prefetch_depth, stage="host")
        try:
            yield from pf
        finally:
            pf.close()
