"""Python-side metrics (reference: python/paddle/fluid/metrics.py)."""

from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "Accuracy", "ChunkEvaluator", "EditDistance",
           "Auc", "Precision", "Recall", "CompositeMetric",
           "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if isinstance(v, (int, float)):
                setattr(self, k, 0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        return self.tp / max(self.tp + self.fp, 1)


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        return self.tp / max(self.tp + self.fn, 1)


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_score = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bucket = np.clip((pos_score * self._num_thresholds).astype(int), 0,
                         self._num_thresholds)
        for b, l in zip(bucket, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos * tot_neg == 0:
            return 0.0
        tp0 = np.concatenate([[0], tp[:-1]])
        fp0 = np.concatenate([[0], fp[:-1]])
        return float(np.sum((fp - fp0) * (tp + tp0) / 2.0) / (tot_pos * tot_neg))


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data updated")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        precision = self.num_correct_chunks / max(self.num_infer_chunks, 1)
        recall = self.num_correct_chunks / max(self.num_label_chunks, 1)
        f1 = 2 * precision * recall / max(precision + recall, 1e-6)
        return precision, recall, f1


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class DetectionMAP(MetricBase):
    """Mean average precision for detection (reference: metrics.py:695
    DetectionMAP + operators/detection_map_op; 11-point or integral AP).

    update() takes per-image detections [[label, score, x1,y1,x2,y2], ...]
    (the multiclass_nms output rows) and ground truth
    [[label, x1,y1,x2,y2], ...]."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 ap_version="integral", evaluate_difficult=False):
        super().__init__(name)
        assert ap_version in ("integral", "11point")
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.evaluate_difficult = evaluate_difficult
        self.reset()

    def reset(self, executor=None, program=None):
        self._dets = []       # (img_id, label, score, box)
        self._gts = []        # (img_id, label, box)
        self._img = 0

    @staticmethod
    def _iou(a, b):
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        iw, ih = max(ix2 - ix1, 0.0), max(iy2 - iy1, 0.0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1]) +
              (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gts):
        """gt rows: [label, x1,y1,x2,y2] or [label, x1,y1,x2,y2,
        difficult]."""
        for d in detections:
            if d[0] < 0:
                continue
            self._dets.append((self._img, int(d[0]), float(d[1]),
                               tuple(float(v) for v in d[2:6])))
        for g in gts:
            difficult = bool(g[5]) if len(g) > 5 else False
            self._gts.append((self._img, int(g[0]),
                              tuple(float(v) for v in g[1:5]), difficult))
        self._img += 1

    def eval(self, executor=None, program=None):
        import collections

        labels = {g[1] for g in self._gts}
        aps = []
        for lab in sorted(labels):
            gts = collections.defaultdict(list)
            npos = 0
            for img, gl, box, difficult in self._gts:
                if gl == lab:
                    hard = difficult and not self.evaluate_difficult
                    gts[img].append([box, False, hard])
                    if not hard:
                        npos += 1
            dets = sorted((d for d in self._dets if d[1] == lab),
                          key=lambda d: -d[2])
            tp, fp = [], []
            for img, _, score, box in dets:
                best, best_g = 0.0, None
                for g in gts.get(img, []):
                    i = self._iou(box, g[0])
                    if i > best:
                        best, best_g = i, g
                if best >= self.overlap_threshold and \
                        best_g is not None:
                    if best_g[2]:
                        continue  # difficult gt: neither tp nor fp (VOC)
                    if not best_g[1]:
                        best_g[1] = True
                        tp.append(1.0)
                        fp.append(0.0)
                    else:
                        tp.append(0.0)
                        fp.append(1.0)
                else:
                    tp.append(0.0)
                    fp.append(1.0)
            if npos == 0:
                continue
            tp = np.cumsum(tp) if tp else np.zeros(0)
            fp = np.cumsum(fp) if fp else np.zeros(0)
            rec = tp / npos if len(tp) else np.zeros(0)
            prec = tp / np.maximum(tp + fp, 1e-9) if len(tp) else \
                np.zeros(0)
            if self.ap_version == "11point":
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                    ap += p / 11.0
            else:
                ap = 0.0
                mrec = np.concatenate([[0.0], rec, [1.0]])
                mpre = np.concatenate([[0.0], prec, [0.0]])
                for i in range(len(mpre) - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = float(np.sum((mrec[idx + 1] - mrec[idx]) *
                                  mpre[idx + 1]))
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
