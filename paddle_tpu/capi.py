"""ctypes bindings for the native C++ inference predictor
(native/src/predictor.cc).

Reference: paddle/fluid/inference/capi/c_api.h — the C deployment ABI
over the C++ predictor. Same role here: `NativePredictor` loads a saved
inference model (io.save_inference_model output) and runs it with the
native interpreter, no Python/JAX in the serving path beyond this thin
ctypes veneer (a pure-C client calls the PD_* symbols directly).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Sequence

import numpy as np

from .native_build import LIB_DIR, SRC_DIR, build_and_load

_SRC = os.path.join(SRC_DIR, "predictor.cc")
_LIB = os.path.join(LIB_DIR, "libptpred.so")

_lib = None
_lib_lock = threading.Lock()


def get_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = build_and_load(_SRC, _LIB, ["-O2"])
        lib.PD_NewPredictor.restype = ctypes.c_void_p
        lib.PD_NewPredictor.argtypes = [ctypes.c_char_p]
        lib.PD_DeletePredictor.argtypes = [ctypes.c_void_p]
        lib.PD_GetError.restype = ctypes.c_char_p
        lib.PD_GetError.argtypes = [ctypes.c_void_p]
        lib.PD_GetInputNum.argtypes = [ctypes.c_void_p]
        lib.PD_GetOutputNum.argtypes = [ctypes.c_void_p]
        lib.PD_GetInputName.restype = ctypes.c_char_p
        lib.PD_GetInputName.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.PD_GetOutputName.restype = ctypes.c_char_p
        lib.PD_GetOutputName.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.PD_PredictorRun.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int]
        lib.PD_GetOutputNdim.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.PD_GetOutputShape.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
        lib.PD_GetOutputDtype.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.PD_GetOutputData.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.c_void_p]
        lib.PD_SupportedOps.restype = ctypes.c_char_p
        lib.PD_SupportedOps.argtypes = []
        _lib = lib
        return _lib


def supported_ops() -> List[str]:
    """The native engine's supported-op manifest, emitted from the C++
    dispatch table itself (PD_SupportedOps) so it cannot drift from what
    the interpreter executes."""
    return get_lib().PD_SupportedOps().decode().split(",")


def native_lib_path() -> str:
    """Path to the built libptpred.so (builds on first use) — handed to
    pure-C clients such as native/src/demo_trainer.c."""
    get_lib()
    return _LIB


class NativePredictor:
    """C++-interpreted inference over a saved model directory."""

    def __init__(self, model_dir: str):
        self._lib = get_lib()
        self._h = self._lib.PD_NewPredictor(model_dir.encode())
        err = self._lib.PD_GetError(self._h)
        if err:
            msg = err.decode()
            self._lib.PD_DeletePredictor(self._h)
            self._h = None
            raise RuntimeError(f"NativePredictor: {msg}")

    @property
    def input_names(self) -> List[str]:
        return [self._lib.PD_GetInputName(self._h, i).decode()
                for i in range(self._lib.PD_GetInputNum(self._h))]

    @property
    def output_names(self) -> List[str]:
        return [self._lib.PD_GetOutputName(self._h, i).decode()
                for i in range(self._lib.PD_GetOutputNum(self._h))]

    def run(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        names = list(feed)
        arrays = []
        for n in names:
            a = np.ascontiguousarray(feed[n])
            if a.dtype == np.float64:
                a = a.astype(np.float32)
            if a.dtype in (np.int32, np.int16):
                a = a.astype(np.int64)
            if a.dtype not in (np.float32, np.int64):
                raise TypeError(f"unsupported feed dtype {a.dtype}")
            arrays.append(a)
        n = len(names)
        c_names = (ctypes.c_char_p * n)(*[s.encode() for s in names])
        c_datas = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays])
        shapes = [np.asarray(a.shape, np.int64) for a in arrays]
        c_shapes = (ctypes.POINTER(ctypes.c_int64) * n)(
            *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
              for s in shapes])
        c_ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays])
        c_dtypes = (ctypes.c_int * n)(
            *[0 if a.dtype == np.float32 else 1 for a in arrays])
        rc = self._lib.PD_PredictorRun(self._h, c_names, c_datas, c_shapes,
                                       c_ndims, c_dtypes, n)
        if rc != 0:
            raise RuntimeError(
                f"native run failed: "
                f"{self._lib.PD_GetError(self._h).decode()}")
        outs = []
        for i in range(self._lib.PD_GetOutputNum(self._h)):
            nd = self._lib.PD_GetOutputNdim(self._h, i)
            shape = np.zeros(nd, np.int64)
            self._lib.PD_GetOutputShape(
                self._h, i, shape.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)))
            if self._lib.PD_GetOutputDtype(self._h, i) == 0:
                buf = np.zeros(tuple(shape), np.float32)
            else:
                buf = np.zeros(tuple(shape), np.int64)
            self._lib.PD_GetOutputData(
                self._h, i, buf.ctypes.data_as(ctypes.c_void_p))
            outs.append(buf)
        return outs

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.PD_DeletePredictor(self._h)
            self._h = None
