"""Shared build-on-first-use helper for the native libraries.

One implementation of the compile-then-load dance used by capi.py
(libptpred), io_native.py (libptio) and ps/native_opt.py (libptpsopt):
g++ the single source file into native/build/ when the .so is missing or
older than its source, writing to a temp path and os.replace()-ing so a
concurrent first-use in another process can never load a half-written
library (os.replace is atomic on POSIX)."""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Sequence

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB_DIR = os.path.join(_REPO, "native", "build")
SRC_DIR = os.path.join(_REPO, "native", "src")


def build_and_load(src: str, lib_path: str,
                   extra_flags: Sequence[str] = ()) -> ctypes.CDLL:
    """Compile `src` into `lib_path` when missing/stale, then CDLL it.

    Raises on compile failure. A load failure of an up-to-date file
    triggers ONE rebuild (covers a partially-written .so from a crashed
    earlier build) before propagating."""
    os.makedirs(os.path.dirname(lib_path), exist_ok=True)

    def build():
        tmp = f"{lib_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-shared", "-fPIC", "-std=c++17", *extra_flags,
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{e.stderr}") from e
        os.replace(tmp, lib_path)

    if not os.path.exists(lib_path) or (
            os.path.getmtime(lib_path) < os.path.getmtime(src)):
        build()
    try:
        return ctypes.CDLL(lib_path)
    except OSError:
        build()  # e.g. a truncated .so left by a crashed writer
        return ctypes.CDLL(lib_path)
