"""Shared build-on-first-use helper for the native libraries.

One implementation of the compile-then-load dance used by capi.py
(libptpred), io_native.py (libptio) and ps/native_opt.py (libptpsopt):
g++ the single source file into native/build/ when the .so is missing or
older than its source, writing to a temp path and os.replace()-ing so a
concurrent first-use in another process can never load a half-written
library (os.replace is atomic on POSIX)."""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Sequence

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB_DIR = os.path.join(_REPO, "native", "build")
SRC_DIR = os.path.join(_REPO, "native", "src")


def _build_stamp(src: str, extra_flags: Sequence[str]) -> str:
    """Staleness key: source bytes + flags + host CPU model. The CPU model
    matters because callers pass ``-march=native`` — a cached .so reused
    on a different CPU would SIGILL at first call, which the
    load-failure rebuild below cannot catch (the load succeeds)."""
    import hashlib

    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    h.update("\0".join(extra_flags).encode())
    try:
        seen = set()
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                if key in ("model name", "flags") and key not in seen:
                    seen.add(key)  # first core's entry is enough
                    h.update(line.encode())
                if len(seen) == 2:
                    break
    except OSError:
        import platform

        h.update(platform.processor().encode())
    return h.hexdigest()


def build_and_load(src: str, lib_path: str,
                   extra_flags: Sequence[str] = ()) -> ctypes.CDLL:
    """Compile `src` into `lib_path` when missing/stale, then CDLL it.

    Staleness covers source content, compile flags, and host CPU (see
    _build_stamp), recorded in a sidecar ``.stamp`` file — an mtime-only
    check would happily reuse a ``-march=native`` .so on a different
    machine or after a flag change. Raises on compile failure. A load
    failure of an up-to-date file triggers ONE rebuild (covers a
    partially-written .so from a crashed earlier build) before
    propagating."""
    os.makedirs(os.path.dirname(lib_path), exist_ok=True)
    stamp_path = lib_path + ".stamp"
    want = _build_stamp(src, extra_flags)

    def build():
        tmp = f"{lib_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-shared", "-fPIC", "-std=c++17", *extra_flags,
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{e.stderr}") from e
        os.replace(tmp, lib_path)
        stamp_tmp = f"{stamp_path}.tmp.{os.getpid()}"
        with open(stamp_tmp, "w") as f:  # atomic-exempt: tmp file, os.replace'd below
            f.write(want)
        os.replace(stamp_tmp, stamp_path)

    def stamp_ok():
        try:
            with open(stamp_path) as f:
                return f.read().strip() == want
        except OSError:
            return False

    if not os.path.exists(lib_path) or not stamp_ok():
        build()
    try:
        return ctypes.CDLL(lib_path)
    except OSError:
        build()  # e.g. a truncated .so left by a crashed writer
        return ctypes.CDLL(lib_path)
