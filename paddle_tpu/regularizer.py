"""Regularizers (reference: python/paddle/fluid/regularizer.py) — append
penalty-gradient ops onto each param's grad."""

from __future__ import annotations

from .core.framework import OpRole, default_main_program, op_role_guard, unique_name

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(name=unique_name.generate("l2_decay"),
                                 shape=param.shape, dtype=param.dtype)
        block.append_op(type="scale", inputs={"X": param}, outputs={"Out": decay},
                        attrs={"scale": self._coeff})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(name=unique_name.generate("l1_sign"),
                                shape=param.shape, dtype=param.dtype)
        block.append_op(type="sign", inputs={"X": param}, outputs={"Out": sign})
        decay = block.create_var(name=unique_name.generate("l1_decay"),
                                 shape=param.shape, dtype=param.dtype)
        block.append_op(type="scale", inputs={"X": sign}, outputs={"Out": decay},
                        attrs={"scale": self._coeff})
        return decay


def append_regularization_ops(params_grads, regularization=None):
    """reference: regularizer.py append_regularization_ops — per-param
    regularizer overrides the global one."""
    out = []
    block = default_main_program().global_block()
    with op_role_guard(OpRole.Backward):
        for param, grad in params_grads:
            reg = getattr(param, "regularizer", None) or regularization
            if reg is None or grad is None:
                out.append((param, grad))
                continue
            decay = reg(param, grad, block)
            new_grad = block.create_var(
                name=unique_name.generate(grad.name + "_reg"),
                shape=grad.shape, dtype=grad.dtype)
            block.append_op(type="elementwise_add", inputs={"X": grad, "Y": decay},
                            outputs={"Out": new_grad})
            out.append((param, new_grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
