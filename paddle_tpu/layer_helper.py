"""LayerHelper (reference: python/paddle/fluid/layer_helper.py:42) — shared
machinery for layers: parameter creation (init ops go to the startup
program), bias/activation appending, dtype plumbing."""

from __future__ import annotations

from typing import Optional

from .core import framework
from .core.framework import Variable, unique_name
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return framework.default_main_program()

    @property
    def startup_program(self):
        return framework.default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def create_variable_for_type_inference(self, dtype, stop_gradient=False) -> Variable:
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, stop_gradient=stop_gradient)

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None, stop_gradient=False):
        attr = ParamAttr._to_attr(attr)
        if attr is False or (isinstance(attr, ParamAttr) and not attr.trainable and attr.name is None
                             and attr.initializer is None and is_bias and self.kwargs.get("bias_attr") is False):
            return None
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(".".join([self.name, suffix]))
        if default_initializer is None:
            default_initializer = (ConstantInitializer(0.0) if is_bias
                                   else XavierInitializer())
        init = attr.initializer or default_initializer

        # main-program parameter (the var the ops read)
        param = self.main_program.global_block().create_parameter(
            name=name, shape=shape, dtype=dtype, trainable=attr.trainable,
            optimize_attr={"learning_rate": attr.learning_rate},
            regularizer=attr.regularizer, do_model_average=attr.do_model_average,
            need_clip=attr.need_clip)
        # startup-program twin + its init op (reference: LayerHelper
        # startup_program parameter creation)
        sb = self.startup_program.global_block()
        if not sb.has_var(name):
            svar = sb.create_parameter(
                name=name, shape=shape, dtype=dtype, trainable=attr.trainable)
            init(svar, sb)
        return param

    def get_parameter(self, name):
        return self.main_program.global_block().var(name)

    # -- common layer tails --------------------------------------------------

    def append_bias_op(self, input_var: Variable, dim_start=1, bias_attr=None,
                       num_flatten_dims=None) -> Variable:
        bias_attr = bias_attr if bias_attr is not None else self.kwargs.get("bias_attr")
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:])
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        if b is None:
            return input_var
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            type="elementwise_add", inputs={"X": input_var, "Y": b},
            outputs={"Out": out}, attrs={"axis": dim_start})
        return out

    def append_activation(self, input_var: Variable, act: Optional[str] = None) -> Variable:
        act = act if act is not None else self.kwargs.get("act")
        if act is None:
            return input_var
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type=act, inputs={"X": input_var}, outputs={"Out": out})
        return out

    def input_dtype(self, input_param_name="input"):
        val = self.kwargs.get(input_param_name)
        if isinstance(val, (list, tuple)):
            val = val[0]
        return val.dtype
