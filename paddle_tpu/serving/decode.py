"""Continuous-batching autoregressive decode engine.

The PR 3 serving stack buckets fixed-shape one-shot predicts; token
generation through it would re-run the full forward per token. This
module is the token-serving half the ROADMAP calls the flagship
workload: a decode engine that composes the substrate the repo already
owns —

- **paged KV cache** (kv_cache.py): fixed-size blocks in ONE
  preallocated device pool, per-sequence block tables, blocks
  allocated on admit / freed on finish, so HBM scales with live
  tokens, not max_seq_len × batch;
- **continuous (in-flight) batching** (ORCA OSDI'22): the scheduler
  admits new requests into the RUNNING decode batch every step and
  retires finished ones without draining it;
- **prefill/decode phase split**: prompts run through per-length
  prefill buckets (the existing BucketPolicy idea applied to sequence
  length), decode always runs at one of a few fixed slot counts — so
  the whole phase grid is a small closed signature set that is
  AOT-warmed once, pre-baked into a PR 6 warmstart artifact
  (`export_warmstart`/`load_warmstart`, `tools/warmstart.py
  bake-decode`), and replayed at boot with zero fresh compiles;
- **lazy token fetches** (PR 5 FetchHandle): each decode step's
  sampled tokens resolve one step LATE — step N dispatches with step
  N-1's tokens still device-resident, so the host never blocks the
  device between steps while the batch composition is stable;
- **PR 7 precision policies**: bf16 decode by default (pools + compute
  dtype), f32 opt-in for exactness; the policy is part of every
  executable's signature and persistent-cache fingerprint;
- **PR 8 boot validation**: config + trace findings in the analysis
  Finding shape, PADDLE_TPU_VALIDATE=2 refuses to serve a broken grid.

Sampling is greedy (beam_size=1) through `ops/beam.beam_search`, whose
finished-freeze semantics keep an ended slot emitting eos without
host-side branching. When the pool runs dry mid-decode, the youngest
active sequence is preempted vLLM-style: blocks freed, request
re-queued with prompt+generated-so-far, re-prefilled later (already
streamed tokens are not re-emitted).
"""

from __future__ import annotations

import collections
import hashlib
import pickle
import queue
import threading
import time
import weakref
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import compile_cache as _cc
from ..core import precision as _precision
from ..core.async_exec import FetchHandle
from ..core.executor import _JitDispatch
from ..observability import events as _events
from ..observability import memwatch as _memwatch
from ..observability import metrics as _m
from ..observability import perfwatch as _perfwatch
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing
from .batcher import QueueFullError, ServerClosed
from .kv_cache import (BlockAllocator, KVCacheConfig, NoBlocksError,
                       build_block_table, init_pools)
from . import kv_reuse as _kvr
from .kv_reuse import ReuseBlockAllocator

if TYPE_CHECKING:  # runtime import is deferred (package bootstrap)
    from .qos import WeightedFairScheduler

__all__ = ["DecodeConfig", "DecodeEngine", "DecodeHandle",
           "DECODE_WARMSTART_FORMAT"]

DECODE_WARMSTART_FORMAT = "paddle_tpu-decode-warmstart-v1"

QUEUE_DEPTH = _m.gauge(
    "paddle_tpu_decode_queue_depth",
    "Requests waiting for a decode slot")
SLOTS = _m.gauge(
    "paddle_tpu_decode_slots",
    "Decode slots (state=active|configured)", labelnames=("state",))
KV_BLOCKS = _m.gauge(
    "paddle_tpu_decode_kv_blocks",
    "KV-cache pool blocks (state=used|free)", labelnames=("state",))
TTFT_SECONDS = _m.histogram(
    "paddle_tpu_decode_ttft_seconds",
    "Submit-to-first-token latency (prefill completion)")
STEP_SECONDS = _m.histogram(
    "paddle_tpu_decode_step_seconds",
    "Wall seconds per decode step (dispatch N to dispatch N+1)")
TOKENS = _m.counter(
    "paddle_tpu_decode_tokens_total",
    "Tokens sampled (phase=prefill|decode)", labelnames=("phase",))
STEPS = _m.counter(
    "paddle_tpu_decode_steps_total",
    "Phase executions (phase=prefill|decode|draft|verify)",
    labelnames=("phase",))
REQUESTS = _m.counter(
    "paddle_tpu_decode_requests_total",
    "Finished requests by outcome (eos|length|rejected|cancelled|error)",
    labelnames=("outcome",))
PREEMPTIONS = _m.counter(
    "paddle_tpu_decode_preemptions_total",
    "Sequences preempted back to the queue on KV-pool pressure")
OCCUPANCY = _m.histogram(
    "paddle_tpu_decode_slot_occupancy",
    "Active slots / compiled slot count per decode step",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))


def _pow2_lengths(lo: int, hi: int) -> Tuple[int, ...]:
    out, b = [], int(lo)
    while b < hi:
        out.append(b)
        b *= 2
    out.append(int(hi))
    return tuple(sorted(set(out)))


class DecodeConfig:
    """Knobs for the decode engine (SERVING.md §Continuous batching).

    decode_slots: the fixed slot counts decode executables exist for;
    each step runs at the smallest config >= live sequences.
    prefill_buckets: prompt-length buckets (pow2 from 8 up to max_len
    by default); a prompt pads to the smallest bucket that fits.
    num_blocks/block_size: the KV pool (block 0 is the null block).
    static_batching=True turns the scheduler into the drain-between-
    batches baseline (admit only into an EMPTY batch) — the A/B
    `tools/serve_bench.py --tokens` measures against.

    KV-reuse knobs (SERVING.md §KV reuse): prefill_chunk > 0 replaces
    the prefill-bucket grid with ONE fixed-size chunk executable —
    prompts prefill in slices interleaved with decode steps;
    prefix_cache=True (requires prefill_chunk) makes the allocator
    ref-counted with a content-hash index so shared prompt prefixes
    resolve to live pool blocks; spec_k > 0 (requires a draft model
    passed to DecodeEngine) proposes k tokens per step through the
    draft and verifies them in one batched target step with exact
    greedy accept/reject. Any of these switches the engine onto the
    synchronous reuse scheduler (no lazy-fetch overlap)."""

    def __init__(self, *, block_size: int = 16, num_blocks: int = 64,
                 decode_slots: Sequence[int] = (4, 8),
                 prefill_buckets: Optional[Sequence[int]] = None,
                 max_len: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 max_queue: int = 64,
                 precision: str = "bf16",
                 static_batching: bool = False,
                 warmstart: Optional[str] = None,
                 prefix_cache: bool = False,
                 prefill_chunk: int = 0,
                 spec_k: int = 0,
                 qos=None,
                 model_tag: Optional[str] = None):
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.decode_slots = tuple(sorted({int(s) for s in decode_slots}))
        self.prefill_buckets = tuple(sorted({int(b) for b in
                                             prefill_buckets})) \
            if prefill_buckets is not None else None
        self.max_len = max_len
        self.eos_id = eos_id
        self.max_queue = int(max_queue)
        self.precision = str(precision)
        self.static_batching = bool(static_batching)
        self.warmstart = warmstart
        self.prefix_cache = bool(prefix_cache)
        self.prefill_chunk = int(prefill_chunk)
        self.spec_k = int(spec_k)
        # per-tenant QoS policy (a qos.QoSPolicy or its from_spec dict;
        # None = single-tenant FIFO) — SERVING.md §Multi-tenancy
        self.qos = qos
        # memwatch owner suffix for multi-model processes: with
        # model_tag="m", HBM providers register as "kv_pool[m]" etc. so
        # per-model KV/param footprints stay attributable while sharing
        # one process budget
        self.model_tag = model_tag
        if self.prefill_chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, got "
                             f"{self.prefill_chunk}")
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {self.spec_k}")
        if self.prefix_cache and not self.prefill_chunk:
            raise ValueError(
                "prefix_cache=True requires prefill_chunk > 0: reused "
                "prefixes start the computed suffix mid-prompt, which "
                "only the chunked (gather-attention) prefill program "
                "supports")


class DecodeHandle:
    """Client side of one generation: a thread-safe token stream.

    `tokens()` yields token ids as the scheduler emits them and ends
    when the request finishes; `result(timeout_s)` collects them all.
    `info` fills in as generation progresses (ttft_s, finish_reason,
    n_tokens)."""

    def __init__(self, req: "_Request"):
        self._req = req

    @property
    def info(self) -> Dict:
        r = self._req
        return {
            "prompt_len": int(r.prompt_len0),
            "n_tokens": len(r.generated),
            "ttft_s": (r.t_first - r.t_submit) if r.t_first else None,
            "finish_reason": r.finish_reason,
        }

    def tokens(self, timeout_s: Optional[float] = None):
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        while True:
            left = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            try:
                item = self._req.events.get(timeout=left)
            except queue.Empty:
                raise TimeoutError(
                    f"generation produced no token within {timeout_s}s")
            if item is None:
                if self._req.error is not None:
                    raise self._req.error
                return
            yield item

    def result(self, timeout_s: Optional[float] = None) -> List[int]:
        return list(self.tokens(timeout_s=timeout_s))


class _Request:
    __slots__ = ("rid", "prompt", "prompt_len0", "max_new", "generated",
                 "events", "t_submit", "t_first", "finish_reason",
                 "error", "cancelled", "last_token", "pos", "blocks",
                 "admitted_at", "tctx", "enqueued_at",
                 "prefill_pos", "draft_pos", "n_reused", "hashes",
                 "tenant")

    def __init__(self, rid: int, prompt: np.ndarray, max_new: int,
                 tenant: str = "default"):
        self.tenant = tenant
        self.rid = rid
        # captured on the submitter's thread; the scheduler thread
        # records queue-wait/prefill/TTFT spans against it later
        self.tctx = _tracing.current_trace()
        self.prompt = prompt                   # grows on preempt-replay
        self.prompt_len0 = len(prompt)         # original, for reporting
        self.max_new = int(max_new)
        self.generated: List[int] = []
        self.events: "queue.Queue" = queue.Queue()
        self.t_submit = time.monotonic()
        self.enqueued_at = self.t_submit   # re-stamped on preempt requeue
        self.t_first: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False
        # slot state (meaningful while active)
        self.last_token = 0
        self.pos = 0                           # next KV write position
        self.blocks: List[int] = []
        self.admitted_at = 0.0
        # KV-reuse state (chunked prefill / prefix cache / speculation)
        self.prefill_pos = 0     # next prompt position to chunk-prefill
        self.draft_pos = 0       # next DRAFT KV write position
        self.n_reused = 0        # prefix blocks resolved from the cache
        self.hashes = None       # chain hashes of the prompt's blocks


class _Pending:
    """One in-flight decode step: the lazy token fetch plus the exact
    batch composition it was dispatched with."""

    __slots__ = ("handle", "tok_dev", "snapshot", "slots", "t_dispatch")

    def __init__(self, handle, tok_dev, snapshot, slots):
        self.handle = handle
        self.tok_dev = tok_dev
        self.snapshot = snapshot               # tuple of rids (padded -1)
        self.slots = slots                     # list of Optional[_Request]
        self.t_dispatch = time.perf_counter()


class DecodeEngine:
    """Continuous-batching token generation over a paged KV cache.

    Built from in-memory model state: `params`/`model_cfg` from
    `models.gpt` (dense configs only). `submit()` is thread-safe and
    reject-not-block (QueueFullError when `max_queue` prompts wait);
    one scheduler thread owns the device pools, the allocator, and
    every phase dispatch."""

    def __init__(self, params, model_cfg, config: Optional[DecodeConfig]
                 = None, draft=None):
        from ..models import gpt as _gpt

        self.config = config or DecodeConfig()
        self.model_cfg = model_cfg
        self.prefill_chunk = int(getattr(self.config, "prefill_chunk",
                                         0))
        self.spec_k = int(getattr(self.config, "spec_k", 0))
        if self.spec_k and draft is None:
            raise ValueError(
                "spec_k > 0 requires a draft model: pass "
                "DecodeEngine(..., draft=(draft_params, draft_cfg))")
        if draft is not None and not self.spec_k:
            raise ValueError(
                "a draft model was passed but spec_k == 0; set "
                "DecodeConfig(spec_k=k) to enable speculation")
        # any reuse feature runs the synchronous scheduler (_loop_sync)
        self._sync = bool(self.prefill_chunk or self.spec_k)
        if self.config.precision not in ("f32", "bf16"):
            _precision.get_policy(self.config.precision)  # typo => full msg
            raise ValueError(
                f"unsupported decode precision "
                f"{self.config.precision!r}; choose from ['f32', 'bf16']")
        policy = _precision.get_policy(
            "bf16" if self.config.precision == "bf16" else "f32")
        self._compute_dtype = policy.compute_dtype or np.dtype("float32")
        self.params = {
            k: _precision.cast_floating(v, self._compute_dtype)
            for k, v in params.items()}
        max_len = int(self.config.max_len or model_cfg.max_len)
        self.kv_cfg = KVCacheConfig(
            layers=model_cfg.layers, kv_heads=model_cfg.heads,
            head_dim=model_cfg.head_dim, max_len=max_len,
            block_size=self.config.block_size,
            num_blocks=self.config.num_blocks,
            dtype=str(np.dtype(self._compute_dtype)))
        # resolved grid lives on the ENGINE, never written back into
        # the caller's config (a DecodeConfig reused across engines
        # must not carry the first engine's derived bucket set)
        self.prefill_buckets = self.config.prefill_buckets \
            if self.config.prefill_buckets is not None \
            else _pow2_lengths(min(8, max_len), max_len)
        self.decode_slots = self.config.decode_slots
        self.eos_id = -1 if self.config.eos_id is None \
            else int(self.config.eos_id)

        # -- draft model (speculative decoding) -----------------------
        # its pools share num_blocks/block_size/max_len with the target
        # so BLOCK TABLES ARE SHARED: one allocation covers both models
        # and prefix-cache hits resolve both models' prompt KV at once
        self._draft = draft
        self._draft_params = None
        self._draft_cfg = None
        self._draft_kv_cfg = None
        if draft is not None:
            draft_params, draft_cfg = draft
            self._draft_cfg = draft_cfg
            self._draft_params = {
                k: _precision.cast_floating(v, self._compute_dtype)
                for k, v in draft_params.items()}
            self._draft_kv_cfg = KVCacheConfig(
                layers=draft_cfg.layers, kv_heads=draft_cfg.heads,
                head_dim=draft_cfg.head_dim, max_len=max_len,
                block_size=self.config.block_size,
                num_blocks=self.config.num_blocks,
                dtype=str(np.dtype(self._compute_dtype)))

        # -- phase grid: one dispatcher per (phase, size) -------------
        bs = self.kv_cfg.block_size
        pol = None if self.config.precision == "f32" \
            else self.config.precision

        def _prefill_fn(p, ids, length, kp, vp, bt):
            return _gpt.apply_prefill(p, model_cfg, ids, length, kp, vp,
                                      bt, block_size=bs,
                                      eos_id=self.eos_id)

        def _decode_fn(p, ids, positions, kp, vp, bts):
            return _gpt.apply_decode_step(p, model_cfg, ids, positions,
                                          kp, vp, bts, block_size=bs,
                                          eos_id=self.eos_id)

        def _chunk_fn(p, ids, start, length, kp, vp, bt):
            return _gpt.apply_prefill_chunk(
                p, model_cfg, ids, start, length, kp, vp, bt,
                block_size=bs, eos_id=self.eos_id)

        # chunked prefill COLLAPSES the prompt-length bucket dimension:
        # the grid carries one chunk executable instead of one program
        # per bucket (warmstart artifacts re-key accordingly)
        self._chunk: Dict[int, _JitDispatch] = {}
        self._prefill: Dict[int, _JitDispatch] = {}
        if self.prefill_chunk:
            self._chunk = {
                self.prefill_chunk: _JitDispatch(
                    jax.jit(_chunk_fn, donate_argnums=(4, 5)),
                    "prefill", meta={"chunk": self.prefill_chunk},
                    policy=pol)}
        else:
            self._prefill = {
                t: _JitDispatch(jax.jit(_prefill_fn,
                                        donate_argnums=(3, 4)),
                                "prefill", meta={"bucket": int(t)},
                                policy=pol)
                for t in self.prefill_buckets}
        self._decode: Dict[int, _JitDispatch] = {
            s: _JitDispatch(jax.jit(_decode_fn, donate_argnums=(3, 4)),
                            "decode", meta={"slots": int(s)}, policy=pol)
            for s in self.decode_slots}

        self._draft_prefill: Dict[int, _JitDispatch] = {}
        self._draft_chunk: Dict[int, _JitDispatch] = {}
        self._draft_decode: Dict[int, _JitDispatch] = {}
        self._verify: Dict[int, _JitDispatch] = {}
        if draft is not None:
            dcfg = self._draft_cfg

            def _dprefill_fn(p, ids, length, kp, vp, bt):
                return _gpt.apply_prefill(p, dcfg, ids, length, kp, vp,
                                          bt, block_size=bs,
                                          eos_id=self.eos_id)

            def _ddecode_fn(p, ids, positions, kp, vp, bts):
                return _gpt.apply_decode_step(
                    p, dcfg, ids, positions, kp, vp, bts, block_size=bs,
                    eos_id=self.eos_id)

            def _dchunk_fn(p, ids, start, length, kp, vp, bt):
                return _gpt.apply_prefill_chunk(
                    p, dcfg, ids, start, length, kp, vp, bt,
                    block_size=bs, eos_id=self.eos_id)

            def _verify_fn(p, ids, positions, kp, vp, bts):
                return _gpt.apply_verify_step(
                    p, model_cfg, ids, positions, kp, vp, bts,
                    block_size=bs, eos_id=self.eos_id)

            if self.prefill_chunk:
                self._draft_chunk = {
                    self.prefill_chunk: _JitDispatch(
                        jax.jit(_dchunk_fn, donate_argnums=(4, 5)),
                        "prefill",
                        meta={"chunk": self.prefill_chunk,
                              "draft": True}, policy=pol)}
            else:
                self._draft_prefill = {
                    t: _JitDispatch(
                        jax.jit(_dprefill_fn, donate_argnums=(3, 4)),
                        "prefill", meta={"bucket": int(t),
                                         "draft": True}, policy=pol)
                    for t in self.prefill_buckets}
            self._draft_decode = {
                s: _JitDispatch(
                    jax.jit(_ddecode_fn, donate_argnums=(3, 4)),
                    "decode", meta={"slots": int(s), "draft": True},
                    policy=pol)
                for s in self.decode_slots}
            self._verify = {
                s: _JitDispatch(
                    jax.jit(_verify_fn, donate_argnums=(3, 4)),
                    "decode", meta={"verify": int(s), "k": self.spec_k},
                    policy=pol)
                for s in self.decode_slots}

        self.analysis = self._validate_boot()

        self._pools = init_pools(self.kv_cfg)
        self._draft_pools = init_pools(self._draft_kv_cfg) \
            if draft is not None else None
        # annotated with the reuse subtype so the lock-order analyzer
        # (tools/lockgraph.py) sees its leaf lock acquired under _cv
        self._alloc: "ReuseBlockAllocator" = \
            ReuseBlockAllocator(self.kv_cfg) \
            if self.config.prefix_cache else BlockAllocator(self.kv_cfg)
        # COW device copy: src block's contents into dst across both
        # pools (shape-cached jit; src/dst are traced scalars so every
        # copy reuses one executable per pool geometry)
        self._copy_block_fn = jax.jit(
            lambda kp, vp, src, dst: (kp.at[:, dst].set(kp[:, src]),
                                      vp.at[:, dst].set(vp[:, src])))
        self._device_kind = getattr(jax.devices()[0], "device_kind",
                                    "unknown")
        # HBM owner attribution: providers hand memwatch the CURRENT
        # pool/param arrays on every sweep — donation replaces the pool
        # buffers each step, so a one-time registration of the arrays
        # themselves would go stale immediately. Weakref'd so a dropped
        # engine (tests build many) never pins its pools alive.
        ref = weakref.ref(self)

        def _kv_arrays():
            eng = ref()
            if eng is None:
                return ()
            out = list(eng._pools)
            if eng._draft_pools is not None:
                out.extend(eng._draft_pools)
            return out

        def _param_arrays():
            eng = ref()
            if eng is None:
                return ()
            out = list(eng.params.values())
            if eng._draft_params is not None:
                out.extend(eng._draft_params.values())
            return out

        # per-model owner attribution: engines sharing a process (the
        # multi-model Server) tag their providers with the model id so
        # memwatch's owner table splits the shared HBM budget by model
        tag = getattr(self.config, "model_tag", None)
        own = (lambda base: f"{base}[{tag}]") if tag else (lambda b: b)
        self._mem_handles = [
            _memwatch.register_provider(own("kv_pool"), _kv_arrays),
            _memwatch.register_provider(own("params"), _param_arrays)]
        if self.config.prefix_cache:
            # retained-prefix accounting: bytes of cached (unreferenced
            # but evictable) blocks across BOTH models' pools. These
            # bytes live INSIDE the kv_pool arrays — memwatch reports
            # them alongside, like executable_bytes, without double-
            # counting them into the live-array total.
            per_block = self._prefix_block_bytes()

            def _prefix_bytes():
                eng = ref()
                if eng is None:
                    return (0, 0)
                n = eng._alloc.cached_blocks()
                return (n * per_block, n)

            self._mem_handles.append(_memwatch.register_bytes_provider(
                own("prefix_cache"), _prefix_bytes))
        # deferred import: the analysis package must not load during
        # package bootstrap; constructors only run after it
        from ..analysis import lockcheck as _lockcheck

        self._cv = _lockcheck.Condition(
            name="serving.decode.DecodeEngine._cv")
        # per-tenant QoS (None = the historical single-tenant FIFO).
        # Deferred import: qos.py pulls QueueFullError from batcher.
        from . import qos as _qos_mod

        self._qosm = _qos_mod
        self._qos = _qos_mod.QoSPolicy.from_spec(
            getattr(self.config, "qos", None))
        # annotated so tools/lockgraph.py can type the attribute (the
        # conditional value defeats constructor inference)
        self._wfq: Optional["WeightedFairScheduler"] = \
            _qos_mod.WeightedFairScheduler(self._qos) \
            if self._qos is not None else None
        self._waiting: "collections.deque[_Request]" = collections.deque()
        self._active: List[_Request] = []
        # chunked-prefill stage: admitted (blocks reserved) but not yet
        # fully prefilled; the sync loop advances the FRONT request one
        # chunk per iteration, interleaved with decode steps
        self._prefilling: "collections.deque[_Request]" = \
            collections.deque()
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._closed = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._rid = 0
        self._last_slot_config: Optional[int] = None
        self._counts = {k: 0 for k in
                        ("eos", "length", "rejected", "cancelled",
                         "error", "preempted")}
        self.warmed = False
        self.warmstart_adopted = 0
        SLOTS.set(max(self.decode_slots), state="configured")
        if self.config.warmstart:
            self.load_warmstart(self.config.warmstart)

    # -- boot validation (PR 8 shape) ----------------------------------

    def _validate_boot(self):
        """Config + trace findings in the analysis Finding shape. Like
        the serving Engine's boot walk: always runs (boot is one-time),
        raises AnalysisError only at PADDLE_TPU_VALIDATE=2, and lands
        in the analysis metrics under where="decode"."""
        from .. import analysis as _an

        t0 = time.perf_counter()
        findings: List[_an.Finding] = []

        def add(sev, msg, var=None):
            findings.append(_an.Finding(
                severity=sev, pass_name="decode_config", message=msg,
                var=var))

        kv, mc = self.kv_cfg, self.model_cfg
        if getattr(mc, "n_experts", 0):
            add(_an.ERROR, "MoE decode is unsupported: the paged decode "
                "step has no expert-dispatch path (ROADMAP item 4) — "
                "serve a dense config")
        if kv.usable_blocks < kv.max_blocks_per_seq:
            add(_an.ERROR,
                f"KV pool cannot hold ONE full sequence: "
                f"{kv.usable_blocks} usable blocks < "
                f"{kv.max_blocks_per_seq} blocks for max_len "
                f"{kv.max_len}", var="num_blocks")
        worst = max(self.decode_slots) * kv.max_blocks_per_seq
        if kv.usable_blocks < worst:
            add(_an.WARNING,
                f"KV pool oversubscribed: {kv.usable_blocks} usable "
                f"blocks < {worst} worst-case ({max(self.decode_slots)} "
                f"slots x {kv.max_blocks_per_seq} blocks) — expect "
                "preemptions under full-length load", var="num_blocks")
        if kv.max_len > mc.max_len:
            add(_an.ERROR,
                f"max_len {kv.max_len} exceeds the model's positional "
                f"table ({mc.max_len})", var="max_len")
        if not (-1 <= self.eos_id < mc.vocab_size):
            add(_an.ERROR,
                f"eos_id {self.eos_id} outside vocab [0, "
                f"{mc.vocab_size})", var="eos_id")
        if self.prefill_chunk:
            # the chunked program covers ANY prompt length under
            # max_len, so the bucket-coverage checks (including the
            # "largest prefill bucket < max_len" preemption-replay
            # warning) are retired on this path: preempt replays
            # re-chunk at any length
            if self.prefill_chunk > kv.max_len:
                add(_an.ERROR,
                    f"prefill_chunk {self.prefill_chunk} exceeds "
                    f"max_len {kv.max_len}", var="prefill_chunk")
        else:
            for t in self.prefill_buckets:
                if t > kv.max_len:
                    add(_an.ERROR, f"prefill bucket {t} exceeds max_len "
                        f"{kv.max_len}", var="prefill_buckets")
            if max(self.prefill_buckets) < kv.max_len:
                add(_an.WARNING,
                    f"largest prefill bucket "
                    f"{max(self.prefill_buckets)} < max_len "
                    f"{kv.max_len}: a pool-pressure preemption whose "
                    "replay prompt (original + generated) outgrows the "
                    "bucket set fails that request — extend "
                    "prefill_buckets to max_len if preemptions are "
                    "expected", var="prefill_buckets")
        if self._draft_cfg is not None:
            dc = self._draft_cfg
            if dc.vocab_size != mc.vocab_size:
                add(_an.ERROR,
                    f"draft vocab_size {dc.vocab_size} != target "
                    f"{mc.vocab_size}: proposed ids would be "
                    "meaningless to the verifier", var="draft")
            if dc.max_len < kv.max_len:
                add(_an.ERROR,
                    f"draft max_len {dc.max_len} < serving max_len "
                    f"{kv.max_len}: the draft runs every position the "
                    "target does", var="draft")
            if getattr(dc, "n_experts", 0):
                add(_an.ERROR, "MoE draft is unsupported (same "
                    "constraint as the target model)", var="draft")
        if self.spec_k and self.spec_k >= kv.max_len:
            add(_an.ERROR, f"spec_k {self.spec_k} >= max_len "
                f"{kv.max_len}", var="spec_k")
        for s in self.decode_slots:
            if s < 1:
                add(_an.ERROR, f"decode slot count {s} < 1",
                    var="decode_slots")
        if not any(f.severity == _an.ERROR for f in findings):
            # shape-trace every phase executable (no XLA, milliseconds):
            # a shape bug fails boot with a structured finding instead
            # of an opaque trace error inside the first live request
            for key in self._phase_keys():
                try:
                    disp = self._phase_dispatch(key)
                    jax.eval_shape(disp._jit, *self._phase_avals(key))
                except Exception as e:
                    findings.append(_an.Finding(
                        severity=_an.ERROR, pass_name="decode_trace",
                        message=f"{key[0]}@{key[1]} fails to trace: "
                                f"{type(e).__name__}: {str(e)[:200]}"))
        _telemetry.record_analysis(
            findings, n_ops=len(self._phase_keys()),
            where="decode", seconds=time.perf_counter() - t0)
        out = {"errors": 0, "warnings": 0, "infos": 0}
        for f in findings:
            out[f.severity + "s"] = out.get(f.severity + "s", 0) + 1
        if any(f.severity == _an.ERROR for f in findings) \
                and _an.validate_level() >= 2:
            raise _an.AnalysisError(findings)
        return out

    # -- phase grid / warmstart ----------------------------------------

    def _phase_keys(self) -> List[Tuple[str, int]]:
        keys: List[Tuple[str, int]] = []
        if self.prefill_chunk:
            keys.append(("chunk", self.prefill_chunk))
        else:
            keys.extend(("prefill", t) for t in self.prefill_buckets)
        keys.extend(("decode", s) for s in self.decode_slots)
        if self._draft is not None:
            if self.prefill_chunk:
                keys.append(("draft_chunk", self.prefill_chunk))
            else:
                keys.extend(("draft_prefill", t)
                            for t in self.prefill_buckets)
            keys.extend(("draft_decode", s) for s in self.decode_slots)
            keys.extend(("verify", s) for s in self.decode_slots)
        return keys

    def _phase_dispatch(self, key) -> _JitDispatch:
        """The grid is a flat (kind, size) → dispatcher map; every
        consumer (boot trace, warmup, warmstart export/load) walks it
        through this one lookup."""
        kind, n = key
        return {"prefill": self._prefill, "chunk": self._chunk,
                "decode": self._decode,
                "draft_prefill": self._draft_prefill,
                "draft_chunk": self._draft_chunk,
                "draft_decode": self._draft_decode,
                "verify": self._verify}[kind][n]

    def _phase_avals(self, key):
        sds = jax.ShapeDtypeStruct
        kind, n = key
        draft = kind.startswith("draft_")
        params = self._draft_params if draft else self.params
        p_sds = jax.tree_util.tree_map(
            lambda a: sds(a.shape, a.dtype), params)
        kv = self._draft_kv_cfg if draft else self.kv_cfg
        pool = sds((kv.layers, kv.num_blocks, kv.block_size,
                    kv.kv_heads, kv.head_dim), np.dtype(kv.dtype))
        mb = kv.max_blocks_per_seq
        base = kind[6:] if draft else kind
        if base == "prefill":
            return (p_sds, sds((1, n), np.int32), sds((), np.int32),
                    pool, pool, sds((mb,), np.int32))
        if base == "chunk":
            return (p_sds, sds((1, n), np.int32), sds((), np.int32),
                    sds((), np.int32), pool, pool,
                    sds((mb,), np.int32))
        if base == "verify":
            return (p_sds, sds((n, self.spec_k + 1), np.int32),
                    sds((n,), np.int32), pool, pool,
                    sds((n, mb), np.int32))
        return (p_sds, sds((n,), np.int32), sds((n,), np.int32),
                pool, pool, sds((n, mb), np.int32))

    def warmup(self) -> int:
        """AOT-compile (or adopt from the persistent compile cache /
        a loaded warmstart artifact) every phase-grid executable.
        Returns how many phases are ready. Idempotent."""
        ready = 0
        for key in self._phase_keys():
            if self._phase_dispatch(key).warm(*self._phase_avals(key)):
                ready += 1
        self.warmed = True
        return ready

    def _model_digest(self) -> str:
        """Binds warmstart artifacts to THIS model + grid: params
        content, model config, and the kv/pool geometry that shapes
        every executable."""
        h = hashlib.sha256()
        h.update(repr((self.model_cfg, self.kv_cfg,
                       self.decode_slots,
                       self.prefill_buckets,
                       self.config.precision,
                       self.eos_id,
                       self.prefill_chunk, self.spec_k,
                       self._draft_cfg)).encode())
        for name in sorted(self.params):
            a = np.ascontiguousarray(np.asarray(self.params[name]))
            h.update(f"{name}:{a.dtype}:{a.shape}".encode())
            h.update(a.tobytes())
        if self._draft_params is not None:
            for name in sorted(self._draft_params):
                a = np.ascontiguousarray(
                    np.asarray(self._draft_params[name]))
                h.update(f"draft:{name}:{a.dtype}:{a.shape}".encode())
                h.update(a.tobytes())
        return h.hexdigest()

    def export_warmstart(self, path: str) -> int:
        """Serialize every warmed phase executable into ONE artifact
        (the PR 6 pattern, keyed by phase instead of batch bucket).
        Call after warmup(); returns how many phases it carries."""
        entries = {}
        for key in self._phase_keys():
            disp = self._phase_dispatch(key)
            exe = disp._aot
            if exe is None:
                continue
            try:
                avals = self._phase_avals(key)
                fp = disp.cache_fingerprint(disp.lower(*avals))
                entries[key] = {
                    "blob": _cc.serialize_executable(exe),
                    "fingerprint": fp}
            except Exception:
                continue  # backend refused: artifact covers fewer phases
        grid = {"decode": list(self.decode_slots)}
        if self.prefill_chunk:
            # chunked path: the bucket dimension is collapsed, so the
            # artifact advertises the chunk size, not buckets
            grid["chunk"] = self.prefill_chunk
        else:
            grid["prefill"] = list(self.prefill_buckets)
        if self.spec_k:
            grid["spec_k"] = self.spec_k
        art = dict(_cc.environment_meta(),
                   format=DECODE_WARMSTART_FORMAT,
                   model_digest=self._model_digest(),
                   grid=grid,
                   created_at=time.time(),
                   entries=entries)
        from ..resilience.atomic import write_bytes

        write_bytes(path, pickle.dumps(art,
                                       protocol=pickle.HIGHEST_PROTOCOL))
        _events.emit("warmstart", action="export_decode", path=path,
                     entries=len(entries))
        return len(entries)

    def load_warmstart(self, path: str) -> int:
        """Adopt the phase executables from a decode warmstart
        artifact; same degradation contract as the serving engine's:
        any mismatch (environment, model digest, per-entry lowering
        fingerprint) costs a reject event + a cold phase, never a
        boot failure."""
        try:
            with open(path, "rb") as f:
                art = pickle.loads(f.read())
            if not isinstance(art, dict) or \
                    art.get("format") != DECODE_WARMSTART_FORMAT:
                raise ValueError("not a decode warmstart artifact")
        except Exception as e:
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"unreadable: {str(e)[:200]}")
            self.warmstart_adopted = 0
            return 0
        env = _cc.environment_meta()
        stored = {k: art.get(k) for k in env}
        if stored != env:
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"environment mismatch: artifact "
                                f"{stored} vs process {env}")
            self.warmstart_adopted = 0
            return 0
        if art.get("model_digest") != self._model_digest():
            _events.emit("warmstart", action="reject", path=path,
                         reason="model digest mismatch — artifact baked "
                                "from a different model/grid")
            self.warmstart_adopted = 0
            return 0
        adopted = 0
        for key, entry in (art.get("entries") or {}).items():
            try:
                kind, n = key
                try:
                    disp = self._phase_dispatch((kind, n))
                except KeyError:
                    continue  # artifact baked with a different grid
                avals = self._phase_avals((kind, n))
                fp = disp.cache_fingerprint(disp.lower(*avals))
                if fp is None or fp != entry["fingerprint"]:
                    continue  # lowering/flags drifted since the bake
                exe = _cc.deserialize_executable(entry["blob"])
                disp.adopt(exe, *avals)
                adopted += 1
            except Exception:
                continue
        self.warmstart_adopted = adopted
        _events.emit("warmstart", action="load_decode", path=path,
                     adopted=adopted)
        return adopted

    # -- client API ----------------------------------------------------

    def start(self):
        """Start the scheduler thread (idempotent; submit() calls it)."""
        with self._cv:
            if self._thread is not None or self._closed:
                return
            self._thread = threading.Thread(
                target=self._loop_sync if self._sync else self._loop,
                name="paddle-tpu-decode", daemon=True)
            self._thread.start()
            _events.emit("decode", action="start",
                         slots=list(self.decode_slots),
                         prefill_buckets=list(self.prefill_buckets),
                         blocks=self.kv_cfg.usable_blocks)

    def submit(self, prompt_ids, max_new_tokens: int = 16,
               tenant: Optional[str] = None) -> DecodeHandle:
        """Enqueue one generation; returns its token-stream handle.
        Reject-not-block: QueueFullError (HTTP 503) when max_queue
        prompts already wait, ServerClosed after stop(). Under a QoS
        policy (DecodeConfig(qos=...)) a full queue sheds the lowest-
        tier waiter (newest first within the tier) via qos.ShedError —
        possibly a QUEUED victim, in which case this arrival is
        admitted in its place — and per-tenant quotas bound one
        tenant's waiting+active footprint."""
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        if prompt.size < 1:
            raise ValueError("prompt must carry at least one token id")
        if self.prefill_chunk:
            # chunked prefill has no bucket ceiling: any prompt that
            # leaves generation room under max_len is admissible
            if prompt.size > self.kv_cfg.max_len - 1:
                raise ValueError(
                    f"prompt length {prompt.size} leaves no room to "
                    f"generate under max_len {self.kv_cfg.max_len}")
        elif prompt.size > self.prefill_buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the largest "
                f"prefill bucket {self.prefill_buckets[-1]}")
        if int(prompt.min()) < 0 or \
                int(prompt.max()) >= self.model_cfg.vocab_size:
            raise ValueError(
                f"prompt token ids must be in [0, "
                f"{self.model_cfg.vocab_size})")
        room = self.kv_cfg.max_len - int(prompt.size)
        if room < 1:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate under max_len {self.kv_cfg.max_len}")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        max_new = min(int(max_new_tokens), room)
        tenant = str(tenant) if tenant else self._qosm.DEFAULT_TENANT
        shed_victim: Optional[_Request] = None
        shed_err: Optional[BaseException] = None
        with self._cv:
            if self._closed:
                self._count("rejected", tenant)
                raise ServerClosed("decode engine is stopped")
            if self._draining:
                self._count("rejected", tenant)
                raise ServerClosed(
                    "decode engine is draining; request rejected")
            qos = self._qos
            if qos is not None:
                quota = qos.quota_of(tenant)
                if quota is not None:
                    have = sum(1 for r in self._waiting
                               if r.tenant == tenant) \
                        + sum(1 for r in self._active
                              if r.tenant == tenant) \
                        + sum(1 for r in self._prefilling
                              if r.tenant == tenant)
                    if have >= quota:
                        tier = qos.tier_of(tenant)
                        self._qosm.SHEDS.inc(tier=tier, kind="quota")
                        _events.emit("shed", where="decode",
                                     tenant=tenant, tier=tier,
                                     shed="quota")
                        self._count("rejected", tenant)
                        raise self._qosm.ShedError(
                            f"tenant {tenant!r} over quota ({quota} "
                            "concurrent generations); request rejected",
                            tenant=tenant, tier=tier, kind="quota")
            if len(self._waiting) >= self.config.max_queue:
                if qos is None:
                    self._count("rejected", tenant)
                    raise QueueFullError(
                        f"decode queue full ({self.config.max_queue} "
                        "waiting); request rejected")
                # tier-ordered shed: lowest tier first, newest first
                # within the tier, the arrival included as a candidate
                entries = [(r.tenant, r.rid) for r in self._waiting] \
                    + [(tenant, self._rid + 1)]
                vi = self._qosm.shed_victim(entries, qos)
                v_tenant = entries[vi][0]
                v_tier = qos.tier_of(v_tenant)
                self._qosm.SHEDS.inc(tier=v_tier, kind="queue")
                _events.emit("shed", where="decode", tenant=v_tenant,
                             tier=v_tier, shed="queue")
                err = self._qosm.ShedError(
                    f"decode queue full ({self.config.max_queue} "
                    f"waiting); shed tier {v_tier!r} (tenant "
                    f"{v_tenant!r})",
                    tenant=v_tenant, tier=v_tier, kind="queue")
                if vi == len(entries) - 1:   # the arrival is the victim
                    self._count("rejected", tenant)
                    raise err
                shed_victim = self._waiting[vi]
                del self._waiting[vi]
                shed_err = err
            self._rid += 1
            req = _Request(self._rid, prompt, max_new, tenant)
            self._waiting.append(req)
            QUEUE_DEPTH.set(len(self._waiting))
            self._cv.notify_all()
        if shed_victim is not None:
            # outside the lock (matches _sweep_cancelled's finish
            # discipline): end the victim's stream with the typed error
            shed_victim.error = shed_err
            self._count("rejected", shed_victim.tenant)
            shed_victim.finish_reason = "rejected"
            shed_victim.events.put(None)
        self.start()
        return DecodeHandle(req)

    def cancel(self, handle: DecodeHandle):
        """Abandon one generation (the HTTP frontend calls this when a
        streaming client disconnects): the scheduler retires the
        request at its next iteration, freeing its slot and KV blocks
        instead of generating the full max_new_tokens into an unread
        queue. Idempotent; a no-op once the request finished."""
        with self._cv:
            handle._req.cancelled = True
            self._cv.notify_all()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain (the fleet's scale-in path, SERVING.md
        §Fleet): stop admitting — new submits raise ServerClosed/503 —
        but let every waiting and active generation run to completion.
        Returns True when the engine emptied within `timeout_s` (False:
        caller decides whether to stop() anyway, cancelling the rest).
        Idempotent; a later start of new traffic requires a new engine.
        """
        deadline = time.monotonic() + float(timeout_s)
        with self._cv:
            if not self._draining:
                self._draining = True
                _events.emit("decode", action="drain",
                             waiting=len(self._waiting),
                             active=len(self._active))
        while time.monotonic() < deadline:
            with self._cv:
                if self._closed or (not self._waiting
                                    and not self._active
                                    and not self._prefilling):
                    return True
            time.sleep(0.01)
        with self._cv:
            return not self._waiting and not self._active \
                and not self._prefilling

    def stop(self):
        """Stop the scheduler: waiting and active requests are
        cancelled (their streams end with finish_reason='cancelled').
        Idempotent; joins the thread. Requests enqueued before any
        scheduler thread existed are drained HERE — _loop's finally
        (the usual cleanup) never runs for a thread never started, and
        a submit racing this stop must not strand its caller blocking
        on a stream that nothing will ever terminate."""
        with self._cv:
            if not self._closed:
                self._closed = True
                self._cv.notify_all()
            t = self._thread
            stranded = [] if t is not None else list(self._waiting)
            if t is None and stranded:
                self._waiting.clear()
                QUEUE_DEPTH.set(0)
        for req in stranded:
            self._finish(req, "cancelled")
        if t is not None:
            t.join(timeout=30.0)
        for h in getattr(self, "_mem_handles", ()):
            _memwatch.unregister_provider(h)
        self._mem_handles = []
        _events.emit("decode", action="stop")

    def load(self) -> Tuple[int, int]:
        """(queued, active) — the cheap pair the /v1/load probe folds
        into its scalar load score without building the full status
        document."""
        with self._cv:
            return (len(self._waiting),
                    len(self._active) + len(self._prefilling))

    def status(self) -> Dict:
        with self._cv:
            waiting = len(self._waiting)
            active = len(self._active)
            prefilling = len(self._prefilling)
            live_tokens = sum(r.pos for r in self._active)
            live_tokens += sum(r.prefill_pos for r in self._prefilling)
            counts = dict(self._counts)
            draining = self._draining
        grid = {"decode_slots": list(self.decode_slots)}
        if self.prefill_chunk:
            grid["prefill_chunk"] = self.prefill_chunk
        else:
            grid["prefill_buckets"] = list(self.prefill_buckets)
        out = {
            "draining": draining,
            "phase_grid": grid,
            "queue_depth": waiting,
            "active": active,
            "slot_config": self._last_slot_config,
            "static_batching": self.config.static_batching,
            "precision": self.config.precision,
            "eos_id": self.eos_id,
            "warmed": self.warmed,
            "warmstart_adopted": self.warmstart_adopted,
            "analysis": self.analysis,
            "kv": self._alloc.stats(live_tokens=live_tokens),
            "requests": counts,
        }
        if self._qos is not None:
            out["qos"] = {
                "policy": self._qos.spec_dict(),
                "served_shares": {
                    t: round(s, 4) for t, s in
                    self._wfq.served_shares().items()},
            }
        if self._sync:
            out["prefilling"] = prefilling
            out["kv_reuse"] = {
                "prefix_cache": self.config.prefix_cache,
                "prefill_chunk": self.prefill_chunk,
                "spec_k": self.spec_k,
                "spec_proposed": self._spec_proposed,
                "spec_accepted": self._spec_accepted,
                "spec_accept_rate": round(
                    self._spec_accepted / self._spec_proposed, 4)
                if self._spec_proposed else None,
            }
        return out

    # -- scheduler internals (single thread owns everything below) -----

    def _count(self, outcome: str, tenant: Optional[str] = None):
        REQUESTS.inc(outcome=outcome)
        if self._qos is not None and tenant is not None:
            self._qosm.TENANT_REQUESTS.inc(
                tenant=tenant, tier=self._qos.tier_of(tenant),
                outcome=outcome)
        self._counts[outcome] = self._counts.get(outcome, 0) + 1

    def _emit_token(self, req: _Request, tok: int, phase: str):
        req.last_token = int(tok)
        req.generated.append(int(tok))
        TOKENS.inc(phase=phase)
        if self._wfq is not None:
            # token-granular service charge: the admission pick reads
            # these virtual times, so sustained token flow to one
            # tenant defers its next admission in favor of underserved
            # same-tier tenants
            self._wfq.charge(req.tenant, 1)
            self._qosm.TENANT_TOKENS.inc(tenant=req.tenant)
        if req.t_first is None:
            req.t_first = time.monotonic()
            TTFT_SECONDS.observe(req.t_first - req.t_submit)
            if self._qos is not None:
                self._qosm.TENANT_TTFT_SECONDS.observe(
                    req.t_first - req.t_submit, tenant=req.tenant)
            # per-request TTFT span: submit -> first sampled token
            _tracing.record_trace_span(
                "decode.ttft", req.tctx, req.t_first - req.t_submit,
                cat="decode", rid=req.rid, prompt_len=req.prompt_len0,
                tenant=req.tenant)
        req.events.put(int(tok))

    def _finished_reason(self, req: _Request) -> Optional[str]:
        if req.generated and req.generated[-1] == self.eos_id:
            return "eos"
        if len(req.generated) >= req.max_new:
            return "length"
        return None

    def _finish(self, req: _Request, reason: str):
        req.finish_reason = reason
        now = time.monotonic()
        if req.t_first is not None and len(req.generated) > 1:
            # decode-phase span: first token -> last token (the
            # prefill/TTFT spans cover everything before it)
            _tracing.record_trace_span(
                "decode.decode", req.tctx, now - req.t_first,
                cat="decode", rid=req.rid,
                tokens=len(req.generated) - 1)
        _tracing.record_trace_span(
            "decode.generate", req.tctx, now - req.t_submit,
            cat="decode", rid=req.rid, tokens=len(req.generated),
            reason=reason, tenant=req.tenant)
        if req.blocks:
            self._alloc.free(req.blocks)   # reuse allocator: decref;
            req.blocks = []                # cached blocks go to LRU
        if req in self._active:
            self._active.remove(req)
        if req in self._prefilling:
            self._prefilling.remove(req)
        self._count(reason, req.tenant)
        req.events.put(None)
        self._kv_gauges()

    def _kv_gauges(self):
        KV_BLOCKS.set(self._alloc.used_blocks(), state="used")
        KV_BLOCKS.set(self._alloc.free_blocks(), state="free")
        if self.config.prefix_cache:
            KV_BLOCKS.set(self._alloc.cached_blocks(), state="cached")
        SLOTS.set(len(self._active), state="active")

    def _bucket_for_len(self, n: int) -> Optional[int]:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return None

    def _slot_config(self) -> int:
        n = max(1, len(self._active))
        for s in self.decode_slots:
            if n <= s:
                return s
        return self.decode_slots[-1]

    def _sweep_cancelled(self):
        """Retire requests whose clients abandoned them (cancel()):
        waiting ones leave the queue, active ones free their slot and
        blocks. Runs at the top of every scheduler iteration; a
        cancelled request with a token still in flight is skipped by
        _resolve's not-in-active check."""
        with self._cv:
            gone_waiting = [r for r in self._waiting if r.cancelled]
            for r in gone_waiting:
                self._waiting.remove(r)
            if gone_waiting:
                QUEUE_DEPTH.set(len(self._waiting))
        for r in gone_waiting:
            self._finish(r, "cancelled")
        for r in [r for r in self._active if r.cancelled]:
            self._finish(r, "cancelled")
        for r in [r for r in self._prefilling if r.cancelled]:
            self._finish(r, "cancelled")

    def _pick_waiting_locked(self) -> int:
        """Index of the next waiting request to admit (caller holds
        _cv, _waiting non-empty): FIFO without a QoS policy; (tier
        priority, weighted-fair virtual time) with one."""
        if self._wfq is None:
            return 0
        return self._wfq.pick([r.tenant for r in self._waiting])

    def _victim_key(self, r: _Request):
        """Preemption/shed ordering under KV pressure: lowest tier
        first (max tier rank), youngest admission within the tier —
        identical to the historical youngest-first rule when no QoS
        policy is attached (rank is constant 0)."""
        rank = 0 if self._qos is None else self._qos.rank_of(r.tenant)
        return (rank, r.admitted_at)

    def _admit(self) -> bool:
        """Move waiting requests into free slots while blocks last;
        each admission runs its prefill (the admission boundary is the
        one place the scheduler syncs with the device). Returns whether
        the batch composition changed."""
        changed = False
        max_slots = self.decode_slots[-1]
        while True:
            with self._cv:
                if not self._waiting or self._closed:
                    break
                if self.config.static_batching and self._active:
                    break  # drain-between-batches baseline
                if len(self._active) >= max_slots:
                    break
                idx = self._pick_waiting_locked()
                req = self._waiting[idx]
                need = -(-len(req.prompt) // self.kv_cfg.block_size)
                if not self._alloc.can_alloc(need):
                    break  # blocks scale with live tokens: defer
                del self._waiting[idx]
                QUEUE_DEPTH.set(len(self._waiting))
            self._prefill_one(req)
            changed = True
        return changed

    def _prefill_one(self, req: _Request):
        # the admission boundary: everything since (re-)enqueue was wait
        _tracing.record_trace_span(
            "decode.queue_wait", req.tctx,
            time.monotonic() - req.enqueued_at, cat="decode",
            rid=req.rid, tenant=req.tenant)
        if self._wfq is not None:
            # prefill service charge: a long prompt is real work even
            # before its first decode token
            self._wfq.charge(req.tenant, len(req.prompt))
        plen = len(req.prompt)
        bucket = self._bucket_for_len(plen)
        if bucket is None:  # replay grew past the largest bucket
            req.error = RuntimeError(
                f"prompt+generated length {plen} exceeds the largest "
                f"prefill bucket {self.prefill_buckets[-1]}")
            self._finish(req, "error")
            return
        need = -(-plen // self.kv_cfg.block_size)
        req.blocks = self._alloc.alloc(need)
        bt = build_block_table(req.blocks, self.kv_cfg.max_blocks_per_seq)
        ids = np.empty((1, bucket), np.int32)
        ids[0, :plen] = req.prompt
        ids[0, plen:] = req.prompt[-1]         # edge-pad (in-distribution)
        kp, vp = self._pools
        t0 = time.perf_counter()
        tok, kp, vp = self._prefill[bucket](
            self.params, ids, np.int32(plen), kp, vp, bt)
        self._pools = (kp, vp)
        tok0 = int(np.asarray(tok)[0])         # admission-boundary sync
        STEPS.inc(phase="prefill")
        _tracing.record_trace_span(
            "decode.prefill", req.tctx, time.perf_counter() - t0,
            cat="decode", t0_perf=t0, rid=req.rid, bucket=int(bucket),
            prompt_len=plen)
        _telemetry.record_dispatch_ready(
            "decode:prefill", time.perf_counter() - t0)
        # live-MFU sample: the bucket executable's retained
        # cost_analysis FLOPs over this prefill's wall window (one
        # token emitted — the TTFT token)
        _perfwatch.record_step(
            "prefill", time.perf_counter() - t0,
            flops=(self._prefill[bucket].current_cost() or {})
            .get("flops"),
            tokens=1, device_kind=self._device_kind)
        if self._draft is not None:
            # the draft prefills EVERY sequence (same ids, same block
            # table, its own pools) so speculation can start at the
            # first decode round
            dkp, dvp = self._draft_pools
            _, dkp, dvp = self._draft_prefill[bucket](
                self._draft_params, ids, np.int32(plen), dkp, dvp, bt)
            self._draft_pools = (dkp, dvp)
            req.draft_pos = plen
            STEPS.inc(phase="draft")
        req.pos = plen
        req.admitted_at = time.monotonic()
        self._active.append(req)
        self._emit_token(req, tok0, phase="prefill")
        reason = self._finished_reason(req)
        if reason:
            self._finish(req, reason)
        self._kv_gauges()

    def _grow_blocks(self, pending: Optional[_Pending]
                     ) -> Optional[_Pending]:
        """Ensure every active slot owns the block its next write
        lands in. On pool exhaustion: resolve the in-flight step (its
        finishes may free blocks), retry, then preempt the youngest
        active sequence until the step fits."""
        while True:
            short = None
            for req in self._active:
                bi = req.pos // self.kv_cfg.block_size
                while bi >= len(req.blocks):
                    try:
                        req.blocks.extend(self._alloc.alloc(1))
                    except NoBlocksError:
                        short = req
                        break
                if short is not None:
                    break
            if short is None:
                return pending
            if pending is not None:
                pending = self._resolve(pending)
                continue  # finishes may have freed enough
            victim = max(self._active, key=self._victim_key)
            self._preempt(victim)

    def _preempt(self, req: _Request):
        """vLLM-style recompute preemption: free the victim's blocks
        and requeue it (front) with prompt = original + generated; the
        replay prefill regenerates its KV and its NEXT token — tokens
        already streamed are not re-emitted."""
        if req in self._active:
            self._active.remove(req)
        else:
            self._prefilling.remove(req)
        self._alloc.free(req.blocks)   # reuse allocator: decref — a
        req.blocks = []                # shared prefix survives for the
        req.prefill_pos = 0            # replay to hit again
        req.draft_pos = 0
        req.n_reused = 0
        req.hashes = None
        # replay prompt: original prompt + everything generated so far
        req.prompt = np.concatenate(
            [req.prompt[:req.prompt_len0],
             np.asarray(req.generated, np.int32)])
        req.enqueued_at = time.monotonic()
        with self._cv:
            self._waiting.appendleft(req)
            QUEUE_DEPTH.set(len(self._waiting))
        PREEMPTIONS.inc()
        self._counts["preempted"] = self._counts.get("preempted", 0) + 1
        extra = {"trace_id": req.tctx.trace_id} \
            if req.tctx is not None and req.tctx.sampled else {}
        _events.emit("decode", action="preempt", rid=req.rid,
                     generated=len(req.generated), tenant=req.tenant,
                     **extra)
        _tracing.record_trace_span(
            "decode.preempt", req.tctx, 0.0, cat="decode", rid=req.rid,
            generated=len(req.generated))
        self._kv_gauges()

    def _snapshot(self, C: int) -> Tuple[Tuple[int, ...],
                                         List[Optional[_Request]]]:
        slots: List[Optional[_Request]] = list(self._active[:C])
        while len(slots) < C:
            slots.append(None)
        return tuple(r.rid if r else -1 for r in slots), slots

    def _dispatch(self, ids_arg, C: int) -> _Pending:
        kp, vp = self._pools
        positions = np.zeros((C,), np.int32)
        bts = np.zeros((C, self.kv_cfg.max_blocks_per_seq), np.int32)
        sig, slots = self._snapshot(C)
        for i, req in enumerate(slots):
            if req is None:
                continue
            positions[i] = req.pos
            bts[i] = build_block_table(req.blocks,
                                       self.kv_cfg.max_blocks_per_seq)
        tok, kp, vp = self._decode[C](self.params, ids_arg, positions,
                                      kp, vp, bts)
        self._pools = (kp, vp)
        for req in slots:
            if req is not None:
                req.pos += 1
        STEPS.inc(phase="decode")
        OCCUPANCY.observe(sum(1 for r in slots if r is not None) / C)
        self._last_slot_config = C
        return _Pending(FetchHandle([tok], site="decode"), tok, sig, slots)

    def _resolve(self, pending: _Pending) -> None:
        """Consume one in-flight step's tokens: stream them, detect
        finishes, retire (freeing blocks). Tokens for slots that were
        already retired/preempted after dispatch are discarded."""
        t_wait = time.perf_counter()
        toks = np.asarray(pending.handle.result()[0])
        now = time.perf_counter()
        wall = now - pending.t_dispatch
        STEP_SECONDS.observe(wall)
        # live-MFU sample: the slot-config executable's retained FLOPs
        # over the dispatch→resolve window; the result() wait is the
        # host-blocked share, occupied slots are the tokens produced
        C = len(pending.slots)
        _perfwatch.record_step(
            "decode", wall,
            flops=(self._decode[C].current_cost() or {}).get("flops"),
            tokens=sum(1 for r in pending.slots if r is not None),
            host_blocked=min(now - t_wait, wall),
            device_kind=self._device_kind)
        for i, req in enumerate(pending.slots):
            if req is None or req not in self._active:
                continue
            self._emit_token(req, int(toks[i]), phase="decode")
            reason = self._finished_reason(req)
            if reason:
                self._finish(req, reason)
        return None

    def _loop(self):
        pending: Optional[_Pending] = None
        try:
            while True:
                with self._cv:
                    while not self._closed and not self._waiting \
                            and not self._active and pending is None:
                        self._cv.wait(timeout=0.5)
                    if self._closed:
                        break
                self._sweep_cancelled()
                self._admit()
                if not self._active:
                    if pending is not None:
                        pending = self._resolve(pending)
                    continue
                pending = self._grow_blocks(pending)
                if not self._active:  # growth preempted everything
                    continue
                C = self._slot_config()
                sig, slots = self._snapshot(C)
                if pending is not None and pending.snapshot == sig:
                    # steady state: feed the previous step's tokens
                    # back on DEVICE — the host never touched them
                    ids_arg = pending.tok_dev
                else:
                    if pending is not None:
                        pending = self._resolve(pending)
                        self._admit()  # retirements freed slots
                        # a request admitted HERE whose prompt length
                        # is an exact block multiple needs its next
                        # block before this dispatch, or its first
                        # decode write lands in the null block
                        self._grow_blocks(None)
                        if not self._active:
                            continue
                        C = self._slot_config()
                        sig, slots = self._snapshot(C)
                    ids_arg = np.zeros((C,), np.int32)
                    for i, req in enumerate(slots):
                        if req is not None:
                            ids_arg[i] = req.last_token
                new_pending = self._dispatch(ids_arg, C)
                if pending is not None:
                    # overlap: resolve step N-1 while step N runs
                    pending = self._resolve(pending)
                pending = new_pending
        except BaseException as e:  # scheduler death must not hang clients
            with self._cv:
                reqs = list(self._active) + list(self._waiting)
                self._waiting.clear()
            for req in reqs:
                req.error = RuntimeError(
                    f"decode scheduler failed: {type(e).__name__}: {e}")
                req.error.__cause__ = e
                self._finish(req, "error")
            raise
        finally:
            if pending is not None:
                try:
                    self._resolve(pending)
                except Exception:  # lint-exempt:swallow: shutdown path; clients are cancelled below
                    pass
            with self._cv:
                reqs = list(self._active) + list(self._waiting)
                self._waiting.clear()
                QUEUE_DEPTH.set(0)
            for req in reqs:
                self._finish(req, "cancelled")

    # -- KV-reuse scheduler (chunked prefill / prefix cache / spec) ----
    #
    # Any reuse feature runs THIS loop instead of _loop: synchronous
    # rounds (each resolves on the host before the next dispatch),
    # trading the lazy-fetch step overlap for mid-prompt admission —
    # one prompt chunk interleaves with every decode round — and for
    # multi-token speculation rounds.

    def _prefix_block_bytes(self) -> int:
        """Device bytes ONE cached block retains across both models'
        pools (K and V, all layers) — the unit of the memwatch
        prefix_cache owner row."""
        def per(kv: KVCacheConfig) -> int:
            return (2 * kv.layers * kv.block_size * kv.kv_heads *
                    kv.head_dim * np.dtype(kv.dtype).itemsize)
        n = per(self.kv_cfg)
        if self._draft_kv_cfg is not None:
            n += per(self._draft_kv_cfg)
        return n

    def _reserve_chunked(self, req: _Request) -> bool:
        """Reserve the full block span for a prompt before chunking
        starts: prefix-cache hits splice cached blocks into the front
        of the table (skipping their recompute entirely), fresh blocks
        cover the rest. All-or-nothing — on a pool shortfall the hits
        are released (decref) and the request stays queued. Caller
        holds self._cv."""
        plen = len(req.prompt)
        bs = self.kv_cfg.block_size
        need = -(-plen // bs)
        reused: List[int] = []
        req.hashes = None
        if self.config.prefix_cache:
            req.hashes = _kvr.hash_blocks(req.prompt, bs)
            # block j is shareable iff (j+1)*bs <= plen-1: the computed
            # suffix must keep >= 1 prompt token, so the chunk program
            # always produces the first-token logits
            usable = [h for j, h in enumerate(req.hashes)
                      if (j + 1) * bs <= plen - 1]
            reused = self._alloc.match_prefix(usable)
        if not self._alloc.can_alloc(need - len(reused)):
            if reused:
                self._alloc.free(reused)
            return False
        req.blocks = list(reused) + self._alloc.alloc(need - len(reused))
        req.n_reused = len(reused)
        req.prefill_pos = len(reused) * bs
        return True

    def _admit_sync(self):
        """Admission for the sync loop: chunked prompts reserve their
        block span and join the prefilling stage (their compute is
        spread over later iterations); without chunking (spec-only
        engines) the whole-prompt prefill runs here as in _admit."""
        max_slots = self.decode_slots[-1]
        while True:
            chunked = False
            with self._cv:
                if not self._waiting or self._closed:
                    return
                if self.config.static_batching and \
                        (self._active or self._prefilling):
                    return
                if len(self._active) + len(self._prefilling) \
                        >= max_slots:
                    return
                idx = self._pick_waiting_locked()
                req = self._waiting[idx]
                if self.prefill_chunk:
                    if not self._reserve_chunked(req):
                        return
                    chunked = True
                else:
                    need = -(-len(req.prompt) // self.kv_cfg.block_size)
                    if not self._alloc.can_alloc(need):
                        return
                del self._waiting[idx]
                QUEUE_DEPTH.set(len(self._waiting))
            if chunked:
                _tracing.record_trace_span(
                    "decode.queue_wait", req.tctx,
                    time.monotonic() - req.enqueued_at, cat="decode",
                    rid=req.rid, tenant=req.tenant)
                if self._wfq is not None:
                    self._wfq.charge(req.tenant, len(req.prompt))
                req.admitted_at = time.monotonic()
                self._prefilling.append(req)
                self._kv_gauges()
            else:
                self._prefill_one(req)

    def _pump_chunk(self):
        """Advance the FRONT prefilling request by one chunk (both
        models when a draft rides along). On the final chunk the
        request's full prompt blocks register in the prefix index, the
        first token emits, and the request joins the decode batch."""
        if not self._prefilling:
            return
        req = self._prefilling[0]
        Ck = self.prefill_chunk
        bs = self.kv_cfg.block_size
        plen = len(req.prompt)
        start = req.prefill_pos
        cid = np.empty((1, Ck), np.int32)
        seg = req.prompt[start:start + Ck]
        cid[0, :len(seg)] = seg
        cid[0, len(seg):] = req.prompt[-1]     # edge-pad (in-distribution)
        bt = build_block_table(req.blocks, self.kv_cfg.max_blocks_per_seq)
        kp, vp = self._pools
        t0 = time.perf_counter()
        tok, kp, vp = self._chunk[Ck](
            self.params, cid, np.int32(start), np.int32(plen), kp, vp,
            bt)
        self._pools = (kp, vp)
        STEPS.inc(phase="prefill")
        if self._draft is not None:
            dkp, dvp = self._draft_pools
            _, dkp, dvp = self._draft_chunk[Ck](
                self._draft_params, cid, np.int32(start), np.int32(plen),
                dkp, dvp, bt)
            self._draft_pools = (dkp, dvp)
            STEPS.inc(phase="draft")
        req.prefill_pos = start + Ck
        done = req.prefill_pos >= plen
        _perfwatch.record_step(
            "prefill", time.perf_counter() - t0,
            flops=(self._chunk[Ck].current_cost() or {}).get("flops"),
            tokens=1 if done else 0, device_kind=self._device_kind)
        if not done:
            return
        tok0 = int(np.asarray(tok)[0])         # end-of-prefill sync
        if self.config.prefix_cache and req.hashes:
            # contents are final: full prompt blocks are never written
            # again (decode/verify writes land at positions >= plen)
            for j, h in enumerate(req.hashes):
                if (j + 1) * bs <= plen - 1:
                    self._alloc.register(req.blocks[j], h)
        _tracing.record_trace_span(
            "decode.prefill", req.tctx,
            time.monotonic() - req.admitted_at, cat="decode",
            rid=req.rid, chunk=int(Ck), prompt_len=plen,
            reused_blocks=req.n_reused)
        req.pos = plen
        req.draft_pos = plen
        self._prefilling.popleft()
        self._active.append(req)
        self._emit_token(req, tok0, phase="prefill")
        reason = self._finished_reason(req)
        if reason:
            self._finish(req, reason)
        self._kv_gauges()

    def _cow_guard(self, req: _Request, lo: int, hi: int):
        """Copy-on-write safety net: any SHARED block among req's
        block indices [lo, hi] (the imminent write span) is replaced
        by a private device copy before the write. Unreachable in the
        normal flow — shared blocks live strictly inside the prompt
        prefix and writes land at positions >= prompt length — but a
        forced share (tests; future partial-block reuse) must not let
        one sequence corrupt another's prefix."""
        if not self.config.prefix_cache:
            return
        for bi in range(lo, min(hi, len(req.blocks) - 1) + 1):
            blk = req.blocks[bi]
            if not self._alloc.is_shared(blk):
                continue
            new = self._alloc.cow_alloc(blk)
            kp, vp = self._pools
            kp, vp = self._copy_block_fn(kp, vp, blk, new)
            self._pools = (kp, vp)
            if self._draft_pools is not None:
                dkp, dvp = self._draft_pools
                dkp, dvp = self._copy_block_fn(dkp, dvp, blk, new)
                self._draft_pools = (dkp, dvp)
            req.blocks[bi] = new

    def _grow_blocks_sync(self, span: int):
        """Every active slot owns (privately) the blocks its next
        `span` KV writes land in. On pool exhaustion the youngest
        admitted sequence — active or still prefilling — is preempted
        until the round fits."""
        bs = self.kv_cfg.block_size
        while True:
            short = None
            try:
                for req in self._active:
                    lo = req.pos // bs
                    hi = (req.pos + span - 1) // bs
                    while hi >= len(req.blocks):
                        req.blocks.extend(self._alloc.alloc(1))
                    self._cow_guard(req, lo, hi)
            except NoBlocksError:
                short = req
            if short is None:
                return
            candidates = list(self._active) + list(self._prefilling)
            victim = max(candidates, key=self._victim_key)
            self._preempt(victim)
            if not self._active:
                return

    def _step_plain_sync(self):
        """One synchronous decode round: every active slot advances
        one token. With a draft model present (speculation's near-
        max_len fallback) the draft runs the same round in lockstep so
        its KV stays position-aligned for the next spec round."""
        self._grow_blocks_sync(1)
        if not self._active:
            return
        C = self._slot_config()
        sig, slots = self._snapshot(C)
        ids = np.zeros((C,), np.int32)
        positions = np.zeros((C,), np.int32)
        bts = np.zeros((C, self.kv_cfg.max_blocks_per_seq), np.int32)
        for i, req in enumerate(slots):
            if req is None:
                continue
            ids[i] = req.last_token
            positions[i] = req.pos
            bts[i] = build_block_table(req.blocks,
                                       self.kv_cfg.max_blocks_per_seq)
        t0 = time.perf_counter()
        kp, vp = self._pools
        tok, kp, vp = self._decode[C](self.params, ids, positions, kp,
                                      vp, bts)
        self._pools = (kp, vp)
        if self._draft is not None:
            self._draft_catch_up()
            dkp, dvp = self._draft_pools
            _, dkp, dvp = self._draft_decode[C](
                self._draft_params, ids, positions, dkp, dvp, bts)
            self._draft_pools = (dkp, dvp)
            STEPS.inc(phase="draft")
        toks = np.asarray(tok)                 # synchronous resolve
        wall = time.perf_counter() - t0
        STEP_SECONDS.observe(wall)
        STEPS.inc(phase="decode")
        occupied = sum(1 for r in slots if r is not None)
        OCCUPANCY.observe(occupied / C)
        self._last_slot_config = C
        _perfwatch.record_step(
            "decode", wall,
            flops=(self._decode[C].current_cost() or {}).get("flops"),
            tokens=occupied, device_kind=self._device_kind)
        for i, req in enumerate(slots):
            if req is None or req not in self._active:
                continue
            req.pos += 1
            if self._draft is not None:
                req.draft_pos = req.pos
            self._emit_token(req, int(toks[i]), phase="decode")
            reason = self._finished_reason(req)
            if reason:
                self._finish(req, reason)

    def _draft_catch_up(self):
        """After a fully-accepted spec round the draft's KV trails the
        target by EXACTLY one position (the round's bonus token never
        passed through the draft). One batched draft step feeds each
        lagging slot the token AT its missing position; non-lagging
        slots ride along with all-zero block tables, so their writes
        land in the null block."""
        if not any(r.draft_pos < r.pos for r in self._active):
            return
        C = self._slot_config()
        sig, slots = self._snapshot(C)
        ids = np.zeros((C,), np.int32)
        positions = np.zeros((C,), np.int32)
        bts = np.zeros((C, self.kv_cfg.max_blocks_per_seq), np.int32)
        for i, req in enumerate(slots):
            if req is None or req.draft_pos >= req.pos:
                continue
            # token at position pos-1 is the second-newest emission
            ids[i] = req.generated[-2] if len(req.generated) >= 2 \
                else int(req.prompt[-1])
            positions[i] = req.draft_pos
            bts[i] = build_block_table(req.blocks,
                                       self.kv_cfg.max_blocks_per_seq)
        dkp, dvp = self._draft_pools
        _, dkp, dvp = self._draft_decode[C](
            self._draft_params, ids, positions, dkp, dvp, bts)
        self._draft_pools = (dkp, dvp)
        STEPS.inc(phase="draft")
        for req in slots:
            if req is not None and req.draft_pos < req.pos:
                req.draft_pos += 1

    def _step_spec(self):
        """One speculation round: k device-chained draft proposals,
        one batched target verification, exact greedy accept — the
        emitted stream is bit-identical to plain decode, at up to k+1
        tokens per target step. A slot too close to max_len for the
        k+1-token span demotes the WHOLE round to the plain path (the
        batch always runs one program per round)."""
        k = self.spec_k
        if any(r.pos + k > self.kv_cfg.max_len - 1
               for r in self._active):
            self._step_plain_sync()
            return
        self._grow_blocks_sync(k + 1)
        if not self._active:
            return
        self._draft_catch_up()
        C = self._slot_config()
        sig, slots = self._snapshot(C)
        ids = np.zeros((C,), np.int32)
        positions = np.zeros((C,), np.int32)
        bts = np.zeros((C, self.kv_cfg.max_blocks_per_seq), np.int32)
        for i, req in enumerate(slots):
            if req is None:
                continue
            ids[i] = req.last_token
            positions[i] = req.pos
            bts[i] = build_block_table(req.blocks,
                                       self.kv_cfg.max_blocks_per_seq)
        t0 = time.perf_counter()
        # k draft steps, each feeding the previous step's DEVICE token
        # — the chain dispatches without a host sync
        dkp, dvp = self._draft_pools
        dtok = ids
        drafts = []
        for j in range(k):
            dtok, dkp, dvp = self._draft_decode[C](
                self._draft_params, dtok,
                (positions + j).astype(np.int32), dkp, dvp, bts)
            drafts.append(dtok)
            STEPS.inc(phase="draft")
        self._draft_pools = (dkp, dvp)
        ids_v = np.empty((C, k + 1), np.int32)
        ids_v[:, 0] = ids
        for j, d in enumerate(drafts):         # draft-chain sync point
            ids_v[:, j + 1] = np.asarray(d)
        kp, vp = self._pools
        vtok, kp, vp = self._verify[C](self.params, ids_v, positions,
                                       kp, vp, bts)
        self._pools = (kp, vp)
        STEPS.inc(phase="verify")
        outs = np.asarray(vtok)                # [C, k+1]
        wall = time.perf_counter() - t0
        STEP_SECONDS.observe(wall)
        occupied = sum(1 for r in slots if r is not None)
        OCCUPANCY.observe(occupied / C)
        self._last_slot_config = C
        emitted = 0
        for i, req in enumerate(slots):
            if req is None or req not in self._active:
                continue
            props = [int(x) for x in ids_v[i, 1:]]
            row = [int(x) for x in outs[i]]
            a = _kvr.accept_length(props, row)
            self._spec_proposed += k
            self._spec_accepted += a
            pos0 = req.pos
            remaining = req.max_new - len(req.generated)
            emit = []
            for t in row[:min(a + 1, remaining)]:
                emit.append(t)
                if t == self.eos_id:
                    break
            req.pos = pos0 + len(emit)
            # full accept leaves the draft one position behind (the
            # bonus token o_k never passed through it); any rejection
            # lands draft_pos exactly at the new pos
            req.draft_pos = min(pos0 + k, req.pos)
            for t in emit:
                self._emit_token(req, int(t), phase="decode")
            emitted += len(emit)
            reason = self._finished_reason(req)
            if reason:
                self._finish(req, reason)
        if self._spec_proposed:
            _kvr.SPEC_ACCEPT_RATE.set(
                self._spec_accepted / self._spec_proposed)
        _perfwatch.record_step(
            "decode", wall,
            flops=(self._verify[C].current_cost() or {}).get("flops"),
            tokens=emitted, device_kind=self._device_kind)

    def _loop_sync(self):
        try:
            while True:
                with self._cv:
                    while not self._closed and not self._waiting \
                            and not self._active \
                            and not self._prefilling:
                        self._cv.wait(timeout=0.5)
                    if self._closed:
                        break
                self._sweep_cancelled()
                self._admit_sync()
                self._pump_chunk()             # one slice per iteration
                if not self._active:
                    continue
                if self.spec_k:
                    self._step_spec()
                else:
                    self._step_plain_sync()
        except BaseException as e:  # scheduler death must not hang clients
            with self._cv:
                reqs = (list(self._active) + list(self._prefilling) +
                        list(self._waiting))
                self._waiting.clear()
            for req in reqs:
                req.error = RuntimeError(
                    f"decode scheduler failed: {type(e).__name__}: {e}")
                req.error.__cause__ = e
                self._finish(req, "error")
            raise
        finally:
            with self._cv:
                reqs = (list(self._active) + list(self._prefilling) +
                        list(self._waiting))
                self._waiting.clear()
                QUEUE_DEPTH.set(0)
            for req in reqs:
                self._finish(req, "cancelled")
