"""Dynamic-batching TPU inference serving (SERVING.md is the guide).

The synchronous `inference.Predictor` is a library; this package is the
deployment surface in front of it:

- bucketing.py — shape-bucket policy (powers-of-two batch buckets with
                 pad/slice helpers) shared with the Predictor, so any
                 batch size maps onto a small AOT-warmable signature
                 set.
- batcher.py   — bounded request queue + coalescing thread: largest
                 fitting bucket under a max_wait_ms deadline,
                 per-request timeouts, reject-not-block admission
                 control, graceful drain.
- engine.py    — Predictor wrapped with bucket-aware dispatch, AOT
                 warmup of every bucket at startup, per-bucket
                 latency/count accounting.
- kv_cache.py  — paged/blocked KV cache: preallocated device block
                 pool + host-side allocator + per-sequence block
                 tables, so decode memory scales with live tokens.
- kv_reuse.py  — block-level KV reuse: ref-counted allocator with a
                 content-hash prefix index (LRU retention, COW) and
                 the speculative-decoding accept rule (SERVING.md
                 §KV reuse).
- decode.py    — continuous-batching autoregressive decode engine:
                 prefill/decode phase split, in-flight batching,
                 streaming token handles, warmstart phase-grid bake,
                 chunked prefill + prefix caching + speculative
                 decoding (SERVING.md §Continuous batching, §KV
                 reuse).
- httpd.py     — JSON-over-HTTP frontend (POST /v1/predict, chunked
                 POST /v1/generate token streaming, GET /v1/status
                 /v1/models, the /v1/load probe + stateful
                 /v1/healthz) on the shared observability HTTP base;
                 multi-model Server (one engine+batcher slot per model
                 id) with zero-downtime hot-swap.
- qos.py       — per-tenant QoS (SERVING.md §Multi-tenancy): tier/
                 weight/quota policy, start-time-fair weighted token
                 scheduling, shed-lowest-tier-first admission and the
                 typed ShedError behind the Retry-After 503.
- registry.py  — content-addressed model registry: publish warmstart
                 artifacts under digest, replicas watch and hot-swap
                 new versions with zero failed requests.
- router.py    — fleet front tier (SERVING.md §Fleet): power-of-two-
                 choices load balancing over N replicas, health
                 ejection, per-endpoint circuit breakers, idempotent
                 retry failover, rendezvous-backed elastic membership.
- replica.py   — one fleet replica process (warmstart boot, rendezvous
                 heartbeat, SIGTERM → leave/drain/stop).
- autoscale.py — queue-depth/p99 control loop moving the replica count
                 within min/max bounds with hysteresis.

Telemetry flows through the PR 1/2 observability stack: queue depth,
batch-size/queue-wait/end-to-end histograms, reject/timeout counters,
per-bucket compile events — all visible on the /metrics endpoint and
the JSONL event log. `tools/serve_bench.py` load-tests the whole path.
"""

from .bucketing import BucketPolicy, common_batch  # noqa: F401
from .batcher import (  # noqa: F401
    Batcher, EngineError, QueueFullError, RequestTimeout, ServerClosed,
)
from .engine import Engine, ServingConfig  # noqa: F401
from .kv_cache import BlockAllocator, KVCacheConfig, NoBlocksError  # noqa: F401
from .kv_reuse import ReuseBlockAllocator, accept_length, hash_blocks  # noqa: F401
from .decode import DecodeConfig, DecodeEngine, DecodeHandle  # noqa: F401
from .httpd import Server  # noqa: F401
from .qos import (  # noqa: F401
    QoSPolicy, ShedError, TenantSpec, WeightedFairScheduler,
)
from .registry import ModelRegistry, RegistryError  # noqa: F401
from .router import (  # noqa: F401
    FleetError, FleetTimeout, NoReplicasError, ReplicaRejected, Router,
    RouterServer, StreamBrokenError, TierShed,
)
from .autoscale import Autoscaler  # noqa: F401

__all__ = [
    "BucketPolicy", "common_batch",
    "Batcher", "EngineError", "QueueFullError", "RequestTimeout",
    "ServerClosed",
    "Engine", "ServingConfig", "Server",
    "BlockAllocator", "KVCacheConfig", "NoBlocksError",
    "ReuseBlockAllocator", "accept_length", "hash_blocks",
    "DecodeConfig", "DecodeEngine", "DecodeHandle",
    "QoSPolicy", "ShedError", "TenantSpec", "WeightedFairScheduler",
    "ModelRegistry", "RegistryError",
    "Router", "RouterServer", "Autoscaler",
    "FleetError", "NoReplicasError", "ReplicaRejected", "FleetTimeout",
    "StreamBrokenError", "TierShed",
]
