"""Per-tenant QoS: priority tiers, weighted-fair token scheduling,
quota enforcement, and tier-ordered overload shedding (SERVING.md
§Multi-tenancy).

A multi-tenant replica must degrade *by tier*, not globally: the old
behavior — one `QueueFullError` 503 for whoever arrives after the
queue fills — lets a single misbehaving low-priority tenant starve
everyone, because arrival order is the only admission signal. This
module supplies the three mechanisms the batcher and the decode
scheduler compose instead:

- **Tiers** (`QoSPolicy`): an ordered list of named priority classes,
  highest first. Every tenant maps to a tier (unknown tenants land on
  `default_tier`). Admission and preemption order is strict across
  tiers: a lower tier never displaces a higher one.
- **Weighted-fair scheduling** (`WeightedFairScheduler`): within a
  tier, tenants share service in proportion to their configured
  weights via start-time fair queuing — each tenant carries a virtual
  time advanced by `tokens / weight` per unit of service, and the
  scheduler always picks the backlogged tenant with the smallest
  virtual time. A tenant arriving after idling starts at the system
  virtual time (no banked credit), so fairness is over *backlogged*
  periods, the textbook SFQ property.
- **Shedding** (`ShedError`, `shed_victim`): under queue pressure the
  victim is the lowest-tier request, newest first within the tier —
  never simply the arriving request. The HTTP layer maps `ShedError`
  to a typed 503 (`{"shed": "<tier>"}` + Retry-After) that the fleet
  router classifies as an *answer*, not a failure to retry elsewhere:
  re-sending a deliberately shed request to a surviving replica
  amplifies exactly the overload the shed is relieving.

Quotas bound a single tenant's concurrent footprint (queued +
in-flight) regardless of pressure, so one tenant cannot occupy every
slot even when the system is otherwise idle.

The scheduler takes an injectable clock for its idle bookkeeping so
share math is unit-testable without wall time.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

from ..observability import metrics as _m
from .batcher import QueueFullError

__all__ = ["QoSPolicy", "ShedError", "TenantSpec",
           "WeightedFairScheduler", "shed_victim"]

# Shed accounting is the overload story's primary evidence: the
# noisy-neighbor gate asserts sheds land on the flooding tier ONLY.
SHEDS = _m.counter(
    "paddle_tpu_serving_sheds_total",
    "Requests shed by QoS admission, by victim tier and cause "
    "(kind=queue|quota)", labelnames=("tier", "kind"))
TENANT_REQUESTS = _m.counter(
    "paddle_tpu_serving_tenant_requests_total",
    "Per-tenant request outcomes (ok|rejected|shed|timeout|error for "
    "the batcher; eos|length|... for decode)",
    labelnames=("tenant", "tier", "outcome"))
TENANT_TOKENS = _m.counter(
    "paddle_tpu_serving_tenant_tokens_total",
    "Generated tokens per tenant (decode engine)",
    labelnames=("tenant",))
TENANT_REQUEST_SECONDS = _m.histogram(
    "paddle_tpu_serving_tenant_request_seconds",
    "End-to-end predict latency per tenant (successful only)",
    labelnames=("tenant",))
TENANT_TTFT_SECONDS = _m.histogram(
    "paddle_tpu_decode_tenant_ttft_seconds",
    "Time to first generated token per tenant",
    labelnames=("tenant",))

DEFAULT_TENANT = "default"


class ShedError(QueueFullError):
    """This request (or its victim's caller) was deliberately shed by
    QoS admission. Maps to HTTP 503 with a typed body
    `{"shed": "<tier>", "kind": "queue"|"quota"}` and a Retry-After
    header; the fleet router treats it as an answer, not a retryable
    replica failure."""

    def __init__(self, msg: str, *, tenant: str, tier: str,
                 kind: str = "queue", retry_after_s: float = 1.0):
        super().__init__(msg)
        self.tenant = tenant
        self.tier = tier
        self.kind = kind
        self.retry_after_s = float(retry_after_s)


class TenantSpec:
    """One tenant's QoS contract: its tier, its weight within the tier
    (share of service under contention), and an optional cap on
    concurrent requests (queued + in-flight; None = unlimited)."""

    def __init__(self, tier: Optional[str] = None, weight: float = 1.0,
                 max_inflight: Optional[int] = None):
        self.tier = tier
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        self.max_inflight = None if max_inflight is None \
            else int(max_inflight)


class QoSPolicy:
    """Tier order + per-tenant specs. Tiers are listed highest-priority
    FIRST; unknown tenants land on `default_tier` (the last = lowest
    tier unless overridden) with weight 1 and no quota."""

    def __init__(self, tiers: Sequence[str] = ("high", "normal", "low"),
                 tenants: Optional[Dict[str, TenantSpec]] = None,
                 default_tier: Optional[str] = None):
        if not tiers:
            raise ValueError("QoSPolicy needs at least one tier")
        self.tiers = tuple(str(t) for t in tiers)
        if len(set(self.tiers)) != len(self.tiers):
            raise ValueError(f"duplicate tier names: {self.tiers}")
        self.default_tier = self.tiers[-1] if default_tier is None \
            else str(default_tier)
        if self.default_tier not in self.tiers:
            raise ValueError(
                f"default_tier {self.default_tier!r} not in {self.tiers}")
        self.tenants: Dict[str, TenantSpec] = dict(tenants or {})
        for name, spec in self.tenants.items():
            if spec.tier is not None and spec.tier not in self.tiers:
                raise ValueError(
                    f"tenant {name!r} names unknown tier {spec.tier!r}; "
                    f"tiers are {self.tiers}")

    @classmethod
    def from_spec(cls, spec) -> Optional["QoSPolicy"]:
        """Coerce a config value into a policy: None passes through
        (QoS off), a QoSPolicy passes through, a dict is the JSON
        shape replica CLIs load from --qos files:

            {"tiers": ["gold", "bronze"], "default_tier": "bronze",
             "tenants": {"acme": {"tier": "gold", "weight": 3,
                                  "max_inflight": 8}}}
        """
        if spec is None or isinstance(spec, cls):
            return spec
        if not isinstance(spec, dict):
            raise TypeError(f"qos spec must be a dict or QoSPolicy, "
                            f"got {type(spec).__name__}")
        tenants = {str(name): TenantSpec(**dict(ts))
                   for name, ts in (spec.get("tenants") or {}).items()}
        return cls(tiers=spec.get("tiers", ("high", "normal", "low")),
                   tenants=tenants,
                   default_tier=spec.get("default_tier"))

    def tier_of(self, tenant: Optional[str]) -> str:
        spec = self.tenants.get(tenant or DEFAULT_TENANT)
        if spec is not None and spec.tier is not None:
            return spec.tier
        return self.default_tier

    def tier_rank(self, tier: str) -> int:
        """0 = highest priority; unknown tiers rank below every
        configured one (shed first, admitted last)."""
        try:
            return self.tiers.index(tier)
        except ValueError:
            return len(self.tiers)

    def rank_of(self, tenant: Optional[str]) -> int:
        return self.tier_rank(self.tier_of(tenant))

    def weight_of(self, tenant: Optional[str]) -> float:
        spec = self.tenants.get(tenant or DEFAULT_TENANT)
        return spec.weight if spec is not None else 1.0

    def quota_of(self, tenant: Optional[str]) -> Optional[int]:
        spec = self.tenants.get(tenant or DEFAULT_TENANT)
        return spec.max_inflight if spec is not None else None

    def spec_dict(self) -> Dict:
        """The from_spec-shaped dict (for /v1/status and obsdump)."""
        return {
            "tiers": list(self.tiers),
            "default_tier": self.default_tier,
            "tenants": {
                name: {"tier": ts.tier, "weight": ts.weight,
                       "max_inflight": ts.max_inflight}
                for name, ts in sorted(self.tenants.items())},
        }


class WeightedFairScheduler:
    """Start-time fair queuing over tenants, tier-priority first.

    `pick(tenants)` returns the index of the candidate to serve next:
    strict tier order across tiers, minimum virtual time within a
    tier, submission order as the tie-break. `charge(tenant, tokens)`
    advances the served tenant's virtual time by tokens/weight. The
    system virtual time (`_vbase`) tracks the served minimum, so a
    tenant returning from idle starts at the current frontier instead
    of cashing in its idle period.

    Callers serialize access under their own scheduler lock (the
    batcher/decode `_cv`); the instance-level lock exists for direct
    use outside one, and is a leaf in the lock order."""

    def __init__(self, policy: QoSPolicy, clock=time.monotonic):
        self.policy = policy
        self._clock = clock
        self._vt: Dict[str, float] = {}
        self._vbase = 0.0
        self._served: Dict[str, float] = {}  # cumulative tokens (stats)
        # deferred import: the analysis package must not load during
        # package bootstrap; constructors only run after it
        from ..analysis import lockcheck as _lockcheck

        self._lock = _lockcheck.Lock(
            "serving.qos.WeightedFairScheduler._lock")

    def vtime(self, tenant: str) -> float:
        with self._lock:
            return max(self._vt.get(tenant, self._vbase), self._vbase)

    def served(self, tenant: str) -> float:
        with self._lock:
            return self._served.get(tenant, 0.0)

    def pick(self, tenants: Sequence[str]) -> int:
        """Index of the next candidate to serve: (tier rank, virtual
        time, position). Advances the system virtual time to the
        winner's start tag — the SFQ v(t) approximation."""
        if not tenants:
            raise ValueError("pick() needs at least one candidate")
        pol = self.policy
        with self._lock:
            best, best_key = 0, None
            for i, t in enumerate(tenants):
                key = (pol.rank_of(t),
                       max(self._vt.get(t, self._vbase), self._vbase), i)
                if best_key is None or key < best_key:
                    best, best_key = i, key
            self._vbase = max(self._vbase, best_key[1])
            return best

    def charge(self, tenant: str, tokens: float):
        """Record `tokens` of service for `tenant` (rows for the
        batcher, generated tokens for decode)."""
        w = self.policy.weight_of(tenant)
        with self._lock:
            v = max(self._vt.get(tenant, self._vbase), self._vbase)
            self._vt[tenant] = v + float(tokens) / w
            self._served[tenant] = self._served.get(tenant, 0.0) \
                + float(tokens)

    def served_shares(self) -> Dict[str, float]:
        with self._lock:
            total = sum(self._served.values())
            if total <= 0:
                return {}
            return {t: s / total for t, s in self._served.items()}


def shed_victim(entries: Iterable[Tuple[str, float]],
                policy: QoSPolicy) -> int:
    """Index of the request to shed under queue pressure: lowest tier
    first, newest first within the tier. `entries` is (tenant,
    order_key) with order_key increasing by arrival (a sequence number
    or enqueue timestamp). The caller includes the INCOMING request as
    the final entry, so an arrival that outranks everything queued
    displaces the queued victim instead of being bounced itself."""
    entries = list(entries)
    if not entries:
        raise ValueError("shed_victim() needs at least one entry")
    return max(range(len(entries)),
               key=lambda i: (policy.rank_of(entries[i][0]),
                              entries[i][1]))
