"""Content-addressed model registry: publish warmstart artifacts,
adopt them on live replicas without a restart (SERVING.md
§Multi-tenancy, "Model registry & hot-swap").

The registry is a directory:

    <root>/registry.json          the manifest (atomic JSON)
    <root>/blobs/<sha256>         content-addressed artifact blobs

`publish()` copies a model's warmstart artifact (PR 6 `Engine.
export_warmstart` / `DecodeEngine.export_warmstart` output) into the
blob store under its own sha256 and records a manifest entry
`{model_id: {version, digest, model_digest, model_dir, path, ...}}`.
Publishing re-derives the model digest from `model_dir/__model__` and
REFUSES an artifact whose embedded `model_digest` disagrees — the
registry must never hand a replica an artifact baked from a different
program than the directory it names (same bucket signatures, different
computation: the silent wrong-answer failure mode the PR 6 binding
checks exist to kill).

`resolve()` returns the entry after re-hashing the blob against its
recorded digest, so a torn or tampered blob is rejected at adoption
time, not served. Versions increase monotonically per model id;
`Server.attach_registry` polls the manifest and hot-swaps a model slot
when its version moves — the adopting replica pays deserialization
I/O, not XLA, so the swap happens with zero failed requests and zero
fresh compiles.

The manifest is written through `resilience.atomic` (rename-commit):
concurrent publishers serialize on the registry lock within a process,
and cross-process readers never observe a torn manifest.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Dict, Optional

from ..observability import events as _events
from ..observability import metrics as _m

__all__ = ["ModelRegistry", "RegistryError"]

MANIFEST = "registry.json"

PUBLISHES = _m.counter(
    "paddle_tpu_registry_publishes_total",
    "Artifacts published into the model registry, by model id",
    labelnames=("model",))
MODEL_VERSION = _m.gauge(
    "paddle_tpu_model_version",
    "Latest registry version per model id (on the publisher); the "
    "adopted version per model slot (on a serving replica)",
    labelnames=("model",))


class RegistryError(RuntimeError):
    """Publish/resolve refused: digest mismatch, unknown model, or a
    corrupt blob/manifest."""


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _artifact_model_digest(path: str) -> Optional[str]:
    """The `model_digest` a warmstart artifact was baked against
    (None when the artifact is unreadable or carries none)."""
    import pickle

    try:
        with open(path, "rb") as f:
            art = pickle.loads(f.read())
        if isinstance(art, dict):
            return art.get("model_digest")
    except Exception:  # lint-exempt:swallow: unreadable/alien artifact carries no digest — publish() then requires an explicit model_dir
        pass
    return None


class ModelRegistry:
    """Digest-addressed store of serving artifacts, one manifest entry
    per model id. Thread-safe within a process; cross-process safe for
    one publisher + many readers (atomic manifest replace)."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(os.path.join(self.root, "blobs"), exist_ok=True)
        # deferred import: the analysis package must not load during
        # package bootstrap; constructors only run after it
        from ..analysis import lockcheck as _lockcheck

        self._lock = _lockcheck.Lock(
            "serving.registry.ModelRegistry._lock")

    # -- manifest ------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST)

    def _read_manifest(self) -> Dict[str, Dict]:
        try:
            with open(self._manifest_path()) as f:
                man = json.load(f)
        except FileNotFoundError:
            return {}
        except (OSError, ValueError) as e:
            raise RegistryError(
                f"unreadable registry manifest "
                f"{self._manifest_path()}: {e}")
        if not isinstance(man, dict):
            raise RegistryError("registry manifest is not a JSON object")
        return man

    def models(self) -> Dict[str, Dict]:
        """Snapshot of every model's latest entry (manifest read)."""
        with self._lock:
            return self._read_manifest()

    def version(self, model_id: str) -> Optional[int]:
        """Latest published version for `model_id` (None = never
        published) — the cheap probe the hot-swap watcher polls."""
        entry = self.models().get(str(model_id))
        return None if entry is None else int(entry["version"])

    # -- publish / resolve ---------------------------------------------

    def publish(self, model_id: str, warmstart: str,
                model_dir: Optional[str] = None,
                meta: Optional[Dict] = None) -> Dict:
        """Copy `warmstart` into the blob store and point `model_id`'s
        manifest entry at it; returns the new entry. When `model_dir`
        is given, the artifact's embedded model digest must match the
        directory's `__model__` program — mismatch raises
        RegistryError (the artifact was baked from a different
        program). Decode warmstarts (no model_dir) bind through the
        artifact's own digest, which the adopting engine re-checks."""
        from .engine import Engine

        model_id = str(model_id)
        if not os.path.exists(warmstart):
            raise RegistryError(f"no warmstart artifact at {warmstart}")
        art_digest = _artifact_model_digest(warmstart)
        dir_digest = Engine._digest_model_file(model_dir)
        if model_dir is not None:
            if dir_digest is None:
                raise RegistryError(
                    f"model_dir {model_dir} has no readable __model__ "
                    "program to digest")
            if art_digest != dir_digest:
                raise RegistryError(
                    f"digest mismatch publishing {model_id!r}: artifact "
                    f"{warmstart} was baked against model_digest "
                    f"{art_digest} but {model_dir}/__model__ hashes to "
                    f"{dir_digest} — rebake the artifact from this "
                    "program")
        blob_digest = _sha256_file(warmstart)
        blob_path = os.path.join(self.root, "blobs", blob_digest)
        with self._lock:
            if not os.path.exists(blob_path):
                # stage + rename: a concurrent reader must never open a
                # half-copied blob under its final (content) name
                tmp = blob_path + ".staging"
                shutil.copyfile(warmstart, tmp)
                os.replace(tmp, blob_path)
            man = self._read_manifest()
            prev = man.get(model_id)
            entry = {
                "model_id": model_id,
                "version": (int(prev["version"]) + 1) if prev else 1,
                "digest": blob_digest,
                "model_digest": art_digest,
                "model_dir": model_dir,
                "path": blob_path,
                "published_at": time.time(),
                "meta": dict(meta or {}),
            }
            man[model_id] = entry
            from ..resilience.atomic import json_dump

            json_dump(man, self._manifest_path(), indent=2,
                      sort_keys=True)
        PUBLISHES.inc(model=model_id)
        MODEL_VERSION.set(entry["version"], model=model_id)
        _events.emit("registry", action="publish", model=model_id,
                     version=entry["version"], digest=blob_digest[:16],
                     model_digest=(art_digest or "")[:16])
        return entry

    def resolve(self, model_id: str) -> Dict:
        """The latest entry for `model_id` with its blob verified
        against the recorded content digest. RegistryError on an
        unknown model or a blob whose bytes no longer hash to the
        manifest's digest (torn copy, tampering, pruned store)."""
        entry = self.models().get(str(model_id))
        if entry is None:
            raise RegistryError(
                f"model {model_id!r} is not in the registry "
                f"({self._manifest_path()})")
        path = entry.get("path") or ""
        if not os.path.exists(path):
            raise RegistryError(
                f"registry blob missing for {model_id!r}: {path}")
        actual = _sha256_file(path)
        if actual != entry.get("digest"):
            raise RegistryError(
                f"registry blob for {model_id!r} fails its digest "
                f"check (manifest {entry.get('digest')}, actual "
                f"{actual}) — refusing to adopt a corrupt artifact")
        return dict(entry)
