"""Bounded-queue micro-batcher: coalesce concurrent requests into
bucket-shaped batches under a latency deadline.

The core serving trade (Clipper NSDI'17, ORCA OSDI'22): a request
arriving alone pays the full host round-trip for a bs=1 dispatch, but
requests arriving together can share one bucket-shaped dispatch —
accelerator throughput scales with batch size far below the roofline
while per-dispatch overhead is flat. The batcher thread takes the
oldest pending request, waits up to `max_wait_ms` for companions that
fit the same signature, concatenates them up to the largest bucket, and
dispatches once.

Admission control is reject-not-block: when `max_queue` requests are
already pending, `submit()` raises `QueueFullError` immediately (the
HTTP frontend maps it to 503) — queueing beyond capacity only converts
overload into timeouts for everyone. Each request also carries its own
deadline; expired requests are dropped at dispatch time and their
callers get `RequestTimeout` (504). `stop()` drains: no new admissions,
pending work completes, the thread exits.

With a QoS policy attached (`Batcher(qos=...)`, SERVING.md
§Multi-tenancy) the overload answer becomes tiered instead of global:
a full queue sheds the lowest-tier request (newest first within the
tier) — which may be a QUEUED victim rather than the arrival — via
`qos.ShedError`; per-tenant quotas cap one tenant's concurrent
footprint; and the batch head is picked by (tier, weighted-fair
virtual time) instead of strict FIFO, so tenants within a tier share
dispatch rows in proportion to their weights.

Requests coalesce only when their non-batch signature (feed names,
trailing dims, dtypes) matches — mixed-signature traffic simply forms
separate batches.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING, Callable, Dict, List, Optional

import numpy as np

from ..observability import events as _events
from ..observability import metrics as _m
from ..observability import tracing as _tracing
from .bucketing import BucketPolicy, common_batch

if TYPE_CHECKING:  # qos.py imports batcher; runtime import is deferred
    from .qos import WeightedFairScheduler

__all__ = ["Batcher", "EngineError", "QueueFullError", "RequestTimeout",
           "ServerClosed"]


class QueueFullError(RuntimeError):
    """Admission control: max_queue requests already pending (HTTP 503)."""


class ServerClosed(RuntimeError):
    """Submitted during/after shutdown drain (HTTP 503)."""


class RequestTimeout(RuntimeError):
    """The request missed its deadline while queued or in flight (504)."""


class EngineError(RuntimeError):
    """The engine raised while executing a dispatched batch (HTTP 500).
    Distinct from pre-enqueue validation ValueErrors (HTTP 400): a model
    failure is the server's fault, not the client's — the original
    exception is chained as __cause__."""


QUEUE_DEPTH = _m.gauge(
    "paddle_tpu_serving_queue_depth",
    "Requests waiting in the batcher queue")
QUEUE_WAIT_SECONDS = _m.histogram(
    "paddle_tpu_serving_queue_wait_seconds",
    "Seconds a request waited in the queue before dispatch")
REQUEST_SECONDS = _m.histogram(
    "paddle_tpu_serving_request_seconds",
    "End-to-end request latency (submit to result, successful only)")
REQUESTS = _m.counter(
    "paddle_tpu_serving_requests_total",
    "Requests by outcome (ok|rejected|timeout|error)",
    labelnames=("outcome",))
BATCH_ROWS = _m.histogram(
    "paddle_tpu_serving_batch_rows",
    "Real (pre-padding) rows per dispatched batch",
    buckets=_m.exponential_buckets(1, 2, 12))


class _Request:
    __slots__ = ("feeds", "n", "sig", "enqueue_t", "deadline",
                 "event", "result", "error", "tctx", "tenant", "seq")

    def __init__(self, feeds, n, sig, deadline, tenant, seq):
        self.feeds = feeds
        self.n = n
        self.sig = sig
        self.enqueue_t = time.monotonic()
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Optional[Dict[str, np.ndarray]] = None
        self.error: Optional[BaseException] = None
        # captured at submit() on the CALLER's thread: the batcher
        # thread records queue-wait/batch spans against it later
        self.tctx = _tracing.current_trace()
        self.tenant = tenant
        self.seq = seq          # arrival order (shed newest-first key)


def _feed_sig(feeds: Dict[str, np.ndarray]):
    return tuple(sorted((k, v.shape[1:], str(v.dtype))
                        for k, v in feeds.items()))


class Batcher:
    """One daemon thread coalescing `submit()` calls into batches for
    `run_batch` (a callable mapping a feed dict with a common leading
    dim to an output dict with the same leading dim)."""

    def __init__(self, run_batch: Callable[[Dict[str, np.ndarray]],
                                           Dict[str, np.ndarray]],
                 policy: BucketPolicy, max_queue: int = 128,
                 max_wait_ms: float = 5.0, timeout_s: float = 30.0,
                 thread_name: str = "paddle-tpu-serving-batcher",
                 output_batched: Optional[Callable[[str],
                                                   Optional[bool]]] = None,
                 qos=None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._run = run_batch
        self._policy = policy
        # per-tenant QoS (None = single-tenant FIFO, the historical
        # behavior). Deferred import: qos.py imports THIS module for
        # the QueueFullError base class.
        from . import qos as _qos_mod

        self._qosm = _qos_mod
        self._qos = _qos_mod.QoSPolicy.from_spec(qos)
        # annotated so tools/lockgraph.py can type the attribute (the
        # conditional value defeats constructor inference)
        self._wfq: Optional["WeightedFairScheduler"] = \
            _qos_mod.WeightedFairScheduler(self._qos) \
            if self._qos is not None else None
        self._seq = 0               # arrival stamp for shed ordering
        self._inflight_by: Dict[str, int] = {}  # tenant -> dispatched
        # name -> does this output carry the batch dim? (False = share
        # whole, True = split, None/unavailable = shape heuristic). The
        # Engine plumbs the Predictor's declared-shape knowledge here so
        # a fixed leading dim that merely equals the row total is not
        # mis-split across requests.
        self._output_batched = output_batched
        self._max_queue = int(max_queue)
        self._max_wait = float(max_wait_ms) / 1000.0
        self._timeout_s = float(timeout_s)
        # deferred import: the analysis package must not load during
        # package bootstrap; constructors only run after it
        from ..analysis import lockcheck as _lockcheck

        self._cv = _lockcheck.Condition(
            name="serving.batcher.Batcher._cv")
        self._pending: List[_Request] = []
        self._inflight = 0  # requests inside a dispatched batch right now
        self._closed = False
        # per-instance outcome counts (the REQUESTS metric is process-
        # global: concurrent servers would cross-contaminate each
        # other's /v1/status and serve_stop numbers without these)
        self._counts = {"ok": 0, "rejected": 0, "timeout": 0, "error": 0}
        self._batch_seq = 0  # links every member's batch span (tracing)
        self._thread = threading.Thread(target=self._loop,
                                        name=thread_name, daemon=True)
        self._thread.start()

    # -- client side ---------------------------------------------------

    def depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def inflight(self) -> int:
        """Requests currently inside a dispatched (executing) batch —
        together with depth() this is the router's load score
        (SERVING.md §Fleet): queued work plus work on the accelerator."""
        with self._cv:
            return self._inflight

    def draining(self) -> bool:
        with self._cv:
            return self._closed

    def outcome_counts(self) -> Dict[str, int]:
        with self._cv:
            return dict(self._counts)

    def _finish(self, outcome: str, tenant: Optional[str] = None):
        REQUESTS.inc(outcome=outcome)
        if self._qos is not None and tenant is not None:
            self._qosm.TENANT_REQUESTS.inc(
                tenant=tenant, tier=self._qos.tier_of(tenant),
                outcome=outcome)
        with self._cv:
            self._counts[outcome] += 1

    def _shed_locked(self, tenant: str, seq: int) -> None:
        """Queue-full admission under QoS (caller holds _cv): pick the
        shed victim across queued requests AND the arrival — lowest
        tier first, newest first within the tier. A queued victim's
        waiting thread is woken with ShedError (its caller gets the
        typed 503) and the arrival is admitted in its place; when the
        arrival IS the victim, ShedError raises here."""
        qos = self._qos
        entries = [(r.tenant, r.seq) for r in self._pending] \
            + [(tenant, seq)]
        vi = self._qosm.shed_victim(entries, qos)
        v_tenant = entries[vi][0]
        v_tier = qos.tier_of(v_tenant)
        self._qosm.SHEDS.inc(tier=v_tier, kind="queue")
        _events.emit("shed", where="batcher", tenant=v_tenant,
                     tier=v_tier, shed="queue")
        err = self._qosm.ShedError(
            f"queue full ({self._max_queue} pending); shed tier "
            f"{v_tier!r} (tenant {v_tenant!r})",
            tenant=v_tenant, tier=v_tier, kind="queue")
        if vi == len(entries) - 1:
            raise err                       # the arrival is the victim
        victim = self._pending.pop(vi)
        QUEUE_DEPTH.set(len(self._pending))
        victim.error = err
        victim.event.set()

    def submit(self, feeds: Dict[str, np.ndarray],
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Block until the request's rows come back from a dispatched
        batch. Raises QueueFullError / ServerClosed (don't queue),
        RequestTimeout (queued or dispatched but missed the deadline),
        qos.ShedError (tier-shed or over-quota under a QoS policy),
        or the engine's own exception."""
        t0 = time.monotonic()
        feeds = {k: np.asarray(v) for k, v in feeds.items()}
        if not feeds:
            raise ValueError("empty feed dict")
        n = common_batch(feeds)
        if not n:
            raise ValueError("feeds must share a leading batch dim >= 1")
        if n > self._policy.max_batch:
            raise ValueError(
                f"request batch {n} exceeds the largest bucket "
                f"{self._policy.max_batch}; split it client-side")
        tenant = str(tenant) if tenant else self._qosm.DEFAULT_TENANT
        timeout = self._timeout_s if timeout_s is None else float(timeout_s)
        with self._cv:
            if self._closed:
                self._finish("rejected", tenant)
                raise ServerClosed("server is draining; request rejected")
            qos = self._qos
            if qos is not None:
                quota = qos.quota_of(tenant)
                if quota is not None:
                    have = self._inflight_by.get(tenant, 0) + sum(
                        1 for r in self._pending if r.tenant == tenant)
                    if have >= quota:
                        tier = qos.tier_of(tenant)
                        self._qosm.SHEDS.inc(tier=tier, kind="quota")
                        _events.emit("shed", where="batcher",
                                     tenant=tenant, tier=tier,
                                     shed="quota")
                        self._finish("rejected", tenant)
                        raise self._qosm.ShedError(
                            f"tenant {tenant!r} over quota ({quota} "
                            "concurrent); request rejected",
                            tenant=tenant, tier=tier, kind="quota")
            self._seq += 1
            req = _Request(feeds, n, _feed_sig(feeds), t0 + timeout,
                           tenant, self._seq)
            if len(self._pending) >= self._max_queue:
                if qos is None:
                    self._finish("rejected", tenant)
                    raise QueueFullError(
                        f"queue full ({self._max_queue} pending); "
                        "request rejected")
                try:
                    self._shed_locked(tenant, req.seq)
                except QueueFullError:
                    self._finish("rejected", tenant)
                    raise
            self._pending.append(req)
            QUEUE_DEPTH.set(len(self._pending))
            self._cv.notify_all()
        req.event.wait(max(0.0, req.deadline - time.monotonic()))
        if not req.event.is_set():
            # still queued → pull it out so the batcher never runs it;
            # already claimed for a dispatch → result is discarded
            with self._cv:
                if req in self._pending:
                    self._pending.remove(req)
                    QUEUE_DEPTH.set(len(self._pending))
            self._finish("timeout", tenant)
            raise RequestTimeout(f"request timed out after {timeout:g}s")
        if req.error is not None:
            if isinstance(req.error, RequestTimeout):
                self._finish("timeout", tenant)
            elif isinstance(req.error, QueueFullError):
                self._finish("rejected", tenant)  # shed while queued
            else:
                self._finish("error", tenant)
            raise req.error
        self._finish("ok", tenant)
        REQUEST_SECONDS.observe(time.monotonic() - t0)
        if self._qos is not None:
            self._qosm.TENANT_REQUEST_SECONDS.observe(
                time.monotonic() - t0, tenant=tenant)
        return req.result

    # -- batcher thread ------------------------------------------------

    def _collect(self) -> List[_Request]:
        """Wait for work, honor the head request's coalescing window,
        then pull out one signature-compatible batch. Returns [] when
        closed and drained."""
        with self._cv:
            while not self._pending:
                if self._closed:
                    return []
                self._cv.wait()
            if self._wfq is not None:
                # tiered weighted-fair head pick: strict tier priority,
                # minimum virtual time within the tier (FIFO tie-break)
                head = self._pending[self._wfq.pick(
                    [r.tenant for r in self._pending])]
            else:
                head = self._pending[0]
            # coalescing window: dispatch early when a full bucket of
            # compatible rows is waiting (or on drain), else wait out
            # max_wait from the head's enqueue for companions to arrive
            deadline = head.enqueue_t + self._max_wait
            while not self._closed:
                rows = sum(r.n for r in self._pending if r.sig == head.sig)
                left = deadline - time.monotonic()
                if rows >= self._policy.max_batch or left <= 0:
                    break
                self._cv.wait(timeout=left)
                if head not in self._pending:     # head gave up (timeout)
                    return []
            now = time.monotonic()
            batch, rest, total = [], [], 0
            for r in self._pending:
                if r.deadline <= now:
                    r.error = RequestTimeout("expired while queued")
                    r.event.set()
                elif r.sig == head.sig and \
                        total + r.n <= self._policy.max_batch:
                    batch.append(r)
                    total += r.n
                else:
                    rest.append(r)
            self._pending = rest
            # claimed requests count as in-flight from the moment they
            # leave the queue until their batch resolves — the load
            # probe must not report an idle replica mid-dispatch
            self._inflight = len(batch)
            self._inflight_by = {}
            for r in batch:
                self._inflight_by[r.tenant] = \
                    self._inflight_by.get(r.tenant, 0) + 1
            QUEUE_DEPTH.set(len(self._pending))
        return batch

    def _dispatch(self, batch: List[_Request]):
        now = time.monotonic()
        total = sum(r.n for r in batch)
        self._batch_seq += 1
        bid = self._batch_seq
        for r in batch:
            QUEUE_WAIT_SECONDS.observe(now - r.enqueue_t)
            # per-request queue-wait span: the router's p99 question
            # ("did the time go to coalescing wait?") answered per trace
            _tracing.record_trace_span(
                "serve.queue_wait", r.tctx, now - r.enqueue_t,
                cat="serve", rows=r.n, batch=bid, tenant=r.tenant)
            if self._wfq is not None:
                # service charge: dispatched rows advance the tenant's
                # virtual time by rows/weight
                self._wfq.charge(r.tenant, r.n)
        BATCH_ROWS.observe(total)
        feeds = {k: np.concatenate([r.feeds[k] for r in batch], axis=0)
                 for k in batch[0].feeds}
        # the first sampled member's context becomes ambient for the
        # engine dispatch, so engine/executor spans nest under ITS
        # trace; every other sampled member gets a linking span carrying
        # the same batch id (batch membership stays reconstructable)
        lead = next((r.tctx for r in batch
                     if r.tctx is not None and r.tctx.sampled), None)
        t_run = time.monotonic()
        try:
            with _tracing.trace_span("serve.batch", cat="serve",
                                     ctx=lead, batch=bid, rows=total,
                                     members=len(batch)):
                outs = self._run(feeds)
            run_dt = time.monotonic() - t_run
            seen_lead = False
            for r in batch:
                if r.tctx is None or not r.tctx.sampled:
                    continue
                if not seen_lead and r.tctx is lead:
                    seen_lead = True
                    continue
                _tracing.record_trace_span(
                    "serve.batch", r.tctx, run_dt, cat="serve",
                    batch=bid, rows=total, members=len(batch))
            # split per request; outputs that don't carry the batch dim
            # (scalars, per-class stats) are shared whole, not sliced
            def _split(v, flag, off, n):
                if flag is False or not getattr(v, "ndim", 0) \
                        or v.shape[0] != total:
                    return v
                return v[off:off + n]

            flags = {k: self._output_batched(k)
                     if self._output_batched else None for k in outs}
            split, off = [], 0
            for r in batch:
                split.append({k: _split(v, flags[k], off, r.n)
                              for k, v in outs.items()})
                off += r.n
        except BaseException as e:  # engine/split error → every caller
            err = EngineError(f"{type(e).__name__}: {e}")
            err.__cause__ = e
            for r in batch:         # sees it; the batcher thread lives on
                r.error = err
                r.event.set()
            return
        for r, res in zip(batch, split):
            r.result = res
            r.event.set()

    def _loop(self):
        while True:
            batch = self._collect()
            if batch:
                try:
                    self._dispatch(batch)
                finally:
                    with self._cv:
                        self._inflight = 0
                        self._inflight_by = {}
                continue
            with self._cv:
                self._inflight = 0
                self._inflight_by = {}
                if self._closed and not self._pending:
                    return

    # -- lifecycle -----------------------------------------------------

    def stop(self, timeout: float = 30.0):
        """Graceful drain: stop admitting, let pending batches finish,
        join the thread. Idempotent."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
