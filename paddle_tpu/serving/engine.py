"""Bucket-aware serving engine: the Predictor wrapped for batch traffic.

Owns the BucketPolicy, builds the Predictor with bucketing enabled (so
every dispatched batch lands on one of the configured signatures),
AOT-warms every bucket at startup (no live request pays an XLA
compile), and accounts per-bucket dispatch latency and batch counts in
the metrics registry. Compile visibility itself comes from the PR 2
`_JitDispatch` instrumentation inside the Predictor: each bucket's
compile appears in `paddle_tpu_compile_seconds{kind="infer"}` and as a
`compile` event, which is what lets a deployment assert its signature
set stays closed under live traffic.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..core import compile_cache as _cc
from ..inference import AnalysisConfig, Predictor, create_paddle_predictor
from ..observability import events as _events
from ..observability import metrics as _m
from .bucketing import BucketPolicy, common_batch

__all__ = ["ServingConfig", "Engine", "WARMSTART_FORMAT"]

WARMSTART_FORMAT = "paddle_tpu-warmstart-v1"

BUCKET_SECONDS = _m.histogram(
    "paddle_tpu_serving_bucket_seconds",
    "Engine dispatch wall time per bucket (pad + run + slice)",
    labelnames=("bucket",))
BATCHES = _m.counter(
    "paddle_tpu_serving_batches_total",
    "Dispatched batches per bucket", labelnames=("bucket",))
PAD_ROWS = _m.counter(
    "paddle_tpu_serving_pad_rows_total",
    "Padding rows added by bucketing (wasted accelerator rows)")
WARMUP_SECONDS = _m.gauge(
    "paddle_tpu_serving_warmup_seconds",
    "Wall seconds the last warmup spent compiling all buckets")


class ServingConfig:
    """Knobs for the dynamic-batching server (full reference in
    SERVING.md §Configuration)."""

    def __init__(self, model_dir: Optional[str] = None, *,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue: int = 128,
                 max_wait_ms: float = 5.0,
                 timeout_s: float = 30.0,
                 warmup: bool = True,
                 aot: bool = True,
                 warmstart: Optional[str] = None,
                 use_tpu: bool = True,
                 device_id: int = 0,
                 host: Optional[str] = None,
                 port: int = 0):
        self.model_dir = model_dir
        self.buckets = tuple(buckets) if buckets is not None else None
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_wait_ms = float(max_wait_ms)
        self.timeout_s = float(timeout_s)
        self.warmup = bool(warmup)
        self.aot = bool(aot)
        self.warmstart = warmstart
        self.use_tpu = bool(use_tpu)
        self.device_id = int(device_id)
        self.host = host
        self.port = int(port)


class Engine:
    """Predictor + BucketPolicy with warmup and per-bucket accounting.
    `run_batch` is the callable the Batcher dispatches to; it is also
    safe to call directly (single-caller deployments that want bucketing
    without the queue)."""

    def __init__(self, config: ServingConfig,
                 predictor: Optional[Predictor] = None):
        self.config = config
        self.policy = BucketPolicy(max_batch=config.max_batch,
                                   buckets=config.buckets)
        if predictor is None:
            acfg = AnalysisConfig(config.model_dir)
            if not config.use_tpu:
                acfg.disable_gpu()
            acfg._device_id = config.device_id
            if config.aot:
                acfg.enable_aot()
            acfg.enable_bucketing(buckets=self.policy.buckets)
            predictor = create_paddle_predictor(acfg)
        else:
            # an externally built predictor must agree on the signature
            # set or live traffic would compile off-bucket shapes that
            # warmup never touched — the engine's policy wins
            predictor.config._bucketing = self.policy
        self._pred = predictor
        self.warmed = False
        # warmstart artifact: adopt each bucket's serialized executable
        # before warmup() ever runs, so boot pays deserialization I/O,
        # not XLA. A missing/mismatched artifact degrades to normal
        # warmup — never an error at serving boot, but always a
        # `warmstart` reject event (a typo'd path booting a fleet cold
        # must be visible in the log, not just as adopted=0 in status).
        self.warmstart_adopted = 0
        if config.warmstart:
            self.load_warmstart(config.warmstart)

    def output_batched(self, name: str) -> Optional[bool]:
        """Does fetch `name` carry the batch dim? From the Predictor's
        declared shapes (None when unknown — e.g. the native engine —
        letting the batcher fall back to its shape heuristic)."""
        return getattr(self._pred, "_fetch_batched", {}).get(name)

    def warmup(self) -> int:
        """AOT-compile every configured bucket; returns how many bucket
        signatures are ready. Idempotent (per-bucket compiles are cached
        by the Predictor)."""
        t0 = time.perf_counter()
        ready = 0
        for b in self.policy.buckets:
            try:
                if self._pred.warm(b):
                    ready += 1
            except ValueError:
                # dynamic non-batch dims: the first live batch per
                # bucket compiles instead; serving still works
                break
        WARMUP_SECONDS.set(time.perf_counter() - t0)
        self.warmed = True
        return ready

    # -- warmstart artifact (serialized bucket executables) -------------

    def _model_digest(self) -> Optional[str]:
        """Content digest of the served model's program (__model__
        file): an artifact baked from a DIFFERENT program must never be
        adopted — same bucket signatures, different computation. None
        when there is no model dir (externally-built predictor); such
        artifacts match only artifacts also baked without one."""
        d = self.config.model_dir
        if not d:
            return None
        try:
            with open(os.path.join(d, "__model__"), "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return None

    def export_warmstart(self, path: str) -> int:
        """Serialize every warmed bucket executable into ONE artifact
        at `path` (atomic write). Call after warmup(); returns how many
        bucket signatures the artifact carries. The artifact embeds the
        environment meta (jax version/backend/device kind) and the
        model digest, both re-checked at load."""
        entries = self._pred.serialize_warm()
        art = dict(_cc.environment_meta(),
                   format=WARMSTART_FORMAT,
                   model_digest=self._model_digest(),
                   buckets=[int(b) for b in self.policy.buckets],
                   created_at=time.time(),
                   entries=entries)
        from ..resilience.atomic import write_bytes

        write_bytes(path, pickle.dumps(art,
                                       protocol=pickle.HIGHEST_PROTOCOL))
        _events.emit("warmstart", action="export", path=path,
                     entries=len(entries),
                     buckets=[int(b) for b in self.policy.buckets])
        return len(entries)

    def load_warmstart(self, path: str) -> int:
        """Adopt the bucket executables from a warmstart artifact.
        Returns how many signatures were adopted (also reflected in
        `warmstart_adopted` / `/v1/status`); 0 (with a `warmstart`
        reject event) when the artifact is unreadable, from another
        jax/backend/device, or baked from a different model — warmup
        then compiles normally, so a stale artifact costs nothing but
        the cold boot it failed to avoid."""
        self.warmstart_adopted = self._load_warmstart(path)
        return self.warmstart_adopted

    def _load_warmstart(self, path: str) -> int:
        try:
            with open(path, "rb") as f:
                art = pickle.loads(f.read())
            if not isinstance(art, dict) \
                    or art.get("format") != WARMSTART_FORMAT:
                raise ValueError("not a warmstart artifact")
        except Exception as e:
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"unreadable: {str(e)[:200]}")
            return 0
        env = _cc.environment_meta()
        stored = {k: art.get(k) for k in env}
        if stored != env:
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"environment mismatch: artifact "
                                f"{stored} vs process {env}")
            return 0
        digest = self._model_digest()
        if art.get("model_digest") != digest:
            _events.emit("warmstart", action="reject", path=path,
                         reason="model digest mismatch — artifact baked "
                                "from a different program")
            return 0
        try:
            entries = art.get("entries") or {}
            adopted = self._pred.adopt_warm(entries)
        except Exception as e:
            # adopt_warm guards per entry, but an artifact whose
            # entries container itself is malformed must still degrade
            # to a cold boot, never crash Engine construction
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"unadoptable entries: {str(e)[:200]}")
            return 0
        _events.emit("warmstart", action="load", path=path,
                     entries=len(entries), adopted=adopted)
        return adopted

    def run_batch(self, feeds: Dict[str, np.ndarray]
                  ) -> Dict[str, np.ndarray]:
        """One bucket-shaped dispatch: the Predictor pads to the bucket,
        runs the compiled signature, and slices back; this layer adds
        the per-bucket latency/count/padding accounting. The warm path
        goes through the Predictor's lazy fetch handle — dispatch and
        host fetch are separate spans, so the dispatch-to-ready
        histogram (site fetch:infer) shows pure device latency while
        BUCKET_SECONDS keeps the end-to-end view the batcher sizes
        against."""
        n = common_batch(feeds)
        if not n:
            raise ValueError("feeds must share a leading batch dim >= 1")
        bucket = self.policy.bucket_for(n) or n
        t0 = time.perf_counter()
        out = self._pred.predict_handle(**feeds).result()
        BUCKET_SECONDS.observe(time.perf_counter() - t0,
                               bucket=str(bucket))
        BATCHES.inc(bucket=str(bucket))
        if bucket != n:
            PAD_ROWS.inc(bucket - n)
        return out

    def status(self) -> Dict:
        return {
            "buckets": [int(b) for b in self.policy.buckets],
            "warmed": self.warmed,
            "warmstart_adopted": self.warmstart_adopted,
            "batches": {str(b): BATCHES.value(bucket=str(b))
                        for b in self.policy.buckets},
            "feeds": self._pred.get_input_names(),
            "fetches": self._pred.get_output_names(),
        }
