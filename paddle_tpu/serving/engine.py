"""Bucket-aware serving engine: the Predictor wrapped for batch traffic.

Owns the BucketPolicy, builds the Predictor with bucketing enabled (so
every dispatched batch lands on one of the configured signatures),
AOT-warms every bucket at startup (no live request pays an XLA
compile), and accounts per-bucket dispatch latency and batch counts in
the metrics registry. Compile visibility itself comes from the PR 2
`_JitDispatch` instrumentation inside the Predictor: each bucket's
compile appears in `paddle_tpu_compile_seconds{kind="infer"}` and as a
`compile` event, which is what lets a deployment assert its signature
set stays closed under live traffic.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..core import compile_cache as _cc
from ..core import precision as _precision
from ..inference import AnalysisConfig, Predictor, create_paddle_predictor
from ..observability import events as _events
from ..observability import memwatch as _memwatch
from ..observability import metrics as _m
from ..observability import tracing as _tracing
from .bucketing import BucketPolicy, common_batch

__all__ = ["ServingConfig", "Engine", "WARMSTART_FORMAT"]

WARMSTART_FORMAT = "paddle_tpu-warmstart-v1"

# written into the .int8 sibling after calibrate_and_quantize: records
# the sha256 of the SOURCE model's __model__ so later boots with
# calibration= still configured can prove the sibling was quantized
# from this very program and skip recalibration
QUANT_SRC_FILE = "__quant_source__.json"

BUCKET_SECONDS = _m.histogram(
    "paddle_tpu_serving_bucket_seconds",
    "Engine dispatch wall time per bucket (pad + run + slice)",
    labelnames=("bucket",))
BATCHES = _m.counter(
    "paddle_tpu_serving_batches_total",
    "Dispatched batches per bucket", labelnames=("bucket",))
PAD_ROWS = _m.counter(
    "paddle_tpu_serving_pad_rows_total",
    "Padding rows added by bucketing (wasted accelerator rows)")
WARMUP_SECONDS = _m.gauge(
    "paddle_tpu_serving_warmup_seconds",
    "Wall seconds the last warmup spent compiling all buckets")
ACCURACY_DELTA = _m.gauge(
    "paddle_tpu_serving_accuracy_delta",
    "Reduced-precision reply deviation from the f32 reference on the "
    "calibration batches (stat=max_abs|mean_abs), set at engine boot "
    "for int8/bf16 precision", labelnames=("stat",))


class ServingConfig:
    """Knobs for the dynamic-batching server (full reference in
    SERVING.md §Configuration)."""

    def __init__(self, model_dir: Optional[str] = None, *,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue: int = 128,
                 max_wait_ms: float = 5.0,
                 timeout_s: float = 30.0,
                 warmup: bool = True,
                 aot: bool = True,
                 warmstart: Optional[str] = None,
                 use_tpu: bool = True,
                 device_id: int = 0,
                 host: Optional[str] = None,
                 port: int = 0,
                 precision: str = "f32",
                 calibration=None,
                 accuracy_check_batches: int = 4,
                 slo_spec=None,
                 qos=None,
                 model_id: str = "default"):
        self.model_dir = model_dir
        self.buckets = tuple(buckets) if buckets is not None else None
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_wait_ms = float(max_wait_ms)
        self.timeout_s = float(timeout_s)
        self.warmup = bool(warmup)
        self.aot = bool(aot)
        self.warmstart = warmstart
        self.use_tpu = bool(use_tpu)
        self.device_id = int(device_id)
        self.host = host
        self.port = int(port)
        # precision: "f32" (default), "bf16"/"mixed_bf16" (policy-based
        # reduced-precision executables per bucket), or "int8"
        # (calibrated post-training quantization of the saved model —
        # needs `calibration`, a callable returning an iterable of feed
        # dicts, unless a previously quantized sibling dir exists).
        # accuracy_check_batches bounds the boot-time f32-vs-reduced
        # reply comparison that feeds /v1/status accuracy_delta (0
        # disables the check).
        if precision not in ("f32", "bf16", "mixed_bf16", "int8"):
            # typos fail with the policy module's full-list message;
            # valid-but-unserved policies (mixed_f16) must ALSO fail
            # fast — silently serving f32 while status reports the
            # requested name is the wrong-width bug this exists to kill
            _precision.get_policy(precision)
            raise ValueError(
                f"unknown precision policy {precision!r} for serving; "
                "choose from ['f32', 'bf16', 'mixed_bf16', 'int8']")
        self.precision = str(precision)
        self.calibration = calibration
        self.accuracy_check_batches = int(accuracy_check_batches)
        # slo_spec: path to a JSON objectives file (or a spec dict) —
        # Server.start() hands it to observability.slo's background
        # evaluator; recording (PADDLE_TPU_TS_DIR) must be on for the
        # burn rates to have data (PROFILE.md §Time series & SLOs)
        self.slo_spec = slo_spec
        # qos: a serving.qos.QoSPolicy or its from_spec dict (None =
        # single-tenant FIFO); model_id: this config's slot name in a
        # multi-model Server and the fleet's routing key (SERVING.md
        # §Multi-tenancy)
        self.qos = qos
        self.model_id = str(model_id)


class Engine:
    """Predictor + BucketPolicy with warmup and per-bucket accounting.
    `run_batch` is the callable the Batcher dispatches to; it is also
    safe to call directly (single-caller deployments that want bucketing
    without the queue)."""

    def __init__(self, config: ServingConfig,
                 predictor: Optional[Predictor] = None):
        self.config = config
        self.policy = BucketPolicy(max_batch=config.max_batch,
                                   buckets=config.buckets)
        self.precision = getattr(config, "precision", "f32")
        self.accuracy_delta: Optional[Dict] = None
        # the directory whose program is actually served (== model_dir
        # except under int8, where it is the calibrated+quantized
        # sibling); warmstart digests bind to THIS program
        self._served_dir = config.model_dir
        if predictor is None:
            if self.precision == "int8":
                self._served_dir = self._prepare_int8_model()
            acfg = AnalysisConfig(self._served_dir)
            if not config.use_tpu:
                acfg.disable_gpu()
            acfg._device_id = config.device_id
            if config.aot:
                acfg.enable_aot()
            # ALWAYS pin the policy: an explicit ServingConfig precision
            # must win the resolution order over PADDLE_TPU_PRECISION /
            # program attrs. "f32" pins f32; "int8" pins f32 too — the
            # quantized program's int8 math lives in the quantized_*
            # kernels, and its f32 glue must match the f32-computed
            # calibration scales, not an ambient bf16 autocast.
            acfg.set_precision(self.precision
                               if self.precision in ("bf16", "mixed_bf16")
                               else "f32")
            acfg.enable_bucketing(buckets=self.policy.buckets)
            predictor = create_paddle_predictor(acfg)
        else:
            if self.precision == "int8":
                raise ValueError(
                    "ServingConfig(precision='int8') cannot adopt an "
                    "externally built predictor — post-training "
                    "quantization rewrites the saved model; build the "
                    "Engine from model_dir instead")
            have = getattr(getattr(predictor, "_policy", None),
                           "name", None)
            if self.precision != "f32" and have != self.precision:
                raise ValueError(
                    f"externally built predictor was loaded under "
                    f"policy {have or 'f32'!r} but ServingConfig("
                    f"precision={self.precision!r}) was requested — "
                    "status and accuracy accounting would misreport; "
                    "call set_precision on its AnalysisConfig instead")
            # an externally built predictor must agree on the signature
            # set or live traffic would compile off-bucket shapes that
            # warmup never touched — the engine's policy wins
            predictor.config._bucketing = self.policy
        self._pred = predictor
        self.warmed = False
        # warmstart artifact: adopt each bucket's serialized executable
        # before warmup() ever runs, so boot pays deserialization I/O,
        # not XLA. A missing/mismatched artifact degrades to normal
        # warmup — never an error at serving boot, but always a
        # `warmstart` reject event (a typo'd path booting a fleet cold
        # must be visible in the log, not just as adopted=0 in status).
        self.warmstart_adopted = 0
        # boot-time static analysis of the served program (the
        # reference's AnalysisPredictor runs its ir_analysis passes at
        # exactly this point): boot is one-time, so the walk always
        # runs; PADDLE_TPU_VALIDATE=2 refuses to serve a program with
        # error-severity findings, anything less records them in
        # /v1/status + the analysis metrics/event and boots anyway.
        self.analysis: Optional[Dict[str, int]] = self._validate_boot()
        if config.warmstart:
            self.load_warmstart(config.warmstart)
        if self.precision != "f32" and config.model_dir \
                and getattr(config, "calibration", None) is not None \
                and getattr(config, "accuracy_check_batches", 0) > 0:
            self._measure_accuracy_delta()

    def _validate_boot(self) -> Optional[Dict[str, int]]:
        """Static-analysis walk over the served program (None for the
        native engine, which carries no ProgramDesc). AnalysisError
        propagates at PADDLE_TPU_VALIDATE=2 — a fleet must fail a bad
        deploy at boot, not on the first live request."""
        prog = getattr(self._pred, "_program", None)
        if prog is None:
            return None
        from ..analysis import validate_level, validate_program

        findings = validate_program(
            prog.desc,
            feed_names=self._pred.get_input_names(),
            fetch_names=self._pred.get_output_names(),
            policy=getattr(self._pred, "_policy", None),
            is_test=True, level=validate_level(), where="serving")
        out = {"errors": 0, "warnings": 0, "infos": 0}
        for f in findings:
            out[f.severity + "s"] = out.get(f.severity + "s", 0) + 1
        return out

    # -- reduced-precision boot helpers ---------------------------------

    def _calibration_reader(self):
        """`config.calibration` as the callable-returning-an-iterable
        contract slim.quantization.calibrate_and_quantize expects (a
        plain list/tuple of feed dicts is wrapped)."""
        cal = self.config.calibration
        if callable(cal):
            return cal
        return lambda: iter(cal)

    def _prepare_int8_model(self) -> str:
        """Calibrate + quantize the saved model into a `.int8` sibling
        dir and serve THAT program: every bucket warmed afterwards is a
        quantized executable (int8 matmul/conv, int32 accumulation,
        f32 replies — ops/quant.py quantized_* kernels dequantize
        before returning). With no calibration configured, a previously
        quantized sibling is reused so restarts don't re-calibrate."""
        cfg = self.config
        if not cfg.model_dir:
            raise ValueError("ServingConfig(precision='int8') needs a "
                             "model_dir (externally built predictors "
                             "cannot be post-training quantized)")
        from ..slim.quantization import (QUANT_META_FILE,
                                         calibrate_and_quantize)

        int8_dir = cfg.model_dir.rstrip("/\\") + ".int8"
        src_digest = self._digest_model_file(cfg.model_dir)
        src_path = os.path.join(int8_dir, QUANT_SRC_FILE)
        recorded = None
        if os.path.exists(src_path):
            try:
                with open(src_path) as f:
                    recorded = json.load(f).get("source_model_digest")
            except (OSError, ValueError):
                recorded = None
        complete = os.path.exists(os.path.join(int8_dir, QUANT_META_FILE))
        if cfg.calibration is None:
            if complete:
                if recorded is not None and src_digest is not None \
                        and recorded != src_digest:
                    # quantized from a DIFFERENT model (model_dir was
                    # replaced since): serving it silently would answer
                    # with the old model's weights
                    raise ValueError(
                        f"previously quantized sibling {int8_dir} was "
                        f"built from a different model than the current"
                        f" {cfg.model_dir} — pass calibration= to "
                        "requantize it")
                _events.emit("quantize", action="serving_reuse",
                             dir=int8_dir)
                return int8_dir
            raise ValueError(
                "ServingConfig(precision='int8') needs calibration= (a "
                "callable returning an iterable of feed dicts) — no "
                f"previously quantized model found at {int8_dir}")
        # calibration configured: still reuse a sibling quantized from
        # THIS program (source-digest marker) — static configs keep
        # calibration= set on every boot, and a gang restart must not
        # pay a full recalibration for an unchanged model
        if complete and src_digest is not None \
                and recorded == src_digest:
            _events.emit("quantize", action="serving_reuse",
                         dir=int8_dir, source_digest=src_digest)
            return int8_dir
        import shutil

        shutil.rmtree(int8_dir, ignore_errors=True)
        act_scales = calibrate_and_quantize(
            cfg.model_dir, self._calibration_reader(),
            save_model_path=int8_dir)
        if src_digest is not None:
            from ..resilience.atomic import json_dump
            json_dump({"source_model_digest": src_digest}, src_path)
        _events.emit("quantize", action="serving_calibrate",
                     dir=int8_dir, activations=len(act_scales))
        return int8_dir

    def _measure_accuracy_delta(self):
        """Boot-time accuracy accounting for reduced-precision serving:
        run the first `accuracy_check_batches` calibration batches
        through an f32 reference predictor AND this engine's predictor
        (both bucket-padded, so no off-bucket signature is minted) and
        record the reply deviation in /v1/status + the metrics
        registry. A failure here downgrades to accuracy_delta=None with
        an event — never a boot failure."""
        import itertools

        cfg = self.config
        try:
            batches = list(itertools.islice(
                iter(self._calibration_reader()()),
                int(cfg.accuracy_check_batches)))
            if not batches:
                return
            acfg = AnalysisConfig(cfg.model_dir)
            if not cfg.use_tpu:
                acfg.disable_gpu()
            acfg._device_id = cfg.device_id
            # the reference MUST be f32 — without the pin it would
            # resolve the same program-attr/env policy as the engine
            # and the reported delta would be reduced-vs-reduced
            acfg.set_precision("f32")
            acfg.enable_bucketing(buckets=self.policy.buckets)
            ref = create_paddle_predictor(acfg)
            max_d, sum_d, n_vals = 0.0, 0.0, 0
            for feed in batches:
                a = ref.predict(**feed)
                b = self._pred.predict(**feed)
                for name in a:
                    if name not in b:
                        continue
                    d = np.abs(np.asarray(a[name], np.float32)
                               - np.asarray(b[name], np.float32))
                    if d.size:
                        max_d = max(max_d, float(d.max()))
                        sum_d += float(d.sum())
                        n_vals += d.size
            self.accuracy_delta = {
                "vs": "f32", "max_abs": max_d,
                "mean_abs": sum_d / max(n_vals, 1),
                "batches": len(batches)}
            ACCURACY_DELTA.set(max_d, stat="max_abs")
            ACCURACY_DELTA.set(self.accuracy_delta["mean_abs"],
                               stat="mean_abs")
            _events.emit("quantize", action="accuracy_check",
                         precision=self.precision, **self.accuracy_delta)
        except Exception as e:
            self.accuracy_delta = None
            _events.emit("quantize", action="accuracy_check_failed",
                         precision=self.precision, error=str(e)[:200])

    def output_batched(self, name: str) -> Optional[bool]:
        """Does fetch `name` carry the batch dim? From the Predictor's
        declared shapes (None when unknown — e.g. the native engine —
        letting the batcher fall back to its shape heuristic)."""
        return getattr(self._pred, "_fetch_batched", {}).get(name)

    def warmup(self) -> int:
        """AOT-compile every configured bucket; returns how many bucket
        signatures are ready. Idempotent (per-bucket compiles are cached
        by the Predictor)."""
        t0 = time.perf_counter()
        ready = 0
        for b in self.policy.buckets:
            try:
                if self._pred.warm(b):
                    ready += 1
            except ValueError:
                # dynamic non-batch dims: the first live batch per
                # bucket compiles instead; serving still works
                break
        WARMUP_SECONDS.set(time.perf_counter() - t0)
        self.warmed = True
        return ready

    # -- warmstart artifact (serialized bucket executables) -------------

    @staticmethod
    def _digest_model_file(model_dir: Optional[str]) -> Optional[str]:
        """sha256 of `model_dir`'s __model__ program file, None when it
        is unreadable or there is no dir."""
        if not model_dir:
            return None
        try:
            with open(os.path.join(model_dir, "__model__"), "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return None

    def _model_digest(self) -> Optional[str]:
        """Content digest of the served model's program (__model__
        file): an artifact baked from a DIFFERENT program must never be
        adopted — same bucket signatures, different computation. None
        when there is no model dir (externally-built predictor); such
        artifacts match only artifacts also baked without one."""
        return self._digest_model_file(self._served_dir)

    def export_warmstart(self, path: str) -> int:
        """Serialize every warmed bucket executable into ONE artifact
        at `path` (atomic write). Call after warmup(); returns how many
        bucket signatures the artifact carries. The artifact embeds the
        environment meta (jax version/backend/device kind) and the
        model digest, both re-checked at load."""
        entries = self._pred.serialize_warm()
        art = dict(_cc.environment_meta(),
                   format=WARMSTART_FORMAT,
                   model_digest=self._model_digest(),
                   buckets=[int(b) for b in self.policy.buckets],
                   created_at=time.time(),
                   entries=entries)
        from ..resilience.atomic import write_bytes

        write_bytes(path, pickle.dumps(art,
                                       protocol=pickle.HIGHEST_PROTOCOL))
        _events.emit("warmstart", action="export", path=path,
                     entries=len(entries),
                     buckets=[int(b) for b in self.policy.buckets])
        return len(entries)

    def load_warmstart(self, path: str) -> int:
        """Adopt the bucket executables from a warmstart artifact.
        Returns how many signatures were adopted (also reflected in
        `warmstart_adopted` / `/v1/status`); 0 (with a `warmstart`
        reject event) when the artifact is unreadable, from another
        jax/backend/device, or baked from a different model — warmup
        then compiles normally, so a stale artifact costs nothing but
        the cold boot it failed to avoid."""
        self.warmstart_adopted = self._load_warmstart(path)
        return self.warmstart_adopted

    def _load_warmstart(self, path: str) -> int:
        try:
            with open(path, "rb") as f:
                art = pickle.loads(f.read())
            if not isinstance(art, dict) \
                    or art.get("format") != WARMSTART_FORMAT:
                raise ValueError("not a warmstart artifact")
        except Exception as e:
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"unreadable: {str(e)[:200]}")
            return 0
        env = _cc.environment_meta()
        stored = {k: art.get(k) for k in env}
        if stored != env:
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"environment mismatch: artifact "
                                f"{stored} vs process {env}")
            return 0
        digest = self._model_digest()
        if art.get("model_digest") != digest:
            _events.emit("warmstart", action="reject", path=path,
                         reason="model digest mismatch — artifact baked "
                                "from a different program")
            return 0
        try:
            entries = art.get("entries") or {}
            adopted = self._pred.adopt_warm(entries)
        except Exception as e:
            # adopt_warm guards per entry, but an artifact whose
            # entries container itself is malformed must still degrade
            # to a cold boot, never crash Engine construction
            _events.emit("warmstart", action="reject", path=path,
                         reason=f"unadoptable entries: {str(e)[:200]}")
            return 0
        _events.emit("warmstart", action="load", path=path,
                     entries=len(entries), adopted=adopted)
        return adopted

    def run_batch(self, feeds: Dict[str, np.ndarray]
                  ) -> Dict[str, np.ndarray]:
        """One bucket-shaped dispatch: the Predictor pads to the bucket,
        runs the compiled signature, and slices back; this layer adds
        the per-bucket latency/count/padding accounting. The warm path
        goes through the Predictor's lazy fetch handle — dispatch and
        host fetch are separate spans, so the dispatch-to-ready
        histogram (site fetch:infer) shows pure device latency while
        BUCKET_SECONDS keeps the end-to-end view the batcher sizes
        against."""
        n = common_batch(feeds)
        if not n:
            raise ValueError("feeds must share a leading batch dim >= 1")
        bucket = self.policy.bucket_for(n) or n
        t0 = time.perf_counter()
        # no-op without a sampled ambient context (the batcher activates
        # its lead request's trace around this call); when sampled, the
        # device dispatch gets its own span with the bucket attributed
        with _tracing.trace_span("serve.dispatch", cat="serve",
                                 bucket=int(bucket), rows=int(n)), \
                _memwatch.oom_guard("serving"):
            out = self._pred.predict_handle(**feeds).result()
        BUCKET_SECONDS.observe(time.perf_counter() - t0,
                               bucket=str(bucket))
        BATCHES.inc(bucket=str(bucket))
        if bucket != n:
            PAD_ROWS.inc(bucket - n)
        return out

    def status(self) -> Dict:
        return {
            "buckets": [int(b) for b in self.policy.buckets],
            "warmed": self.warmed,
            "precision": self.precision,
            "accuracy_delta": self.accuracy_delta,
            "analysis": self.analysis,
            "warmstart_adopted": self.warmstart_adopted,
            "batches": {str(b): BATCHES.value(bucket=str(b))
                        for b in self.policy.buckets},
            "feeds": self._pred.get_input_names(),
            "fetches": self._pred.get_output_names(),
        }
