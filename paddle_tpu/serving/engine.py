"""Bucket-aware serving engine: the Predictor wrapped for batch traffic.

Owns the BucketPolicy, builds the Predictor with bucketing enabled (so
every dispatched batch lands on one of the configured signatures),
AOT-warms every bucket at startup (no live request pays an XLA
compile), and accounts per-bucket dispatch latency and batch counts in
the metrics registry. Compile visibility itself comes from the PR 2
`_JitDispatch` instrumentation inside the Predictor: each bucket's
compile appears in `paddle_tpu_compile_seconds{kind="infer"}` and as a
`compile` event, which is what lets a deployment assert its signature
set stays closed under live traffic.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..inference import AnalysisConfig, Predictor, create_paddle_predictor
from ..observability import metrics as _m
from .bucketing import BucketPolicy, common_batch

__all__ = ["ServingConfig", "Engine"]

BUCKET_SECONDS = _m.histogram(
    "paddle_tpu_serving_bucket_seconds",
    "Engine dispatch wall time per bucket (pad + run + slice)",
    labelnames=("bucket",))
BATCHES = _m.counter(
    "paddle_tpu_serving_batches_total",
    "Dispatched batches per bucket", labelnames=("bucket",))
PAD_ROWS = _m.counter(
    "paddle_tpu_serving_pad_rows_total",
    "Padding rows added by bucketing (wasted accelerator rows)")
WARMUP_SECONDS = _m.gauge(
    "paddle_tpu_serving_warmup_seconds",
    "Wall seconds the last warmup spent compiling all buckets")


class ServingConfig:
    """Knobs for the dynamic-batching server (full reference in
    SERVING.md §Configuration)."""

    def __init__(self, model_dir: Optional[str] = None, *,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue: int = 128,
                 max_wait_ms: float = 5.0,
                 timeout_s: float = 30.0,
                 warmup: bool = True,
                 aot: bool = True,
                 use_tpu: bool = True,
                 device_id: int = 0,
                 host: Optional[str] = None,
                 port: int = 0):
        self.model_dir = model_dir
        self.buckets = tuple(buckets) if buckets is not None else None
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_wait_ms = float(max_wait_ms)
        self.timeout_s = float(timeout_s)
        self.warmup = bool(warmup)
        self.aot = bool(aot)
        self.use_tpu = bool(use_tpu)
        self.device_id = int(device_id)
        self.host = host
        self.port = int(port)


class Engine:
    """Predictor + BucketPolicy with warmup and per-bucket accounting.
    `run_batch` is the callable the Batcher dispatches to; it is also
    safe to call directly (single-caller deployments that want bucketing
    without the queue)."""

    def __init__(self, config: ServingConfig,
                 predictor: Optional[Predictor] = None):
        self.config = config
        self.policy = BucketPolicy(max_batch=config.max_batch,
                                   buckets=config.buckets)
        if predictor is None:
            acfg = AnalysisConfig(config.model_dir)
            if not config.use_tpu:
                acfg.disable_gpu()
            acfg._device_id = config.device_id
            if config.aot:
                acfg.enable_aot()
            acfg.enable_bucketing(buckets=self.policy.buckets)
            predictor = create_paddle_predictor(acfg)
        else:
            # an externally built predictor must agree on the signature
            # set or live traffic would compile off-bucket shapes that
            # warmup never touched — the engine's policy wins
            predictor.config._bucketing = self.policy
        self._pred = predictor
        self.warmed = False

    def output_batched(self, name: str) -> Optional[bool]:
        """Does fetch `name` carry the batch dim? From the Predictor's
        declared shapes (None when unknown — e.g. the native engine —
        letting the batcher fall back to its shape heuristic)."""
        return getattr(self._pred, "_fetch_batched", {}).get(name)

    def warmup(self) -> int:
        """AOT-compile every configured bucket; returns how many bucket
        signatures are ready. Idempotent (per-bucket compiles are cached
        by the Predictor)."""
        t0 = time.perf_counter()
        ready = 0
        for b in self.policy.buckets:
            try:
                if self._pred.warm(b):
                    ready += 1
            except ValueError:
                # dynamic non-batch dims: the first live batch per
                # bucket compiles instead; serving still works
                break
        WARMUP_SECONDS.set(time.perf_counter() - t0)
        self.warmed = True
        return ready

    def run_batch(self, feeds: Dict[str, np.ndarray]
                  ) -> Dict[str, np.ndarray]:
        """One bucket-shaped dispatch: the Predictor pads to the bucket,
        runs the compiled signature, and slices back; this layer adds
        the per-bucket latency/count/padding accounting. The warm path
        goes through the Predictor's lazy fetch handle — dispatch and
        host fetch are separate spans, so the dispatch-to-ready
        histogram (site fetch:infer) shows pure device latency while
        BUCKET_SECONDS keeps the end-to-end view the batcher sizes
        against."""
        n = common_batch(feeds)
        if not n:
            raise ValueError("feeds must share a leading batch dim >= 1")
        bucket = self.policy.bucket_for(n) or n
        t0 = time.perf_counter()
        out = self._pred.predict_handle(**feeds).result()
        BUCKET_SECONDS.observe(time.perf_counter() - t0,
                               bucket=str(bucket))
        BATCHES.inc(bucket=str(bucket))
        if bucket != n:
            PAD_ROWS.inc(bucket - n)
        return out

    def status(self) -> Dict:
        return {
            "buckets": [int(b) for b in self.policy.buckets],
            "warmed": self.warmed,
            "batches": {str(b): BATCHES.value(bucket=str(b))
                        for b in self.policy.buckets},
            "feeds": self._pred.get_input_names(),
            "fetches": self._pred.get_output_names(),
        }
