"""Elastic autoscaling control loop for the serving fleet.

Closes the loop between the Router's gauges and the
ReplicaSupervisor's scale_out/scale_in (SERVING.md §Fleet): every
`interval_s` it reads

  * **utilization** — `router.mean_load_per_healthy()`: mean (queue
    depth + in-flight) per healthy replica, i.e. the /v1/load scalar
    the router already polls, and
  * **latency** — `router.recent_p99(window_s)`: trailing p99 of
    successful predicts, and
  * **SLO burn** (optional) — `burn_rate_fn`, typically
    `observability.slo.SLOEngine.max_burn_rate`: the worst confirmed
    fast-window burn rate across declared objectives (PROFILE.md §Time
    series & SLOs), so the fleet grows when the error budget is being
    SPENT too fast, not only when queues are visibly deep,

and moves the replica count within `[min_replicas, max_replicas]` with
classic hysteresis so noise cannot flap the fleet:

  * scale OUT when load > `high_load` (or p99 > `p99_high_ms`, or burn
    ≥ `burn_high`) for `breach_polls` CONSECUTIVE polls AND
    `out_cooldown_s` has passed since the last scaling action;
  * scale IN when load < `low_load` AND p99 is under any configured
    bound AND burn is under `burn_high` for `clear_polls` consecutive
    polls AND `in_cooldown_s` passed — deliberately slower than
    scale-out (capacity mistakes in the down direction hurt users; in
    the up direction they only cost a replica).

The gap between `high_load` and `low_load` is the hysteresis band: a
fleet sitting anywhere inside it is left alone. Scale-out lands within
seconds because replicas boot from the PR 6 warmstart artifact;
scale-in is graceful because the supervisor SIGTERMs and the replica
runs leave→drain→stop (zero dropped in-flight requests, tested by
`serve_bench --fleet`).

Multi-model fleets (SERVING.md §Multi-tenancy) allocate replica counts
per model by running one Autoscaler + ReplicaSupervisor pair per model
id against the SAME router: `Autoscaler(model="bert")` scopes the
utilization signal to `router.mean_load_per_healthy(model="bert")` —
the replicas advertising that model in /v1/load — while each model's
supervisor boots replicas serving only its model. Models then scale
independently on their own load, sharing the fleet's front door.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..observability import events as _events
from ..observability import metrics as _m

__all__ = ["Autoscaler"]

AUTOSCALE = _m.counter(
    "paddle_tpu_fleet_autoscale_total",
    "Autoscaler scaling actions", labelnames=("direction",))
TARGET = _m.gauge(
    "paddle_tpu_fleet_target_replicas",
    "Replica count the autoscaler currently steers toward")


class Autoscaler:
    """Queue-depth/p99 control loop over a router + supervisor — see
    the module docstring for the policy. `router` and `supervisor` are
    duck-typed (tests drive fakes): router needs
    mean_load_per_healthy() and recent_p99(); supervisor needs
    replica_count(), scale_out() and scale_in()."""

    def __init__(self, router, supervisor, *,
                 model: Optional[str] = None,
                 min_replicas: int = 1, max_replicas: int = 4,
                 high_load: float = 4.0, low_load: float = 0.5,
                 p99_high_ms: Optional[float] = None,
                 burn_rate_fn=None, burn_high: float = 14.4,
                 interval_s: float = 0.5,
                 breach_polls: int = 3, clear_polls: int = 6,
                 out_cooldown_s: float = 5.0,
                 in_cooldown_s: float = 10.0,
                 clock=time.monotonic):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if low_load >= high_load:
            raise ValueError(
                "low_load must be < high_load — the gap between them "
                "is the hysteresis band; without it the fleet flaps")
        self.router = router
        self.supervisor = supervisor
        # scope the utilization signal to one model's replica slice
        # (per-model allocation: one Autoscaler+Supervisor pair per
        # model id, all sharing one Router). None = whole fleet.
        self.model = str(model) if model is not None else None
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.high_load = float(high_load)
        self.low_load = float(low_load)
        self.p99_high_ms = p99_high_ms
        # optional SLO input: a zero-arg callable returning the current
        # worst fast-window burn rate (0.0 = budget-neutral traffic)
        self.burn_rate_fn = burn_rate_fn
        self.burn_high = float(burn_high)
        self.interval_s = float(interval_s)
        self.breach_polls = int(breach_polls)
        self.clear_polls = int(clear_polls)
        self.out_cooldown_s = float(out_cooldown_s)
        self.in_cooldown_s = float(in_cooldown_s)
        self._clock = clock
        self._high_streak = 0
        self._low_streak = 0
        self._last_action_t: Optional[float] = None
        self._actions = {"out": 0, "in": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle-tpu-fleet-autoscaler",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # control loop must outlive a bad tick
                _events.emit("fleet", action="autoscale_error",
                             error=f"{type(e).__name__}: {e}"[:200])
            self._stop.wait(self.interval_s)

    # -- the control law ----------------------------------------------

    def _cooldown_over(self, cooldown_s: float) -> bool:
        return self._last_action_t is None or \
            (self._clock() - self._last_action_t) >= cooldown_s

    def tick(self) -> Optional[str]:
        """One control decision; returns "out", "in", or None (also the
        unit-test entry — tests drive ticks directly with fakes and an
        injected clock). Streak counters only advance on polls with a
        real signal: an empty fleet (load None) is the supervisor's /
        router's problem, not a scale-in signal."""
        n = self.supervisor.replica_count()
        if self.model is not None:
            load = self.router.mean_load_per_healthy(model=self.model)
        else:
            # keyword-free call keeps duck-typed test fakes (zero-arg
            # mean_load_per_healthy) working unchanged
            load = self.router.mean_load_per_healthy()
        p99 = self.router.recent_p99()
        p99_ms = p99 * 1000.0 if p99 is not None else None
        TARGET.set(n)
        if load is None:
            # nothing healthy to measure: hold position (the supervisor
            # respawn/boot path is responsible for bringing one back)
            self._high_streak = self._low_streak = 0
            return None

        burn = None
        if self.burn_rate_fn is not None:
            try:
                burn = float(self.burn_rate_fn())
            except Exception:
                burn = None  # lint-exempt:swallow: a broken SLO feed must not stop load/p99 scaling

        high = load > self.high_load or (
            self.p99_high_ms is not None and p99_ms is not None
            and p99_ms > self.p99_high_ms) or (
            burn is not None and burn >= self.burn_high)
        low = load < self.low_load and (
            self.p99_high_ms is None or p99_ms is None
            or p99_ms <= self.p99_high_ms) and (
            burn is None or burn < self.burn_high)
        self._high_streak = self._high_streak + 1 if high else 0
        self._low_streak = self._low_streak + 1 if low else 0

        if high and self._high_streak >= self.breach_polls \
                and n < self.max_replicas \
                and self._cooldown_over(self.out_cooldown_s):
            endpoint = self.supervisor.scale_out()
            self._after_action("out", n, load, p99_ms,
                               endpoint=endpoint)
            return "out"
        if low and self._low_streak >= self.clear_polls \
                and n > self.min_replicas \
                and self._cooldown_over(self.in_cooldown_s):
            endpoint = self.supervisor.scale_in()
            self._after_action("in", n, load, p99_ms, endpoint=endpoint)
            return "in"
        return None

    def _after_action(self, direction: str, n_before: int,
                      load: float, p99_ms: Optional[float],
                      endpoint: Optional[str]):
        self._last_action_t = self._clock()
        self._high_streak = self._low_streak = 0
        self._actions[direction] += 1
        AUTOSCALE.inc(direction=direction)
        TARGET.set(n_before + (1 if direction == "out" else -1))
        _events.emit("fleet", action=f"scale_{direction}_decision",
                     replicas_before=n_before,
                     load=round(load, 3),
                     p99_ms=round(p99_ms, 3) if p99_ms else None,
                     endpoint=endpoint)

    def status(self) -> Dict:
        return {
            "model": self.model,
            "min": self.min_replicas, "max": self.max_replicas,
            "high_load": self.high_load, "low_load": self.low_load,
            "p99_high_ms": self.p99_high_ms,
            "burn_high": self.burn_high
            if self.burn_rate_fn is not None else None,
            "high_streak": self._high_streak,
            "low_streak": self._low_streak,
            "actions": dict(self._actions),
        }
