"""Fleet front tier: a replica router with health ejection, breaker-
gated retry failover, and elastic rendezvous-backed membership.

One Engine on one chip is a single point of failure and a fixed
capacity ceiling; the fleet tier (SERVING.md §Fleet) puts a `Router` in
front of N replica `serving.Server` processes so a replica crash is a
retried request, not a client-visible outage, and capacity follows the
replica set:

* **balancing** — power-of-two-choices on live load: a background poll
  thread caches each replica's `/v1/load` scalar (queue depth +
  in-flight work, satellite of this PR) every `poll_interval_s`; a pick
  samples two healthy replicas and routes to the lower cached load
  plus a locally tracked in-flight delta (the cache is at most one
  interval stale, the local delta makes consecutive picks spread).
  P2C needs only the scalar — the router never parses a full status
  document on the request path (Mitzenmacher '01: two choices get
  exponentially better max-load than one; more choices add little).
* **health ejection** — the poll thread probes `/v1/healthz`;
  `eject_threshold` consecutive failures/timeouts/503s eject the
  replica from the pick set (`fleet` event + metric), a succeeding
  probe readmits it. A connect failure on the request path ejects
  immediately — waiting out the poll interval would burn retries on a
  corpse.
* **circuit breaking** — every endpoint is wrapped in a PR 10
  `resilience.retry.CircuitBreaker`; `allow()` admission happens only
  for the replica a pick actually chose (an un-picked candidate must
  not consume the half-open probe slot) and EVERY admitted call reports
  success or failure — including unexpected exceptions, so a dying
  probe thread releases the slot instead of wedging the breaker
  half-open forever (the PR 10 leak-fix contract, extended here to the
  router's usage pattern and regression-tested in tests/test_fleet.py).
* **retry failover** — `/v1/predict` is idempotent (pure function of
  its feeds): a connect error, wire timeout, or replica 5xx re-sends
  the request to a different surviving replica, up to `retries` times
  (`paddle_tpu_fleet_retries_total{reason}`); a replica 503 (queue
  full / draining) is NOT a breaker failure — the replica is healthy
  and talking — but also fails over. Client errors (400) and
  request-deadline 504s never retry. Streamed `/v1/generate` is NOT
  blindly retried: a stream that dies before the first token was
  delivered is resubmitted from scratch on another replica; once
  tokens have been delivered the router surfaces a typed
  `StreamBrokenError` — silently replaying a generation after the
  client consumed half of it could emit a token sequence that
  disagrees with what was already delivered (composition-dependent
  sampling, non-greedy decode), so the CLIENT owns that retry.
* **QoS shed passthrough** — a 503 whose body carries a `"shed"` key
  is a tier shed (SERVING.md §Multi-tenancy): the replica
  deliberately rejected the request under its admission policy, so
  the router treats it as an ANSWER, not a failure — no failover
  retry (re-sending a shed request onto a surviving replica amplifies
  exactly the overload the shed is relieving), no breaker penalty,
  and the typed body + Retry-After propagate to the client unchanged
  (`paddle_tpu_fleet_sheds_total{tier}`, typed `TierShed` from the
  library API).
* **model routing** — replicas advertise the model ids they serve in
  the /v1/load body (multi-model Server, SERVING.md §Multi-tenancy);
  a request carrying `"model"` is picked only among replicas
  advertising that id (a replica advertising nothing is assumed to
  serve everything — single-model fleets predate the field), and a
  replica 404 "unknown model" fails over without a breaker penalty —
  the replica is alive, the router's model map was just stale.
  `mean_load_per_healthy(model=...)` scopes the autoscaler's
  utilization signal to one model's slice of the fleet.
* **elastic membership** — point the router at the same PR 9
  `FileRendezvous` store the replicas heartbeat into
  (`Router(rdzv_dir=...)`): member ids ARE endpoints ("host:port"),
  the poll thread folds joins/leaves into the replica set, and
  `paddle_tpu_fleet_world_size` tracks the live set. Scale-out /
  scale-in / respawn live in `distributed/launch_serve.py`
  (ReplicaSupervisor) and `serving/autoscale.py` (Autoscaler).

`RouterServer` is the HTTP face of the tier: the same /v1 surface as a
replica (predict, generate, status, healthz), so clients cannot tell a
fleet from a single server, plus the fleet view under /v1/status.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Dict, Iterator, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, urlparse

from ..observability import events as _events
from ..observability import httpbase as _base
from ..observability import metrics as _m
from ..observability import tracing as _tracing
from ..observability.metrics import _json_safe
from ..resilience.retry import CircuitBreaker

__all__ = ["Router", "RouterServer", "FleetError", "NoReplicasError",
           "StreamBrokenError", "ReplicaRejected", "FleetTimeout",
           "TierShed"]


REPLICAS = _m.gauge(
    "paddle_tpu_fleet_replicas",
    "Router replica counts by state", labelnames=("state",))
WORLD_SIZE = _m.gauge(
    "paddle_tpu_fleet_world_size",
    "Replica endpoints the router currently knows (healthy + ejected)")
REQUESTS = _m.counter(
    "paddle_tpu_fleet_requests_total",
    "Router requests by outcome (ok|error|rejected|timeout)",
    labelnames=("outcome",))
RETRIES = _m.counter(
    "paddle_tpu_fleet_retries_total",
    "Requests re-sent to another replica, by failure class "
    "(connect|server_error|busy|no_model|stream_restart)",
    labelnames=("reason",))
EJECTIONS = _m.counter(
    "paddle_tpu_fleet_ejections_total",
    "Health ejections per endpoint", labelnames=("endpoint",))
READMISSIONS = _m.counter(
    "paddle_tpu_fleet_readmissions_total",
    "Ejected endpoints readmitted after a passing health probe",
    labelnames=("endpoint",))
BREAKER_STATE = _m.gauge(
    "paddle_tpu_fleet_breaker_state",
    "Per-endpoint circuit-breaker state (0 closed, 1 half-open, 2 open)",
    labelnames=("endpoint",))
PICKS = _m.counter(
    "paddle_tpu_fleet_picks_total",
    "Power-of-two-choices routing decisions per endpoint",
    labelnames=("endpoint",))
REQUEST_SECONDS = _m.histogram(
    "paddle_tpu_fleet_request_seconds",
    "Router end-to-end request latency (successful predicts, incl. "
    "failover retries)")
FLEET_SHEDS = _m.counter(
    "paddle_tpu_fleet_sheds_total",
    "QoS tier-shed 503s the router passed through as answers (no "
    "failover), by shed tier", labelnames=("tier",))

_BREAKER_LEVEL = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
                  CircuitBreaker.OPEN: 2}


class FleetError(RuntimeError):
    """Base class for router-level failures."""


class NoReplicasError(FleetError):
    """No healthy, breaker-admitted replica left to try (HTTP 503)."""


class ReplicaRejected(FleetError):
    """Every tried replica rejected the request with 503 — the fleet is
    saturated or draining; clients should back off (HTTP 503)."""


class FleetTimeout(FleetError):
    """A replica answered 504: the request's own deadline is spent, so
    re-sending it elsewhere would only double the damage (HTTP 504)."""


class TierShed(FleetError):
    """A replica answered with a QoS tier-shed 503 — a deliberate,
    policy-scoped ANSWER, not a failure: the router does not fail
    over (that would amplify the overload the shed is relieving) and
    the replica takes no breaker penalty. Carries the replica's typed
    `body` ({"shed": tier, "kind": "queue"|"quota", "tenant": ...})
    and its suggested `retry_after_s` backoff."""

    def __init__(self, msg: str, body: Optional[Dict] = None,
                 retry_after_s: float = 1.0):
        super().__init__(msg)
        self.body = dict(body or {})
        self.retry_after_s = float(retry_after_s)

    @property
    def tier(self) -> Optional[str]:
        return self.body.get("shed")


class StreamBrokenError(FleetError):
    """A streamed generation died AFTER tokens were delivered. The
    router must not silently resubmit — the replayed sequence is not
    guaranteed to extend what the client already consumed — so the
    client owns this retry. Carries `tokens_delivered`."""

    def __init__(self, msg: str, tokens_delivered: int):
        super().__init__(msg)
        self.tokens_delivered = int(tokens_delivered)


class _Replica:
    """Router-side view of one replica endpoint."""

    __slots__ = ("endpoint", "breaker", "healthy", "consec_fail",
                 "load", "inflight", "picks", "source", "last_error",
                 "last_state", "models")

    def __init__(self, endpoint: str, breaker: CircuitBreaker,
                 source: str):
        self.endpoint = endpoint
        self.breaker = breaker
        self.healthy = True      # optimistic: first probe corrects it
        self.consec_fail = 0
        self.load = 0.0          # cached /v1/load scalar
        self.inflight = 0        # router-local in-flight delta
        self.picks = 0
        self.source = source     # "static" | "rendezvous"
        self.last_error: Optional[str] = None
        self.last_state: Optional[str] = None
        # model ids the replica advertises in /v1/load; None = the
        # replica predates the field (or no poll yet) = serves anything
        self.models: Optional[frozenset] = None


class Router:
    """Load-balancing front tier over N replica endpoints — see the
    module docstring for the algorithm. Thread-safe: the HTTP frontend
    calls predict()/generate() from concurrent handler threads."""

    def __init__(self, endpoints: Sequence[str] = (), *,
                 rdzv_dir: Optional[str] = None,
                 rendezvous=None,
                 poll_interval_s: float = 0.25,
                 probe_timeout_s: float = 2.0,
                 eject_threshold: int = 2,
                 retries: int = 2,
                 request_timeout_s: float = 30.0,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 2.0):
        if rdzv_dir is not None and rendezvous is not None:
            raise ValueError("pass rdzv_dir OR a rendezvous, not both")
        if rendezvous is None and rdzv_dir is not None:
            from ..distributed.rendezvous import FileRendezvous

            # scan-only membership view: the router never register()s,
            # so it is not a member — it just reads live heartbeats
            rendezvous = FileRendezvous(
                rdzv_dir, worker_id="fleet-router", min_workers=1)
        self._rdzv = rendezvous
        self.poll_interval_s = float(poll_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.eject_threshold = int(eject_threshold)
        self.retries = int(retries)
        self.request_timeout_s = float(request_timeout_s)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_reset_s = float(breaker_reset_s)
        # deferred import: the analysis package must not load during
        # package bootstrap; constructors only run after it
        from ..analysis import lockcheck as _lockcheck

        self._lock = _lockcheck.Lock("serving.router.Router._lock")
        self._replicas: Dict[str, _Replica] = {}
        self._counts = {"ok": 0, "error": 0, "rejected": 0, "timeout": 0}
        self._retry_counts: Dict[str, int] = {}
        # sliding latency window for the autoscaler's p99 gauge:
        # (monotonic ts, seconds) of recent successful predicts
        self._lat_window: "deque[Tuple[float, float]]" = deque(maxlen=1024)
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._rng = random.Random(0x5EED)
        for ep in endpoints:
            self._ensure_replica(str(ep), source="static")

    # -- membership ----------------------------------------------------

    def _make_breaker(self, endpoint: str) -> CircuitBreaker:
        def on_transition(old, new, _ep=endpoint):
            BREAKER_STATE.set(_BREAKER_LEVEL[new], endpoint=_ep)
            _events.emit("fleet", action="breaker", endpoint=_ep,
                         old=old, new=new)

        return CircuitBreaker(failure_threshold=self._breaker_threshold,
                              reset_timeout_s=self._breaker_reset_s,
                              on_transition=on_transition)

    def _ensure_replica(self, endpoint: str, source: str) -> _Replica:
        with self._lock:
            rep = self._replicas.get(endpoint)
            if rep is None:
                rep = _Replica(endpoint, self._make_breaker(endpoint),
                               source)
                self._replicas[endpoint] = rep
                joined = True
            else:
                joined = False
        if joined:
            BREAKER_STATE.set(0, endpoint=endpoint)
            _events.emit("fleet", action="member_join", endpoint=endpoint,
                         source=source)
            self._set_gauges()
        return rep

    def add_replica(self, endpoint: str):
        """Statically add one replica endpoint ("host:port")."""
        self._ensure_replica(str(endpoint), source="static")

    def remove_replica(self, endpoint: str):
        """Drop one endpoint from the pick set (scale-in bookkeeping;
        rendezvous-sourced members leave automatically)."""
        with self._lock:
            rep = self._replicas.pop(str(endpoint), None)
        if rep is not None:
            _events.emit("fleet", action="member_leave",
                         endpoint=rep.endpoint, source=rep.source)
            self._set_gauges()

    def endpoints(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def healthy_endpoints(self) -> List[str]:
        with self._lock:
            return sorted(ep for ep, r in self._replicas.items()
                          if r.healthy)

    # -- background poll (membership + health + load) ------------------

    def start(self):
        """Start the poll thread (idempotent). Without it the router
        still works — ejection then happens only through request-path
        failures and membership stays static."""
        with self._lock:
            if self._poll_thread is not None \
                    and self._poll_thread.is_alive():
                return
            self._poll_stop.clear()
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="paddle-tpu-fleet-router",
                daemon=True)
            self._poll_thread.start()

    def stop(self):
        """Stop and join the poll thread. Idempotent."""
        self._poll_stop.set()
        with self._lock:
            t, self._poll_thread = self._poll_thread, None
        if t is not None:
            t.join(timeout=10.0)

    def _poll_loop(self):
        while not self._poll_stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # poll must never die; next tick retries
                _events.emit("fleet", action="poll_error",
                             error=f"{type(e).__name__}: {e}"[:200])
            self._poll_stop.wait(self.poll_interval_s)

    def poll_once(self):
        """One membership + health + load sweep (the poll thread's
        body, callable directly from tests and single-threaded
        drivers)."""
        if self._rdzv is not None:
            live = set(self._rdzv.live_members())
            for ep in live:
                self._ensure_replica(ep, source="rendezvous")
            with self._lock:
                gone = [ep for ep, r in self._replicas.items()
                        if r.source == "rendezvous" and ep not in live]
            for ep in gone:
                self.remove_replica(ep)
        with self._lock:
            targets = list(self._replicas.values())
        for rep in targets:
            self._probe(rep)
        self._set_gauges()

    def _probe(self, rep: _Replica):
        """Health + load probe of one replica (no lock held — these are
        blocking socket calls)."""
        try:
            code, body = self._get_json(rep.endpoint, "/v1/healthz",
                                        self.probe_timeout_s)
        except Exception as e:
            self._health_result(rep, ok=False,
                                error=f"{type(e).__name__}: {e}")
            return
        state = body.get("state") if isinstance(body, dict) else None
        rep.last_state = state
        if code != 200:
            self._health_result(rep, ok=False,
                                error=f"healthz {code} state={state}")
            return
        self._health_result(rep, ok=True)
        try:
            code, load = self._get_json(rep.endpoint, "/v1/load",
                                        self.probe_timeout_s)
            if code == 200 and isinstance(load, dict):
                models = load.get("models")
                with self._lock:
                    rep.load = float(load.get("load", 0.0))
                    if isinstance(models, (list, tuple)):
                        rep.models = frozenset(str(m) for m in models)
        except Exception:
            # load staleness is benign (health just passed); the next
            # poll refreshes it
            pass  # lint-exempt:swallow: stale load is self-healing

    def _health_result(self, rep: _Replica, ok: bool,
                       error: Optional[str] = None):
        with self._lock:
            if ok:
                rep.consec_fail = 0
                rep.last_error = None
                readmit = not rep.healthy
                rep.healthy = True
            else:
                readmit = False
                rep.consec_fail += 1
                rep.last_error = error
                if rep.healthy \
                        and rep.consec_fail >= self.eject_threshold:
                    rep.healthy = False
                    ejected = True
                else:
                    ejected = False
        if ok and readmit:
            READMISSIONS.inc(endpoint=rep.endpoint)
            _events.emit("fleet", action="readmit", endpoint=rep.endpoint)
            self._set_gauges()
        elif not ok and ejected:
            EJECTIONS.inc(endpoint=rep.endpoint)
            _events.emit("fleet", action="eject", endpoint=rep.endpoint,
                         reason=error, consec_fail=rep.consec_fail)
            self._set_gauges()

    def _eject_now(self, rep: _Replica, reason: str):
        """Request-path ejection: a connect failure means the replica
        is gone NOW — waiting out `eject_threshold` poll intervals
        would burn every retry on a corpse. The next passing health
        probe readmits it."""
        with self._lock:
            was = rep.healthy
            rep.healthy = False
            rep.consec_fail = max(rep.consec_fail, self.eject_threshold)
            rep.last_error = reason
        if was:
            EJECTIONS.inc(endpoint=rep.endpoint)
            _events.emit("fleet", action="eject", endpoint=rep.endpoint,
                         reason=reason, path="request")
            self._set_gauges()

    def _set_gauges(self):
        with self._lock:
            healthy = sum(1 for r in self._replicas.values() if r.healthy)
            total = len(self._replicas)
        REPLICAS.set(healthy, state="healthy")
        REPLICAS.set(total - healthy, state="ejected")
        WORLD_SIZE.set(total)

    # -- picking (power-of-two-choices) --------------------------------

    def _pick(self, exclude: frozenset,
              model: Optional[str] = None) -> Optional[_Replica]:
        """Choose a replica: sample two healthy candidates, take the
        lower (cached load + local in-flight delta), then ask its
        breaker. A breaker refusal excludes the candidate and re-picks,
        so an un-chosen candidate never consumes the half-open probe
        slot. `model` restricts candidates to replicas advertising that
        model id (None advertisement = serves anything). Returns None
        when nothing is admissible."""
        tried = set(exclude)
        while True:
            with self._lock:
                cands = [r for r in self._replicas.values()
                         if r.healthy and r.endpoint not in tried
                         and (model is None or r.models is None
                              or model in r.models)]
                if not cands:
                    return None
                if len(cands) > 2:
                    cands = self._rng.sample(cands, 2)
                rep = min(cands, key=lambda r: r.load + r.inflight)
            # allow() outside the router lock: it takes the breaker's
            # own lock and may fire transition hooks
            if rep.breaker.allow():
                with self._lock:
                    rep.picks += 1
                    rep.inflight += 1
                PICKS.inc(endpoint=rep.endpoint)
                return rep
            tried.add(rep.endpoint)

    def _release(self, rep: _Replica):
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)

    # -- HTTP plumbing -------------------------------------------------

    @staticmethod
    def _get_json(endpoint: str, path: str, timeout: float):
        # lint-exempt:traceheader: health/load probes are poll-loop work, not request-scoped
        req = urllib.request.Request(f"http://{endpoint}{path}")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except ValueError:
                body = {}
            return e.code, body

    @staticmethod
    def _post(endpoint: str, path: str, payload: Dict, timeout: float):
        """POST JSON; returns (code, parsed-body). Wire-level failures
        (refused/reset/timeout) raise OSError/URLError for the caller's
        retry classification. The ambient trace context (the attempt
        span _route_predict activates) is injected as `traceparent` so
        the replica's spans join this request's trace."""
        body = json.dumps(_json_safe(payload)).encode()
        req = urllib.request.Request(
            f"http://{endpoint}{path}", data=body,
            headers={"Content-Type": "application/json",
                     **_tracing.trace_headers()})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                parsed = json.loads(e.read())
            except ValueError:
                parsed = {}
            return e.code, parsed

    # -- request path --------------------------------------------------

    def _finish(self, outcome: str, t0: Optional[float] = None):
        REQUESTS.inc(outcome=outcome)
        with self._lock:
            self._counts[outcome] += 1
            if outcome == "ok" and t0 is not None:
                dt = time.monotonic() - t0
                self._lat_window.append((time.monotonic(), dt))
        if outcome == "ok" and t0 is not None:
            REQUEST_SECONDS.observe(time.monotonic() - t0)

    def _retry(self, reason: str, rep: _Replica, error: str):
        RETRIES.inc(reason=reason)
        with self._lock:
            self._retry_counts[reason] = \
                self._retry_counts.get(reason, 0) + 1
        _events.emit("fleet", action="retry", reason=reason,
                     endpoint=rep.endpoint, error=error[:200])

    def _shed_answer(self, rep: _Replica, body: Dict):
        """Classify a replica's typed shed 503 as the request's ANSWER
        (metric + event + counts), then raise TierShed — never called
        on a path that would fail over afterwards."""
        tier = str(body.get("shed"))
        FLEET_SHEDS.inc(tier=tier)
        _events.emit("fleet", action="shed", endpoint=rep.endpoint,
                     tier=tier, shed=body.get("kind"),
                     tenant=body.get("tenant"))
        self._finish("rejected")
        try:
            retry_after = float(body.get("retry_after_s", 1.0))
        except (TypeError, ValueError):
            retry_after = 1.0
        raise TierShed(str(body.get("error") or f"request shed "
                           f"(tier {tier})"),
                       body=body, retry_after_s=retry_after)

    def predict(self, feeds: Dict, timeout_s: Optional[float] = None,
                model: Optional[str] = None,
                tenant: Optional[str] = None) -> Dict:
        """Route one idempotent predict: pick → POST → on failure,
        fail over to a different surviving replica (`retries` times).
        `model` routes to replicas serving that model id; `tenant`
        rides to the replica's QoS admission. Raises NoReplicasError /
        ReplicaRejected / TierShed (QoS shed: an answer, not retried) /
        FleetTimeout / FleetError (replica 500 everywhere) / ValueError
        (the replica's 400 validation echo)."""
        payload = {"feeds": feeds}
        if timeout_s is not None:
            payload["timeout_s"] = timeout_s
        if model is not None:
            payload["model"] = str(model)
        if tenant is not None:
            payload["tenant"] = str(tenant)
        return self._route_predict(payload, timeout_s)

    def _route_predict(self, payload: Dict,
                       timeout_s: Optional[float]) -> Dict:
        with _tracing.trace_span("router.predict", cat="fleet"):
            return self._route_predict_traced(payload, timeout_s)

    def _route_predict_traced(self, payload: Dict,
                              timeout_s: Optional[float]) -> Dict:
        timeout = self.request_timeout_s if timeout_s is None \
            else float(timeout_s)
        model = payload.get("model")
        model = str(model) if model is not None else None
        t0 = time.monotonic()
        exclude: set = set()
        last: Tuple[str, str] = ("", "no replicas known")
        for _attempt in range(self.retries + 1):
            rep = self._pick(frozenset(exclude), model=model)
            if rep is None:
                break
            try:
                # wire budget slightly above the request deadline so the
                # replica's own 504 wins the race when it can; the
                # attempt span is what the replica's spans parent to —
                # each failover attempt is its own child of
                # router.predict, so retry time is attributed per try
                with _tracing.trace_span("router.attempt", cat="fleet",
                                         endpoint=rep.endpoint,
                                         attempt=_attempt):
                    code, body = self._post(rep.endpoint, "/v1/predict",
                                            payload, timeout + 5.0)
            except (OSError, urllib.error.URLError, socket.timeout) as e:
                # connect refused/reset/timeout: replica is gone or
                # wedged — breaker failure, immediate ejection, failover
                rep.breaker.record_failure()
                self._release(rep)
                self._eject_now(rep, f"{type(e).__name__}: {e}"[:200])
                self._retry("connect", rep, str(e))
                exclude.add(rep.endpoint)
                last = (rep.endpoint, f"{type(e).__name__}: {e}")
                continue
            except BaseException as e:
                # anything unexpected (MemoryError, injected faults,
                # KeyboardInterrupt in a worker thread): the admitted
                # call MUST report, or a half-open probe slot leaks and
                # the breaker wedges (PR 10 contract)
                rep.breaker.record_failure()
                self._release(rep)
                raise e
            self._release(rep)
            if code == 200:
                rep.breaker.record_success()
                self._finish("ok", t0)
                return body
            err = str(body.get("error", "")) if isinstance(body, dict) \
                else ""
            if code == 503:
                rep.breaker.record_success()
                if isinstance(body, dict) and body.get("shed"):
                    # QoS tier shed: a deliberate, policy-scoped
                    # ANSWER — failing over would amplify exactly the
                    # overload the shed is relieving
                    self._shed_answer(rep, body)
                self._retry("busy", rep, err)
                exclude.add(rep.endpoint)
                last = (rep.endpoint, f"503: {err}")
                continue
            if code == 404:
                # unknown model on this replica: it is alive — the
                # router's model map was just stale. No breaker
                # penalty; try a replica that does serve it.
                rep.breaker.record_success()
                self._retry("no_model", rep, err)
                exclude.add(rep.endpoint)
                last = (rep.endpoint, f"404: {err}")
                continue
            if code == 504:
                # the request's own deadline died inside the replica;
                # re-sending would double the latency damage
                rep.breaker.record_success()
                self._finish("timeout")
                raise FleetTimeout(
                    f"replica {rep.endpoint} timed out the request: "
                    f"{err}")
            if code == 400:
                # client error: deterministic — no replica will accept it
                rep.breaker.record_success()
                self._finish("error")
                raise ValueError(f"replica rejected request: {err}")
            # 5xx (and anything else): replica-side failure
            rep.breaker.record_failure()
            self._retry("server_error", rep, f"{code}: {err}")
            exclude.add(rep.endpoint)
            last = (rep.endpoint, f"{code}: {err}")
        # retries exhausted / nothing admissible
        ep, why = last
        if not exclude and ep == "":
            self._finish("rejected")
            raise NoReplicasError(
                "no healthy replica admitted the request "
                f"(known: {self.endpoints()})")
        if why.startswith("503"):
            self._finish("rejected")
            raise ReplicaRejected(
                f"every tried replica rejected the request; last "
                f"{ep}: {why}")
        self._finish("error")
        raise FleetError(
            f"request failed on every tried replica; last {ep}: {why}")

    # -- token generation ----------------------------------------------

    def generate(self, ids: Sequence[int], max_new_tokens: int = 16,
                 timeout_s: Optional[float] = None,
                 model: Optional[str] = None,
                 tenant: Optional[str] = None) -> Iterator[Dict]:
        """Streamed generation through the fleet: yields the replica's
        ndjson records ({"token": t}... then the {"done": ...} tail).
        Failover rule (SERVING.md §Fleet): a stream that dies with ZERO
        tokens delivered is resubmitted from scratch on another
        replica; once a token has been yielded a failure raises
        StreamBrokenError — the router will not splice two generations
        together. A QoS tier shed raises TierShed without failover;
        `model` restricts the pick to replicas serving that id."""
        timeout = self.request_timeout_s if timeout_s is None \
            else float(timeout_s)
        payload = {"ids": list(int(i) for i in ids),
                   "max_new_tokens": int(max_new_tokens),
                   "stream": True}
        if model is not None:
            payload["model"] = str(model)
        if tenant is not None:
            payload["tenant"] = str(tenant)
        # captured ONCE: the generator body runs on the consumer's
        # thread across yields, so the ambient contextvar must not be
        # mutated here — per-attempt children are minted explicitly and
        # handed to _stream_one for header injection
        tctx = _tracing.current_trace()
        exclude: set = set()
        last: Tuple[str, str] = ("", "no replicas known")
        for _attempt in range(self.retries + 1):
            rep = self._pick(frozenset(exclude),
                             model=payload.get("model"))
            if rep is None:
                break
            delivered = 0
            child = tctx.child() \
                if tctx is not None and tctx.sampled else tctx
            t0a = time.perf_counter()
            try:
                try:
                    for rec in self._stream_one(rep, payload, timeout,
                                                tctx=child):
                        if "token" in rec:
                            delivered += 1
                        yield rec
                finally:
                    _tracing.record_span_ctx(
                        child, "router.generate", time.perf_counter() -
                        t0a, cat="fleet", t0_perf=t0a,
                        endpoint=rep.endpoint, attempt=_attempt,
                        tokens=delivered)
                rep.breaker.record_success()
                self._release(rep)
                self._finish("ok")
                return
            except (OSError, urllib.error.URLError, socket.timeout,
                    http.client.HTTPException, ValueError) as e:
                # http.client.IncompleteRead is how an abruptly closed
                # chunked stream surfaces — a broken stream, same as a
                # reset socket
                rep.breaker.record_failure()
                self._release(rep)
                self._eject_now(rep, f"{type(e).__name__}: {e}"[:200])
                if delivered:
                    self._finish("error")
                    _events.emit("fleet", action="stream_broken",
                                 endpoint=rep.endpoint, tokens=delivered)
                    raise StreamBrokenError(
                        f"stream from {rep.endpoint} died after "
                        f"{delivered} token(s); resubmit is the "
                        f"client's call", tokens_delivered=delivered)
                self._retry("stream_restart", rep, str(e))
                exclude.add(rep.endpoint)
                last = (rep.endpoint, f"{type(e).__name__}: {e}")
                continue
            except _ReplicaShed as e:
                # QoS tier shed: the ANSWER — no failover, no penalty
                rep.breaker.record_success()
                self._release(rep)
                self._shed_answer(rep, e.body)
            except _ReplicaBusy as e:
                rep.breaker.record_success()
                self._release(rep)
                self._retry("busy", rep, str(e))
                exclude.add(rep.endpoint)
                last = (rep.endpoint, f"503: {e}")
                continue
            except _ReplicaHTTPError as e:
                self._release(rep)
                if e.code == 404:
                    # unknown model here: alive replica, stale model
                    # map — fail over without a breaker penalty
                    rep.breaker.record_success()
                    self._retry("no_model", rep, str(e))
                    exclude.add(rep.endpoint)
                    last = (rep.endpoint, f"404: {e}")
                    continue
                if e.code == 400:
                    # deterministic client error: every replica would
                    # reject it the same way — no retry, no breaker
                    # penalty (the replica behaved correctly)
                    rep.breaker.record_success()
                    self._finish("error")
                    raise ValueError(f"replica rejected generation: "
                                     f"{e}") from None
                # replica-side 5xx: breaker failure + failover, but NO
                # health ejection — the replica answered, it is not gone
                rep.breaker.record_failure()
                self._retry("server_error", rep, f"{e.code}: {e}")
                exclude.add(rep.endpoint)
                last = (rep.endpoint, f"{e.code}: {e}")
                continue
            except GeneratorExit:
                # the CLIENT abandoned the stream (frontend disconnect)
                # — the replica did nothing wrong, but the admitted
                # breaker call must still report to release a probe slot
                rep.breaker.record_success()
                self._release(rep)
                raise
            except BaseException:
                rep.breaker.record_failure()
                self._release(rep)
                raise
        ep, why = last
        self._finish("rejected" if why.startswith("503") else "error")
        raise NoReplicasError(
            f"no replica could serve the generation; last {ep}: {why}")

    def _stream_one(self, rep: _Replica, payload: Dict,
                    timeout: float, tctx=None) -> Iterator[Dict]:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            f"http://{rep.endpoint}/v1/generate", data=body,
            headers={"Content-Type": "application/json",
                     **_tracing.trace_headers(tctx)})
        try:
            resp = urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            try:
                parsed = json.loads(e.read())
            except ValueError:
                parsed = {}
            if not isinstance(parsed, dict):
                parsed = {}
            err = str(parsed.get("error", ""))
            if e.code == 503:
                if parsed.get("shed"):
                    raise _ReplicaShed(parsed)
                raise _ReplicaBusy(err or "replica busy")
            # any other HTTP status: the replica answered — this is NOT
            # a broken wire, and must not ride the URLError-subclass
            # path into record_failure + ejection
            raise _ReplicaHTTPError(e.code, err or f"HTTP {e.code}")
        done = False
        with resp:
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)  # ValueError → broken stream
                if rec.get("done") and rec.get("error"):
                    # replica-side mid-stream failure travels in-band
                    raise ValueError(f"replica error: {rec['error']}")
                if rec.get("done"):
                    done = True
                yield rec
        if not done:
            # a complete ndjson stream ends with a {"done": ...}
            # record; EOF without one means the replica died with the
            # socket closing cleanly — that is a broken stream, not an
            # empty generation
            raise ValueError("stream ended without a done record")

    # -- status --------------------------------------------------------

    def mean_load_per_healthy(self,
                              model: Optional[str] = None
                              ) -> Optional[float]:
        """Mean (cached load + in-flight) across healthy replicas —
        the autoscaler's utilization signal. `model` scopes the mean
        to replicas advertising that model id (per-model autoscaling,
        SERVING.md §Multi-tenancy). None when no replica qualifies
        (which is its own, louder signal)."""
        with self._lock:
            loads = [r.load + r.inflight
                     for r in self._replicas.values()
                     if r.healthy and (model is None or r.models is None
                                       or model in r.models)]
        if not loads:
            return None
        return sum(loads) / len(loads)

    def recent_p99(self, window_s: float = 30.0) -> Optional[float]:
        """p99 of successful predict latencies (seconds) inside the
        trailing `window_s` — the autoscaler's latency signal."""
        cutoff = time.monotonic() - float(window_s)
        with self._lock:
            xs = sorted(dt for (ts, dt) in self._lat_window
                        if ts >= cutoff)
        if not xs:
            return None
        return xs[min(len(xs) - 1, int(round(0.99 * (len(xs) - 1))))]

    def profile(self, seconds: float = 1.0,
                replica: Optional[str] = None,
                timeout_s: Optional[float] = None) -> Dict:
        """Fan POST /v1/profile across the healthy fleet — or at ONE
        member when `replica` names an endpoint — and collect each
        capture's artifact paths. Replicas trace concurrently (one
        thread per target), so a fleet-wide capture covers the same
        wall window on every member; per-replica wire failures land in
        that replica's entry instead of failing the whole fan-out.
        Raises NoReplicasError when nothing is targetable."""
        if replica is not None:
            if replica not in self.endpoints():
                raise NoReplicasError(
                    f"unknown replica {replica!r}; members: "
                    f"{self.endpoints()}")
            targets = [replica]
        else:
            targets = self.healthy_endpoints()
            if not targets:
                raise NoReplicasError("no healthy replicas to profile")
        # the reply can only come back after the capture window closes,
        # so the per-replica HTTP timeout must cover window + export
        timeout = float(timeout_s) if timeout_s is not None \
            else float(seconds) + 30.0
        results: Dict[str, Dict] = {}

        def one(ep):
            try:
                code, body = self._post(
                    ep, "/v1/profile", {"seconds": float(seconds)},
                    timeout)
            except (OSError, urllib.error.URLError) as e:
                code, body = None, {"error": str(e)}
            if not isinstance(body, dict):
                body = {"body": body}
            results[ep] = {"code": code, **body}

        threads = [threading.Thread(target=one, args=(ep,),
                                    daemon=True) for ep in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 10.0)
        ok = sum(1 for r in results.values() if r.get("code") == 200)
        _events.emit("profile", action="fleet", seconds=float(seconds),
                     targets=len(targets), ok=ok)
        return {"seconds": float(seconds), "targets": len(targets),
                "ok": ok, "replicas": results}

    def status(self) -> Dict:
        with self._lock:
            reps = [{
                "endpoint": r.endpoint,
                "healthy": r.healthy,
                "state": r.last_state,
                "breaker": r.breaker.state,
                "load": r.load,
                "inflight": r.inflight,
                "picks": r.picks,
                "consec_fail": r.consec_fail,
                "source": r.source,
                "error": r.last_error,
                "models": sorted(r.models)
                if r.models is not None else None,
            } for r in sorted(self._replicas.values(),
                              key=lambda r: r.endpoint)]
            counts = dict(self._counts)
            retry_counts = dict(self._retry_counts)
        p99 = self.recent_p99()
        return {
            "fleet": True,
            "world_size": len(reps),
            "healthy": sum(1 for r in reps if r["healthy"]),
            "replicas": reps,
            "requests": counts,
            "retries": retry_counts,
            "recent_p99_ms": round(p99 * 1000, 3) if p99 else None,
            "elastic": self._rdzv is not None,
        }


class _ReplicaBusy(RuntimeError):
    """Internal: replica answered 503 to a generate submit."""


class _ReplicaShed(RuntimeError):
    """Internal: replica answered a generate submit with a typed QoS
    shed 503 — an answer, not saturation. Carries the parsed body."""

    def __init__(self, body: Dict):
        super().__init__(str(body.get("error", "request shed")))
        self.body = dict(body)


class _ReplicaHTTPError(RuntimeError):
    """Internal: replica answered a generate submit with a non-503
    HTTP error — the replica is alive and talking, so this must not be
    classified as a broken wire (no ejection; 400 is not even a
    breaker failure)."""

    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = int(code)


# ---------------------------------------------------------------------------
# HTTP frontend
# ---------------------------------------------------------------------------


class _RouterHandler(_base.QuietHandler):
    server_version = "paddle-tpu-fleet-router"
    protocol_version = "HTTP/1.1"
    router_server: "RouterServer" = None  # bound per-server subclass
    _tctx = None  # per-request TraceContext, set at the top of do_*

    def _json_reply(self, code: int, payload: Dict, headers=None):
        hdrs = dict(headers or {})
        hdrs.update(_tracing.response_headers(self._tctx))
        self._reply(code, "application/json",
                    json.dumps(_json_safe(payload)) + "\n",
                    extra_headers=hdrs)

    def _shed_reply(self, e: TierShed):
        """Propagate a replica's typed QoS shed 503 unchanged: the
        body ({"shed": tier, ...}) and Retry-After the replica chose —
        clients of the fleet see exactly what single-replica clients
        see."""
        self._json_reply(
            503, e.body or {"error": str(e), "shed": e.tier},
            headers={"Retry-After":
                     str(max(1, int(round(e.retry_after_s))))})

    def do_GET(self):  # noqa: N802 - stdlib naming
        try:
            self._tctx = _tracing.begin_request(self.headers)
            path = urlparse(self.path).path
            router = self.router_server.router
            if path == "/v1/status":
                self._json_reply(200, router.status())
            elif path == "/v1/healthz":
                healthy = len(router.healthy_endpoints())
                self._json_reply(
                    200 if healthy else 503,
                    {"status": "ok" if healthy else "unavailable",
                     "state": "serving" if healthy else "no_replicas",
                     "healthy_replicas": healthy})
            else:
                self._reply(404, "text/plain",
                            "not found; routes: POST /v1/predict "
                            "/v1/generate, GET /v1/status /v1/healthz\n")
        except _base.CLIENT_GONE:
            pass

    def _chunk(self, line: str):
        data = line.encode("utf-8")
        self.wfile.write(f"{len(data):x}\r\n".encode())
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()

    def _do_generate(self, payload: Dict):
        # the trace ROOT at the fleet edge (or a child of the caller's
        # context): router.generate children mint per-attempt spans and
        # inject traceparent into the upstream replica call
        with _tracing.trace_span("router.http_generate", cat="fleet",
                                 ctx=self._tctx):
            self._do_generate_traced(payload)

    def _do_generate_traced(self, payload: Dict):
        router = self.router_server.router
        ids = payload.get("ids")
        if not isinstance(ids, (list, tuple)) or not ids:
            self._json_reply(400, {"error": 'missing/empty "ids" list'})
            return
        stream = bool(payload.get("stream", True))
        try:
            # parse errors are the CLIENT's (non-numeric ids /
            # max_new_tokens / timeout_s): 400 here, never a dropped
            # connection from a dead handler thread
            ids = [int(i) for i in ids]
            timeout = payload.get("timeout_s")
            kw = dict(max_new_tokens=int(payload.get("max_new_tokens",
                                                     16)),
                      timeout_s=float(timeout)
                      if timeout is not None else None,
                      model=payload.get("model"),
                      tenant=payload.get("tenant"))
        except (ValueError, TypeError) as e:
            self._json_reply(400, {"error": f"malformed generate "
                                           f"request: {e}"})
            return
        if not stream:
            toks, tail = [], {}
            try:
                for rec in router.generate(ids, **kw):
                    if "token" in rec:
                        toks.append(int(rec["token"]))
                    elif rec.get("done"):
                        tail = rec
            except TierShed as e:
                self._shed_reply(e)
                return
            except (NoReplicasError, ReplicaRejected) as e:
                self._json_reply(503, {"error": str(e)})
                return
            except ValueError as e:
                # the replica's own 400 echoed through the fleet
                self._json_reply(400, {"error": str(e)})
                return
            except StreamBrokenError as e:
                self._json_reply(502, {
                    "error": str(e), "type": "StreamBrokenError",
                    "tokens_delivered": e.tokens_delivered})
                return
            except FleetError as e:
                self._json_reply(502, {"error": str(e)})
                return
            self._json_reply(200, {
                "tokens": toks,
                "finish_reason": tail.get("finish_reason"),
                "ttft_ms": tail.get("ttft_ms")})
            return
        # streaming proxy: the first record decides failover, so pull it
        # before committing the 200 (a pre-token failure must fail over
        # inside router.generate, not half-reply to the client)
        gen = router.generate(ids, **kw)
        try:
            first = next(gen)
        except StopIteration:
            self._json_reply(502, {"error": "empty stream from fleet"})
            return
        except TierShed as e:
            self._shed_reply(e)
            return
        except (NoReplicasError, ReplicaRejected) as e:
            self._json_reply(503, {"error": str(e)})
            return
        except ValueError as e:
            self._json_reply(400, {"error": str(e)})
            return
        except FleetError as e:
            self._json_reply(502, {"error": str(e)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-cache")
        for name, value in _tracing.response_headers(self._tctx).items():
            self.send_header(name, value)
        self.end_headers()
        try:
            self._chunk(json.dumps(_json_safe(first)) + "\n")
            for rec in gen:
                self._chunk(json.dumps(_json_safe(rec)) + "\n")
        except _base.CLIENT_GONE:
            gen.close()  # abandons the upstream replica stream too
            return
        except StreamBrokenError as e:
            try:
                self._chunk(json.dumps({
                    "done": True, "error": str(e),
                    "type": "StreamBrokenError",
                    "tokens_delivered": e.tokens_delivered}) + "\n")
            except _base.CLIENT_GONE:
                return
        except FleetError as e:
            try:
                self._chunk(json.dumps({"done": True,
                                        "error": str(e)}) + "\n")
            except _base.CLIENT_GONE:
                return
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()
        self.close_connection = True

    def _do_profile(self, query: str, payload: Dict):
        """POST /v1/profile[?replica=host:port] — proxy the capture to
        one replica or fan it across the healthy fleet. The reply
        aggregates each member's artifact paths (or its failure)."""
        try:
            seconds = float(payload.get("seconds", 1.0))
        except (TypeError, ValueError):
            self._json_reply(400, {"error": '"seconds" must be a '
                                            'number'})
            return
        replica = parse_qs(query).get("replica", [None])[0] \
            or payload.get("replica")
        router = self.router_server.router
        try:
            body = router.profile(seconds, replica=replica,
                                  timeout_s=payload.get("timeout_s"))
        except NoReplicasError as e:
            self._json_reply(503, {"error": str(e)})
            return
        except FleetError as e:
            self._json_reply(502, {"error": str(e)})
            return
        self._json_reply(200, body)

    def do_POST(self):  # noqa: N802 - stdlib naming
        try:
            # trace root at the fleet edge: extract the caller's
            # traceparent or start (head-sample) a fresh trace; every
            # reply echoes X-Request-Id + traceparent
            self._tctx = _tracing.begin_request(self.headers)
            url = urlparse(self.path)
            path = url.path
            if path not in ("/v1/predict", "/v1/generate",
                            "/v1/profile"):
                self._reply(404, "text/plain",
                            "not found; POST routes: /v1/predict, "
                            "/v1/generate, /v1/profile\n")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(length) or b"{}") \
                    if length else {}
            except (ValueError, TypeError):
                self._json_reply(400, {"error": "body must be JSON"})
                return
            if not isinstance(payload, dict):
                self._json_reply(400, {"error": "body must be a JSON "
                                                "object"})
                return
            if path == "/v1/profile":
                self._do_profile(url.query, payload)
                return
            if path == "/v1/generate":
                self._do_generate(payload)
                return
            feeds = payload.get("feeds")
            if not isinstance(feeds, dict) or not feeds:
                self._json_reply(400, {"error":
                                       'missing/empty "feeds" object'})
                return
            router = self.router_server.router
            try:
                with _tracing.activate(self._tctx):
                    body = router._route_predict(
                        payload, payload.get("timeout_s"))
            except TierShed as e:
                self._shed_reply(e)
                return
            except (NoReplicasError, ReplicaRejected) as e:
                self._json_reply(503, {"error": str(e)},
                                 headers={"Retry-After": "1"})
                return
            except FleetTimeout as e:
                self._json_reply(504, {"error": str(e)})
                return
            except ValueError as e:
                self._json_reply(400, {"error": str(e)})
                return
            except FleetError as e:
                self._json_reply(502, {"error": str(e)})
                return
            self._json_reply(200, body)
        except _base.CLIENT_GONE:
            pass


class RouterServer:
    """HTTP face of the fleet: the same /v1 surface as a single
    replica, served by a Router. start() begins polling + listening;
    stop() is idempotent and atexit-safe."""

    def __init__(self, router: Router, host: Optional[str] = None):
        self.router = router
        handler = type("_BoundRouterHandler", (_RouterHandler,),
                       {"router_server": self})
        self._http = _base.HTTPServerHandle(
            handler, thread_name="paddle-tpu-fleet-router-http")
        self._host = host

    def start(self, port: int = 0) -> int:
        self.router.start()
        try:
            return self._http.start(port, host=self._host)
        except BaseException:
            self.router.stop()  # failed bind must not leak the poller
            raise

    def stop(self):
        self._http.stop()
        self.router.stop()

    def port(self) -> Optional[int]:
        return self._http.port()
