"""Paged (blocked) KV cache for autoregressive decode.

The classic serving memory problem (vLLM SOSP'23): a contiguous
per-sequence KV buffer must be sized for max_seq_len, so HBM scales
with max_len × batch even when most sequences are short — and XLA's
static shapes make "grow the buffer" a recompile. The paged design
keeps ONE preallocated device pool of fixed-size blocks per layer
(`[L, num_blocks, block_size, kv_heads, head_dim]`) plus a tiny
per-sequence *block table* mapping logical positions to pool blocks.
Memory then scales with LIVE TOKENS (rounded up to the block size),
sequences grow by appending a block id to their table — a host-side
int, never a new executable — and the decode executable's shapes stay
fixed no matter which sequences are resident.

Layering: this module owns the host-side `BlockAllocator` (free-list,
alloc/free, fragmentation accounting) and the pure jnp pool helpers
(`init_pools`, `write_token_kv`, `write_prefill_kv`, `gather_kv`)
that `models/gpt.py` composes into its decode-step attention. The
scheduler that decides WHICH sequences own which blocks lives in
`serving/decode.py`.

Block 0 is reserved as the *null block*: padded/inactive decode slots
and out-of-range table entries all read and write it, so a fixed-shape
executable needs no validity branches — the attention length mask
already guarantees nothing read from the null block ever contributes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KVCacheConfig", "BlockAllocator", "NoBlocksError",
           "init_pools", "write_token_kv", "write_prefill_kv",
           "write_chunk_kv", "write_span_kv", "gather_kv",
           "NULL_BLOCK"]

NULL_BLOCK = 0


class NoBlocksError(RuntimeError):
    """The pool has fewer free blocks than the allocation needs (the
    scheduler reacts by deferring admission or preempting a sequence —
    never by growing the pool, whose size is baked into executables)."""


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Shape of the device pool. `max_len` bounds any single sequence
    (prompt + generated) and fixes the block-table width every decode
    executable is compiled against."""

    layers: int
    kv_heads: int
    head_dim: int
    max_len: int
    block_size: int = 16
    num_blocks: int = 64
    dtype: str = "bfloat16"

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-int(self.max_len) // int(self.block_size))

    @property
    def usable_blocks(self) -> int:
        return int(self.num_blocks) - 1  # block 0 is the null block

    def pool_bytes(self) -> int:
        """Device bytes of BOTH pools (K and V)."""
        per = (self.layers * self.num_blocks * self.block_size *
               self.kv_heads * self.head_dim)
        return 2 * per * jnp.dtype(self.dtype).itemsize


class BlockAllocator:
    """Host-side free-list over the pool's block ids (1..num_blocks-1;
    block 0 is never handed out). Single-owner by design — the decode
    scheduler thread is the only caller — so no locking here.

    Fragmentation accounting: paged allocation has no *external*
    fragmentation (any free block serves any sequence), so the number
    reported is *internal* waste — slots allocated but not (yet)
    holding a live token — which `waste_fraction` reports against the
    allocated capacity."""

    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        if cfg.num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is reserved), got "
                f"{cfg.num_blocks}")
        self._free: List[int] = list(range(cfg.num_blocks - 1, 0, -1))
        self._owned: Dict[int, bool] = {}

    def free_blocks(self) -> int:
        return len(self._free)

    def used_blocks(self) -> int:
        return len(self._owned)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Take n blocks off the free list; raises NoBlocksError
        without allocating anything when fewer than n are free (a
        partial grant would leak on the caller's error path)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise NoBlocksError(
                f"need {n} blocks, only {len(self._free)} of "
                f"{self.cfg.usable_blocks} free")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._owned[b] = True
        return out

    def free(self, blocks: Sequence[int]):
        """Return blocks to the pool. Double-free and foreign ids are
        programming errors and raise — silently re-listing a block
        would hand the same block to two sequences."""
        for b in blocks:
            if b == NULL_BLOCK:
                raise ValueError("block 0 (null block) is never "
                                 "allocated and cannot be freed")
            if b not in self._owned:
                raise ValueError(f"block {b} is not allocated "
                                 "(double free?)")
            del self._owned[b]
            self._free.append(int(b))

    def stats(self, live_tokens: int = 0) -> Dict[str, float]:
        used = self.used_blocks()
        cap = used * self.cfg.block_size
        waste = max(0, cap - int(live_tokens))
        return {
            "blocks_total": self.cfg.usable_blocks,
            "blocks_free": self.free_blocks(),
            "blocks_used": used,
            "block_size": self.cfg.block_size,
            "live_tokens": int(live_tokens),
            "allocated_token_capacity": cap,
            "internal_waste_tokens": waste,
            "waste_fraction": round(waste / cap, 4) if cap else 0.0,
            "pool_bytes": self.cfg.pool_bytes(),
        }


# ---------------------------------------------------------------------------
# Pure pool helpers (traced into the decode/prefill executables)
# ---------------------------------------------------------------------------


def init_pools(cfg: KVCacheConfig) -> Tuple[jax.Array, jax.Array]:
    """Zeroed K and V pools, `[L, NB, BS, kv_heads, head_dim]`."""
    shape = (cfg.layers, cfg.num_blocks, cfg.block_size, cfg.kv_heads,
             cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def write_token_kv(pool_l: jax.Array, kv: jax.Array,
                   block_tables: jax.Array, positions: jax.Array,
                   block_size: int) -> jax.Array:
    """Scatter one token's K (or V) per slot into a single layer's pool
    slice. pool_l `[NB, BS, H, D]`, kv `[S, H, D]`, block_tables
    `[S, MB]`, positions `[S]`. Inactive slots carry all-zero tables,
    so their writes land in the null block."""
    blk = jnp.take_along_axis(
        block_tables, (positions // block_size)[:, None], axis=1)[:, 0]
    slot = positions % block_size
    return pool_l.at[blk, slot].set(kv)


def write_prefill_kv(pool_l: jax.Array, kv: jax.Array,
                     block_table: jax.Array, block_size: int) -> jax.Array:
    """Scatter a whole prompt's K (or V) into one layer's pool slice.
    pool_l `[NB, BS, H, D]`, kv `[T, H, D]` (positions 0..T-1),
    block_table `[MB]`. Positions past the sequence's allocated blocks
    hit table entries that are still 0 and land in the null block;
    positions inside the last allocated block but past the true length
    write garbage slots that the decode step overwrites before any
    mask ever lets them be read."""
    t = jnp.arange(kv.shape[0], dtype=jnp.int32)
    blk = block_table[t // block_size]
    slot = t % block_size
    return pool_l.at[blk, slot].set(kv)


def write_chunk_kv(pool_l: jax.Array, kv: jax.Array,
                   block_table: jax.Array, start: jax.Array,
                   block_size: int) -> jax.Array:
    """Scatter one prompt SLICE's K (or V) into one layer's pool slice
    (chunked prefill). pool_l `[NB, BS, H, D]`, kv `[C, H, D]` holding
    positions start..start+C-1, block_table `[MB]`. Positions past the
    table width are redirected to the null block (the final chunk's
    edge-padded tail can run past max_len); positions inside allocated
    blocks but past the true prompt length write garbage slots that
    later writes overwrite before any mask lets them be read — the
    same contract as write_prefill_kv."""
    t = jnp.arange(kv.shape[0], dtype=jnp.int32) + start
    bi = t // block_size
    mb = block_table.shape[0]
    blk = jnp.where(bi < mb, block_table[jnp.minimum(bi, mb - 1)],
                    NULL_BLOCK)
    return pool_l.at[blk, t % block_size].set(kv)


def write_span_kv(pool_l: jax.Array, kv: jax.Array,
                  block_tables: jax.Array, positions: jax.Array,
                  block_size: int) -> jax.Array:
    """Scatter a W-token span per slot into one layer's pool slice
    (speculative verification). pool_l `[NB, BS, H, D]`, kv
    `[S, W, H, D]` holding each slot's positions p..p+W-1, block_tables
    `[S, MB]`, positions `[S]` = each slot's span start. Slots with
    all-zero tables (inactive / masked out) write the null block; span
    positions past the table width are redirected there too."""
    w = kv.shape[1]
    t = positions[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    bi = t // block_size
    mb = block_tables.shape[1]
    blk = jnp.take_along_axis(block_tables, jnp.minimum(bi, mb - 1),
                              axis=1)
    blk = jnp.where(bi < mb, blk, NULL_BLOCK)
    return pool_l.at[blk, t % block_size].set(kv)


def gather_kv(pool_l: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Gather every slot's full (padded) context from one layer's pool
    slice: `[NB, BS, H, D]` × `[S, MB]` → `[S, MB*BS, H, D]`. The
    caller masks positions `> position` (unwritten tail + null-block
    reads of inactive slots)."""
    s, mb = block_tables.shape
    ctx = pool_l[block_tables]                       # [S, MB, BS, H, D]
    return ctx.reshape(s, mb * pool_l.shape[1], *pool_l.shape[2:])


def build_block_table(blocks: Sequence[int], max_blocks: int) -> np.ndarray:
    """Host helper: a sequence's padded table row (unused tail = null
    block)."""
    row = np.zeros((max_blocks,), np.int32)
    n = len(blocks)
    if n > max_blocks:
        raise ValueError(f"{n} blocks exceed table width {max_blocks}")
    row[:n] = np.asarray(list(blocks), np.int32)
    return row
