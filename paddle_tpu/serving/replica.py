"""One fleet replica process: `python -m paddle_tpu.serving.replica`.

The unit the ReplicaSupervisor (distributed/launch_serve.py) spawns and
the Router (serving/router.py) discovers: boots a `serving.Server` —
from a PR 6 warmstart artifact when one is given, so a scale-out
replica is serving in seconds instead of paying an XLA warmup —
registers its endpoint as a PR 9 `FileRendezvous` member (worker_id IS
the "host:port" endpoint; the heartbeat thread keeps it live), and
serves until SIGTERM, which triggers the graceful scale-in sequence:

  1. leave the rendezvous (the router's next poll stops picking us),
  2. drain (listener stays up: in-flight work finishes, stragglers get
     503 + Retry-After and fail over through the router),
  3. stop, exit 0 (rc 0 tells the supervisor the exit was deliberate —
     anything else is a crash and respawns the slot).

Serving membership needs no generations/barrier — replicas never form a
collective — so this module uses only register/heartbeat/leave from the
rendezvous protocol; the router reads `live_members()`.

Stdout speaks one JSON "ready" line once serving (the supervisor and
benches wait on it): {"ready": true, "endpoint": ..., "pid": ...,
"warmstart_adopted": n, "slot": k}.

Multi-tenant flags (SERVING.md §Multi-tenancy): `--model-id` names the
model this replica serves (advertised to the router through /v1/load),
`--qos FILE` loads a tier/tenant policy JSON enabling weighted-fair
admission, and `--registry DIR` watches a model registry so newly
published artifact versions are hot-swapped in without a restart.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def _build_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.serving.replica", description=__doc__)
    ap.add_argument("--model-dir", default="",
                    help="saved inference model for the predict path "
                    "(optional when --decode-tiny builds a decode-only "
                    "replica)")
    ap.add_argument("--decode-tiny", type=int, default=None,
                    metavar="SEED",
                    help="attach a tiny-GPT continuous-batching decode "
                    "engine initialized from this seed — the fleet "
                    "bench / trace-gate shape of a token-serving "
                    "replica (POST /v1/generate)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 binds an ephemeral port (printed in the "
                    "ready line and registered in the rendezvous)")
    ap.add_argument("--rdzv-dir", default="",
                    help="fleet membership store (PADDLE_TPU_RDZV_DIR "
                    "fallback); empty = standalone replica")
    ap.add_argument("--warmstart", default="",
                    help="PR 6 warmstart artifact: boot without paying "
                    "XLA compiles")
    ap.add_argument("--slot", type=int, default=-1,
                    help="supervisor slot id (informational)")
    ap.add_argument("--buckets", default="",
                    help="comma batch buckets (default: policy pow2)")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-queue", type=int, default=128)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--timeout-s", type=float, default=30.0)
    ap.add_argument("--precision", default="f32")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0)
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX_PLATFORMS=cpu before jax loads "
                    "(fleet simulation / tests)")
    ap.add_argument("--model-id", default="default",
                    help="model id this replica's default slot serves "
                    "(advertised in /v1/load for the router's "
                    "model-aware picks; SERVING.md §Multi-tenancy)")
    ap.add_argument("--qos", default="",
                    help="path to a QoS policy JSON file ({tiers, "
                    "default_tier, tenants}) enabling tiered "
                    "admission + weighted-fair scheduling")
    ap.add_argument("--registry", default="",
                    help="model registry root to watch: newly "
                    "published artifact versions are hot-swapped in "
                    "with zero downtime")
    ap.add_argument("--registry-poll-s", type=float, default=1.0)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = _build_args(argv)
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    from .engine import ServingConfig
    from .httpd import Server

    if not args.model_dir and args.decode_tiny is None:
        print(json.dumps({"ready": False,
                          "error": "need --model-dir and/or "
                                   "--decode-tiny"}), flush=True)
        return 2
    decode = None
    if args.decode_tiny is not None:
        import jax

        from ..models import gpt
        from .decode import DecodeConfig, DecodeEngine

        mcfg = gpt.GPTConfig.tiny()
        mcfg.dtype = "float32"
        params, _ = gpt.init(jax.random.key(int(args.decode_tiny)), mcfg)
        decode = DecodeEngine(params, mcfg, DecodeConfig(
            block_size=8, num_blocks=64, decode_slots=(4,),
            prefill_buckets=(8, 16), precision="f32", max_len=64))
    buckets = tuple(int(b) for b in args.buckets.split(",")) \
        if args.buckets else None
    qos = None
    if args.qos:
        with open(args.qos) as f:
            qos = json.load(f)
    cfg = ServingConfig(
        args.model_dir or None, buckets=buckets,
        max_batch=args.max_batch,
        max_queue=args.max_queue, max_wait_ms=args.max_wait_ms,
        timeout_s=args.timeout_s, precision=args.precision,
        warmstart=args.warmstart or None, use_tpu=not args.cpu,
        host=args.host, qos=qos, model_id=args.model_id)
    server = Server(cfg, decode=decode)
    if args.registry:
        from .registry import ModelRegistry

        server.attach_registry(ModelRegistry(args.registry),
                               poll_s=args.registry_poll_s)
    port = server.start(args.port)
    endpoint = f"{args.host}:{port}"
    # env-gated time-series recording (PADDLE_TPU_TS_DIR): Server.start
    # already tried; call again explicitly so a replica records even
    # when the supervisor flips the env on between respawns
    from ..observability import timeseries as _timeseries

    _timeseries.maybe_start_recorder()

    rdzv = None
    rdzv_dir = args.rdzv_dir or os.environ.get("PADDLE_TPU_RDZV_DIR", "")
    if rdzv_dir:
        from ..distributed.rendezvous import FileRendezvous

        rdzv = FileRendezvous(rdzv_dir, worker_id=endpoint,
                              min_workers=1,
                              heartbeat_s=args.heartbeat_s,
                              dead_after_s=max(2.5,
                                               5 * args.heartbeat_s))
        rdzv.register()
        rdzv.start_heartbeat()

    stop_ev = threading.Event()

    def _on_term(signum, frame):
        stop_ev.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    print(json.dumps({
        "ready": True, "endpoint": endpoint, "pid": os.getpid(),
        "slot": args.slot,
        "warmstart_adopted":
            server._engine.warmstart_adopted
            if server._engine is not None else 0}), flush=True)

    stop_ev.wait()
    # graceful scale-in: stop being routable FIRST, then finish the
    # in-flight work, then tear down (SERVING.md §Fleet drain contract)
    if rdzv is not None:
        rdzv.leave()
    server.drain(timeout=args.drain_timeout_s)
    server.stop()
    # publish any buffered sampled spans before exit so the trace-dir
    # reassembly (obsdump trace) sees this replica's half of the tree,
    # and take the recorder's final time-series sample for the same
    # reason (a replica shorter than the interval must still record)
    from ..observability import tracing as _tracing

    _tracing.flush_trace_sink()
    _timeseries.stop_recorder()
    return 0


if __name__ == "__main__":
    sys.exit(main())
