"""Block-level KV reuse: prefix caching, COW, and speculative accept.

The paged KV cache (kv_cache.py) already stores every sequence's K/V in
fixed-size pool blocks addressed through per-sequence block tables —
the exact structure the vLLM/PagedAttention sharing model (Kwon et al.,
SOSP'23) and SGLang's RadixAttention prefix reuse exploit: two prompts
that agree on their first N·block_size tokens can point their first N
table entries at the SAME pool blocks, and the later request skips
recomputing that prefix entirely. This module owns the host-side state
that makes sharing safe:

- **`ReuseBlockAllocator`** — the `BlockAllocator` free-list made
  ref-counted, plus a content-hash index over FULL blocks. The hash is
  a chain (`h_j = H(h_{j-1} ‖ tokens[j·bs:(j+1)·bs])`), so a block's
  hash commits to its entire prefix — a flat per-block hash would let
  block j of one prompt match block j of a different prefix. A lookup
  (`match_prefix`) resolves the longest run of cached blocks and takes
  a reference on each; `free` is decref: the last reference moves a
  *registered* block onto an LRU of retained-but-unreferenced blocks
  (still serving future hits) instead of the free list, and `alloc`
  evicts from that LRU oldest-first when the free list alone cannot
  satisfy a request — so cached prefixes cost nothing until the pool
  is actually short, and the existing recompute-preemption path
  composes unchanged on top (preemption decrefs; eviction reclaims).

- **Sharing rule** — only FULL blocks are ever shared, and only while
  at least one prompt token remains to compute (block j of a prompt of
  length L is reusable iff `(j+1)·bs ≤ L-1`), so the computed suffix
  always starts on a block boundary and produces the first-token
  logits. Full prompt blocks are never written again (decode/verify
  writes land at positions ≥ L), so shared blocks are read-only by
  construction.

- **Copy-on-write** — the safety net behind that construction: before
  the scheduler writes into a block, `is_shared`/`cow_alloc` give it a
  private replacement (the engine device-copies the contents and swaps
  the table entry). Unreachable in the normal admission flow, counted
  (`event="cow"`) and tested via a forced share.

- **Speculative accept rule** (`accept_length`) — the exact greedy
  acceptance for speculative decoding: draft tokens d_1..d_k are
  accepted up to the longest prefix where d_j equals the target's own
  greedy output o_{j-1}; the emitted tokens o_0..o_a are then
  bit-identical to plain one-token-per-step decode by induction.

Locking: the decode scheduler thread is the only mutator, but
`/v1/status` and the memwatch bytes provider read the cache accounting
from other threads, so all state is guarded by a lockcheck-named lock
(leaf-level: nothing else is acquired while it is held).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import metrics as _m
from .kv_cache import BlockAllocator, KVCacheConfig, NoBlocksError, \
    NULL_BLOCK

__all__ = ["ReuseBlockAllocator", "hash_blocks", "accept_length",
           "PREFIX_CACHE", "BLOCKS_REUSED", "SPEC_ACCEPT_RATE"]

PREFIX_CACHE = _m.counter(
    "paddle_tpu_prefix_cache_total",
    "Prefix-cache block events: hit (admission resolved a prompt "
    "block from the index), miss (a hashed full block had no cached "
    "counterpart), evict (an unreferenced cached block reclaimed "
    "under pool pressure), cow (a shared block copied before a write)",
    labelnames=("event",))
BLOCKS_REUSED = _m.gauge(
    "paddle_tpu_decode_blocks_reused",
    "Cumulative KV blocks resolved from the prefix cache instead of "
    "being recomputed (each saves block_size prefill tokens)")
SPEC_ACCEPT_RATE = _m.gauge(
    "paddle_tpu_decode_spec_accept_rate",
    "Running speculative-decoding accept rate: draft tokens accepted "
    "by target verification / draft tokens proposed, since boot")

_HASH_SEED = b"paddle_tpu-kv-prefix-v1:"


def hash_blocks(tokens, block_size: int) -> List[bytes]:
    """Chain hashes for every FULL block of a token sequence: one
    digest per block, each committing to the whole prefix up to and
    including that block (`h_j = H(h_{j-1} ‖ block_j_tokens)`). The
    trailing partial block (if any) gets no hash — partial blocks are
    never shared."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32).ravel())
    bs = int(block_size)
    h = hashlib.sha256(_HASH_SEED + str(bs).encode()).digest()
    out: List[bytes] = []
    for j in range(len(toks) // bs):
        h = hashlib.sha256(h + toks[j * bs:(j + 1) * bs].tobytes()) \
            .digest()
        out.append(h)
    return out


def accept_length(draft: Sequence[int], out: Sequence[int]) -> int:
    """Exact greedy acceptance: `draft` = the k proposed tokens,
    `out` = the target's k+1 verification outputs (out[j] is what the
    target emits after accepting draft[:j]). Returns a — the longest
    prefix with draft[j] == out[j] — so emitting out[:a+1] reproduces
    plain greedy decode exactly: out[a] is the target's own correction
    (or, on full accept, its bonus token)."""
    a = 0
    for j in range(len(draft)):
        if int(draft[j]) != int(out[j]):
            break
        a += 1
    return a


class ReuseBlockAllocator(BlockAllocator):
    """Ref-counted `BlockAllocator` with a content-hash prefix index
    and LRU retention of unreferenced cached blocks.

    Block lifecycle: alloc → refcount 1 → (register with a chain hash)
    → shared via match_prefix (refcount += 1 per reader) → free is
    decref → at refcount 0 a registered block parks on the LRU (still
    indexed, evictable), an unregistered one returns to the free list.
    `can_alloc`/`alloc` treat LRU blocks as allocatable: eviction
    (oldest first) is folded into allocation, so callers — admission,
    mid-decode growth, preemption retries — need no new code paths."""

    def __init__(self, cfg: KVCacheConfig):
        super().__init__(cfg)
        from ..analysis import lockcheck as _lockcheck

        self._lock = _lockcheck.Lock(
            name="serving.kv_reuse.ReuseBlockAllocator._lock")
        self._refs: Dict[int, int] = {}
        self._hash_of: Dict[int, bytes] = {}     # block -> chain hash
        self._index: Dict[bytes, int] = {}       # chain hash -> block
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.reused_total = 0
        self.evicted_total = 0
        self.cow_total = 0
        self.hits_total = 0
        self.misses_total = 0

    # -- capacity ------------------------------------------------------

    def cached_blocks(self) -> int:
        with self._lock:
            return len(self._lru)

    def can_alloc(self, n: int) -> bool:
        with self._lock:
            return n <= len(self._free) + len(self._lru)

    def _evict_for_locked(self, n: int):
        """Reclaim LRU cached blocks until the free list holds n."""
        evicted = 0
        while len(self._free) < n:
            blk, _ = self._lru.popitem(last=False)       # oldest first
            del self._index[self._hash_of.pop(blk)]
            self._free.append(int(blk))
            evicted += 1
        if evicted:
            self.evicted_total += evicted
            PREFIX_CACHE.inc(evicted, event="evict")

    def alloc(self, n: int) -> List[int]:
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        with self._lock:
            if n > len(self._free) + len(self._lru):
                raise NoBlocksError(
                    f"need {n} blocks, only {len(self._free)} free + "
                    f"{len(self._lru)} evictable of "
                    f"{self.cfg.usable_blocks}")
            self._evict_for_locked(n)
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._owned[b] = True
                self._refs[b] = 1
        return out

    def free(self, blocks: Sequence[int]):
        """Decref. The last reference parks a registered block on the
        LRU (contents retained for future hits); an unregistered block
        goes straight back to the free list. Double-free still raises."""
        with self._lock:
            for b in blocks:
                if b == NULL_BLOCK:
                    raise ValueError("block 0 (null block) is never "
                                     "allocated and cannot be freed")
                r = self._refs.get(b)
                if r is None:
                    raise ValueError(f"block {b} is not allocated "
                                     "(double free?)")
                if r > 1:
                    self._refs[b] = r - 1
                    continue
                del self._refs[b]
                del self._owned[b]
                if b in self._hash_of:
                    self._lru[b] = None
                else:
                    self._free.append(int(b))

    # -- prefix index --------------------------------------------------

    def register(self, block: int, h: bytes):
        """Index a live FULL block under its chain hash (called once
        its contents are final — full prompt blocks are never written
        again). First registration wins: an identical block already in
        the index keeps serving hits and `block` stays private."""
        with self._lock:
            if block not in self._refs:
                raise ValueError(
                    f"block {block} is not live; only referenced "
                    "blocks can be registered")
            other = self._index.get(h)
            if other is not None and other != block:
                return
            self._index[h] = block
            self._hash_of[block] = h

    def match_prefix(self, hashes: Sequence[bytes]) -> List[int]:
        """Resolve the longest run of cached blocks for a prompt's
        chain hashes, taking one reference on each match (a hit on an
        LRU-parked block revives it). Returns the matched block ids in
        prefix order — the caller splices them into the new sequence's
        block table and prefills only from `len(matches)·block_size`."""
        out: List[int] = []
        with self._lock:
            for h in hashes:
                b = self._index.get(h)
                if b is None:
                    break
                if b in self._refs:
                    self._refs[b] += 1
                else:
                    self._lru.pop(b, None)
                    self._refs[b] = 1
                    self._owned[b] = True
                out.append(b)
            hits, misses = len(out), len(hashes) - len(out)
            self.hits_total += hits
            self.misses_total += misses
            self.reused_total += hits
        if hits:
            PREFIX_CACHE.inc(hits, event="hit")
        if misses:
            PREFIX_CACHE.inc(misses, event="miss")
        BLOCKS_REUSED.set(self.reused_total)
        return out

    # -- sharing / COW -------------------------------------------------

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._refs.get(block, 0)

    def incref(self, block: int):
        with self._lock:
            if block not in self._refs:
                raise ValueError(f"block {block} is not allocated")
            self._refs[block] += 1

    def is_shared(self, block: int) -> bool:
        with self._lock:
            return self._refs.get(block, 0) > 1

    def cow_alloc(self, block: int) -> int:
        """Copy-on-write: allocate a private replacement for a shared
        block and drop the caller's reference on the original. The
        caller device-copies the pool contents old→new and swaps its
        block-table entry. Raises NoBlocksError (nothing changed) when
        the pool cannot supply the replacement."""
        with self._lock:
            if self._refs.get(block, 0) < 2:
                raise ValueError(
                    f"block {block} is not shared (refcount "
                    f"{self._refs.get(block, 0)}); copy-on-write is "
                    "only for shared blocks")
            if 1 > len(self._free) + len(self._lru):
                raise NoBlocksError(
                    f"copy-on-write needs 1 block, 0 free of "
                    f"{self.cfg.usable_blocks}")
            self._evict_for_locked(1)
            new = self._free.pop()
            self._owned[new] = True
            self._refs[new] = 1
            self._refs[block] -= 1
            self.cow_total += 1
        PREFIX_CACHE.inc(event="cow")
        return new

    # -- accounting ----------------------------------------------------

    def stats(self, live_tokens: int = 0) -> Dict[str, float]:
        s = super().stats(live_tokens)
        with self._lock:
            s.update({
                "blocks_cached": len(self._lru),
                "blocks_reused_total": self.reused_total,
                "prefix_hits_total": self.hits_total,
                "prefix_misses_total": self.misses_total,
                "evictions_total": self.evicted_total,
                "cow_total": self.cow_total,
            })
        return s
