"""JSON-over-HTTP serving frontend + the `Server` that ties the
subsystem together (engine + batcher + HTTP, one object to start/stop).

Routes (schema documented in SERVING.md §HTTP API):

  POST /v1/predict   {"feeds": {name: nested-list}, "timeout_s": opt}
                     → 200 {"outputs": {name: nested-list}, "batch": n}
                     → 400 malformed request / bad shapes
                     → 503 queue full or draining (admission control —
                       the client should back off or retry elsewhere)
                     → 504 request missed its deadline
                     → 500 engine error
  POST /v1/generate  {"ids": [tok,...], "max_new_tokens": N,
                      "stream": true|false, "timeout_s": opt}
                     token generation on the continuous-batching decode
                     engine (SERVING.md §Continuous batching). With
                     stream=true (default): a chunked
                     application/x-ndjson body, one {"token": t} line
                     per generated token as the scheduler emits it,
                     closed by {"done": true, "finish_reason": ...,
                     "tokens": n, "ttft_ms": x}. With stream=false: one
                     JSON reply carrying the full token list. 503 when
                     the decode queue is full, 404 when the server has
                     no decode engine attached.
  GET  /v1/status    queue depth, buckets, request/batch counters,
                     decode queue/slot-occupancy/TTFT block, uptime —
                     the operator's one-look view
  GET  /v1/load      the router's cheap load probe (SERVING.md §Fleet):
                     {"load": scalar, "inflight": n, "queue_depth": q,
                     "state": ...} touching only the batcher/decode
                     counters — power-of-two-choices picks must not pay
                     a full status() walk per poll
  GET  /v1/healthz   readiness, with a real serving-state signal for
                     the fleet router's health ejection: 200 only while
                     state == "serving"; 503 with {"state": "warming"}
                     before every bucket/phase is warmed, {"state":
                     "draining"} after drain() began (scale-in), and
                     {"state": "stopped"} once the decode engine or
                     batcher is gone. (The process-wide anomaly-aware
                     probe stays on the observability server,
                     PADDLE_TPU_METRICS_PORT.)
  GET  /v1/models    the multi-model surface (SERVING.md
                     §Multi-tenancy): one row per model slot — id,
                     program digest, adopted registry version, warm
                     state, per-slot request counts.

Multi-tenancy (SERVING.md §Multi-tenancy): /v1/predict and
/v1/generate accept optional "model" and "tenant" payload fields. A
`Server` holds one engine+batcher slot per model id (all sharing the
process and its HBM budget); QoS shed/quota rejections answer 503 with
a Retry-After header and the typed body {"shed": "<tier>", "kind":
"queue"|"quota"} that the fleet router classifies as an answer rather
than a retryable failure. `hot_swap()` (and the registry watcher
behind `attach_registry()`) replaces a slot's engine with one built
from a newly published artifact while the old batcher drains — zero
failed requests, and zero fresh compiles when the artifact's
executables are adopted.

Built on `observability.httpbase` — same silent logging, locked
idempotent start/stop, daemon threading, and atexit discipline as the
/metrics endpoint. Feed dtypes need not be declared client-side: the
Predictor casts to the model's declared feed dtypes, so plain JSON
numbers round-trip.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional
from urllib.parse import urlparse

import numpy as np

from ..observability import events as _events
from ..observability import httpbase as _base
from ..observability import memwatch as _memwatch
from ..observability import slo as _slo
from ..observability import timeseries as _timeseries
from ..observability import tracing as _tracing
from ..observability import metrics as _m
from ..observability.metrics import _json_safe
from .decode import DecodeEngine
from .batcher import (Batcher, EngineError, QueueFullError,
                      RequestTimeout, ServerClosed)
from .engine import Engine, ServingConfig
from .qos import QoSPolicy, ShedError

__all__ = ["Server"]

MODEL_SWAPS = _m.counter(
    "paddle_tpu_model_swaps_total",
    "Completed zero-downtime model hot-swaps, by model id",
    labelnames=("model",))


class _ServingHandler(_base.QuietHandler):
    server_version = "paddle-tpu-serving"
    # chunked transfer (the /v1/generate stream) needs HTTP/1.1; all
    # non-chunked replies already send explicit Content-Length, which
    # 1.1 keep-alive requires
    protocol_version = "HTTP/1.1"
    serving: "Server" = None  # bound per-Server via a subclass

    _tctx = None  # per-request TraceContext, set at the top of do_*

    def _json_reply(self, code: int, payload: Dict, headers=None):
        # strict-JSON discipline (same as metrics.dump): a model output
        # containing NaN/Inf must not make json.dumps emit bare NaN
        # tokens that RFC-8259 clients reject — non-finite floats become
        # strings ("nan"/"inf"/"-inf"), documented in SERVING.md
        hdrs = dict(headers or {})
        # every /v1/* reply carries the request id + traceparent so the
        # caller (and the fleet router's logs) can join against the
        # trace sink and the JSONL event log (SERVING.md §HTTP API)
        hdrs.update(_tracing.response_headers(self._tctx))
        self._reply(code, "application/json",
                    json.dumps(_json_safe(payload)) + "\n",
                    extra_headers=hdrs)

    def do_GET(self):  # noqa: N802 - stdlib naming
        try:
            self._tctx = _tracing.begin_request(self.headers)
            path = urlparse(self.path).path
            if path == "/v1/status":
                self._json_reply(200, self.serving.status())
            elif path == "/v1/load":
                self._json_reply(200, self.serving.load())
            elif path == "/v1/healthz":
                state = self.serving.state()
                self._json_reply(
                    200 if state == "serving" else 503,
                    {"status": "ok" if state == "serving"
                     else "unavailable", "state": state})
            elif path == "/v1/models":
                self._json_reply(200, {"models": self.serving.models()})
            else:
                self._reply(404, "text/plain",
                            "not found; routes: POST /v1/predict, "
                            "GET /v1/status /v1/load /v1/healthz "
                            "/v1/models\n")
        except _base.CLIENT_GONE:
            pass

    # -- token streaming (/v1/generate) --------------------------------

    def _chunk(self, line: str):
        data = line.encode("utf-8")
        self.wfile.write(f"{len(data):x}\r\n".encode())
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()

    def _shed_reply(self, e: ShedError):
        """The typed shed/quota 503: Retry-After + {"shed": tier} body
        the fleet router classifies as an ANSWER (no failover retry) —
        re-sending a deliberately shed request onto a surviving replica
        amplifies exactly the overload the shed is relieving."""
        self._json_reply(
            503, {"error": str(e), "shed": e.tier, "kind": e.kind,
                  "tenant": e.tenant,
                  "retry_after_s": e.retry_after_s},
            headers={"Retry-After":
                     str(max(1, int(round(e.retry_after_s))))})

    def _do_generate(self, payload: Dict):
        from .batcher import QueueFullError, ServerClosed

        model = payload.get("model")
        decode = self.serving._decode_for(model)
        if decode is None:
            if model is not None \
                    and str(model) not in self.serving._decodes:
                self._json_reply(404, {"error": f"unknown model "
                                                f"{str(model)!r}"})
                return
            self._json_reply(404, {"error": "no decode engine attached "
                                            "to this server"})
            return
        # the request-root span: decode.submit below captures the child
        # context, so queue-wait/prefill/TTFT spans recorded later by
        # the scheduler thread land under this request's trace
        with _tracing.trace_span("http.generate", cat="serve",
                                 ctx=self._tctx):
            self._generate_traced(payload, decode)

    def _generate_traced(self, payload: Dict, decode):
        ids = payload.get("ids")
        if not isinstance(ids, (list, tuple)) or not ids:
            self._json_reply(400, {"error": 'missing/empty "ids" list'})
            return
        max_new = payload.get("max_new_tokens", 16)
        stream = bool(payload.get("stream", True))
        timeout = payload.get("timeout_s")
        try:
            handle = decode.submit(ids, max_new_tokens=int(max_new),
                                   tenant=payload.get("tenant"))
        except ShedError as e:
            self._shed_reply(e)
            return
        except (QueueFullError, ServerClosed) as e:
            self._json_reply(503, {"error": str(e)},
                             headers=self.serving._retry_after())
            return
        except (ValueError, TypeError) as e:
            self._json_reply(400, {"error": str(e)})
            return
        if not stream:
            try:
                toks = handle.result(timeout_s=timeout)
            except Exception as e:
                # the reply is an error, so nobody will ever read the
                # rest of this generation — free its slot/blocks now
                decode.cancel(handle)
                self._json_reply(500, {"error": f"{type(e).__name__}: "
                                                f"{e}"})
                return
            info = handle.info
            self._json_reply(200, {
                "tokens": toks, "finish_reason": info["finish_reason"],
                "ttft_ms": round(info["ttft_s"] * 1000, 3)
                if info["ttft_s"] is not None else None})
            return
        # streaming: chunked ndjson, one line per token as it lands
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-cache")
        for name, value in _tracing.response_headers(self._tctx).items():
            self.send_header(name, value)
        self.end_headers()
        n = 0
        try:
            for tok in handle.tokens(timeout_s=timeout):
                self._chunk(json.dumps({"token": int(tok)}) + "\n")
                n += 1
            info = handle.info
            self._chunk(json.dumps(_json_safe({
                "done": True, "tokens": n,
                "finish_reason": info["finish_reason"],
                "ttft_ms": round(info["ttft_s"] * 1000, 3)
                if info["ttft_s"] is not None else None})) + "\n")
        except _base.CLIENT_GONE:
            # the reader hung up mid-stream: abandon the generation so
            # its decode slot and KV blocks free NOW instead of after
            # max_new_tokens of unread work
            decode.cancel(handle)
            return
        except Exception as e:
            decode.cancel(handle)
            # headers are gone; the error must travel in-band
            try:
                self._chunk(json.dumps({
                    "done": True, "error": f"{type(e).__name__}: {e}",
                    "tokens": n}) + "\n")
            except _base.CLIENT_GONE:
                return
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()
        # one generation per connection: chunked keep-alive reuse buys
        # nothing here and a half-read stream must not poison the next
        # request on the socket
        self.close_connection = True

    def do_POST(self):  # noqa: N802 - stdlib naming
        try:
            # extract-or-start the request's trace context (W3C
            # traceparent in, X-Request-Id/traceparent out); the active
            # span threads through batcher/decode/engine spans
            self._tctx = _tracing.begin_request(self.headers)
            path = urlparse(self.path).path
            if path == "/v1/profile":
                # on-demand capture on the SERVING port: the fleet
                # router can profile a replica under live traffic
                # through the same address it routes inference to.
                # This handler thread blocks for the window; the
                # ThreadingHTTPServer keeps /v1/predict flowing.
                from ..observability.httpd import handle_profile_request

                code, body = handle_profile_request(self)
                self._reply(code, "application/json", body)
                return
            if path not in ("/v1/predict", "/v1/generate"):
                self._reply(404, "text/plain",
                            "not found; POST routes: /v1/predict, "
                            "/v1/generate, /v1/profile\n")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(length))
            except (ValueError, TypeError):
                self._json_reply(400, {"error": "body must be JSON"})
                return
            if path == "/v1/generate":
                if not isinstance(payload, dict):
                    self._json_reply(400, {"error": "body must be a "
                                                    "JSON object"})
                    return
                self._do_generate(payload)
                return
            with _tracing.trace_span("http.predict", cat="serve",
                                     ctx=self._tctx):
                self._do_predict(payload)
        except _base.CLIENT_GONE:
            pass

    def _do_predict(self, payload):
        try:
            # chaos hook for latency-SLO testing (serve_bench --fleet
            # gate 5): when PADDLE_TPU_SLOW_SHIM_FILE names an existing
            # file, every predict sleeps the float it contains — a slow
            # replica that can be injected and lifted mid-life by
            # creating/removing the file, no restart needed
            shim = os.environ.get("PADDLE_TPU_SLOW_SHIM_FILE")
            if shim:
                try:
                    with open(shim) as f:
                        delay = float(f.read().strip() or 0.0)
                except (OSError, ValueError):
                    delay = 0.0
                if delay > 0:
                    time.sleep(delay)
            feeds = payload.get("feeds") if isinstance(payload, dict) \
                else None
            if not isinstance(feeds, dict) or not feeds:
                self._json_reply(400, {"error":
                                       'missing/empty "feeds" object'})
                return
            try:
                arrays = {str(k): np.asarray(v) for k, v in feeds.items()}
            except (ValueError, TypeError):
                self._json_reply(400, {"error": "feeds must be rectangular "
                                               "numeric arrays"})
                return
            timeout = payload.get("timeout_s")
            model = payload.get("model")
            if model is not None \
                    and str(model) not in self.serving._model_ids():
                self._json_reply(404, {"error": f"unknown model "
                                                f"{str(model)!r}"})
                return
            try:
                outs = self.serving.submit(
                    arrays, timeout_s=timeout, model=model,
                    tenant=payload.get("tenant"))
            except ShedError as e:
                self._shed_reply(e)
                return
            except (QueueFullError, ServerClosed) as e:
                # draining replicas add Retry-After so the fleet router
                # (and any well-behaved client) re-sends elsewhere NOW
                # and re-polls this replica after the drain window
                self._json_reply(503, {"error": str(e)},
                                 headers=self.serving._retry_after())
                return
            except RequestTimeout as e:
                self._json_reply(504, {"error": str(e)})
                return
            except EngineError as e:
                # model/engine failure is the server's fault — a 400
                # would make clients retry a request that cannot succeed
                self._json_reply(500, {"error": str(e)})
                return
            except ValueError as e:
                # pre-enqueue validation (empty/ragged/oversize feeds)
                self._json_reply(400, {"error": str(e)})
                return
            except Exception as e:
                self._json_reply(500, {"error": f"{type(e).__name__}: {e}"})
                return
            batch = next(iter(arrays.values())).shape[0] \
                if next(iter(arrays.values())).ndim else 1
            self._json_reply(200, {
                "outputs": {k: np.asarray(v).tolist()
                            for k, v in outs.items()},
                "batch": int(batch)})
        except _base.CLIENT_GONE:
            pass


class Server:
    """The dynamic-batching TPU inference server: build with a
    ServingConfig (or hand in an existing Predictor), `start()` to warm
    the buckets and begin listening, `stop()` to drain and shut down.
    Both are idempotent; stop is also registered atexit so tests and
    crashing deployments never leak the listener or batcher thread."""

    def __init__(self, config: ServingConfig,
                 predictor=None, decode=None, models=None,
                 registry=None):
        """`decode`, when given, is a `decode.DecodeEngine` (or a dict
        `{model_id: DecodeEngine}` for multi-model generation); the
        server then also answers POST /v1/generate and folds the decode
        block into /v1/status. A decode-only server (no model_dir, no
        predictor) skips the predict engine entirely — /v1/predict
        answers 503. `models`, when given, is `{model_id:
        ServingConfig}` for ADDITIONAL predict models served from this
        process alongside `config`'s (the default slot, named by
        `config.model_id`); all slots share the process, its HBM
        budget, and one listener. `registry`, when given, is a
        `registry.ModelRegistry` the server watches for hot-swaps
        (see attach_registry)."""
        self.config = config
        self._default_id = getattr(config, "model_id", "default")
        decodes = decode if isinstance(decode, dict) else \
            ({self._default_id: decode} if decode is not None else {})
        self._decodes: Dict[str, DecodeEngine] = \
            {str(k): v for k, v in decodes.items()}
        # annotated so tools/lockgraph.py can type the attribute (the
        # value is a constructor parameter it cannot infer from)
        self._decode: Optional[DecodeEngine] = \
            self._decodes.get(self._default_id)
        self._engine = None \
            if (self._decodes and config.model_dir is None
                and predictor is None) \
            else Engine(config, predictor=predictor)
        self._batcher: Optional[Batcher] = None
        # additional predict-model slots: model_id -> {config, engine,
        # batcher}; engines build NOW (fail a bad config at
        # construction like the default slot), batchers at start()
        self._extra: Dict[str, Dict] = {}
        for mid, mcfg in (models or {}).items():
            mid = str(mid)
            if mid == self._default_id:
                raise ValueError(
                    f"models= duplicates the default slot {mid!r}")
            self._extra[mid] = {"config": mcfg,
                                "engine": Engine(mcfg),
                                "batcher": None}
        self._qos = QoSPolicy.from_spec(getattr(config, "qos", None))
        handler = type("_BoundServingHandler", (_ServingHandler,),
                       {"serving": self})
        self._http = _base.HTTPServerHandle(
            handler, thread_name="paddle-tpu-serving-http")
        # deferred import: the analysis package must not load during
        # package bootstrap; constructors only run after it
        from ..analysis import lockcheck as _lockcheck

        self._lock = _lockcheck.Lock("serving.httpd.Server._lock")
        self._started_t: Optional[float] = None
        self._draining = False
        # registry hot-swap state: adopted version per model slot, the
        # watcher thread, and its stop flag
        self._versions: Dict[str, int] = {}
        self._registry = None
        self._watch_ids = None
        self._watch_poll_s = 1.0
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        if registry is not None:
            self.attach_registry(registry)

    # -- lifecycle -----------------------------------------------------

    def start(self, port: Optional[int] = None) -> int:
        """Warm the buckets, start the batcher and the HTTP listener.
        Returns the bound port; a second call returns it unchanged."""
        with self._lock:
            if self._started_t is not None:
                return self._http.port()
            self._draining = False
            # thread-spawn ordering is the leak discipline: everything
            # that can FAIL (warmups, the bind) happens before anything
            # that starts a thread, except the batcher — whose
            # constructor spawns — which is therefore created last
            # before the bind and stopped if the bind raises. The
            # decode scheduler starts only after the bind succeeds, so
            # a failed start never leaves it running (and never kills
            # the caller's engine, whose stop() is terminal).
            if self.config.warmup:
                for dec in self._decodes.values():
                    if not dec.warmed:
                        dec.warmup()
            batcher = None
            if self._engine is not None:
                if self.config.warmup:
                    self._engine.warmup()
                batcher = self._make_batcher(self._engine, self.config)
            extra_batchers = []
            try:
                for mid, slot in self._extra.items():
                    if slot["config"].warmup:
                        slot["engine"].warmup()
                    extra_batchers.append(
                        (mid, self._make_batcher(slot["engine"],
                                                 slot["config"])))
                bound = self._http.start(
                    self.config.port if port is None else port,
                    host=self.config.host)
            except BaseException:
                if batcher is not None:
                    batcher.stop()  # failed bind must not leak the thread
                for _, b in extra_batchers:
                    b.stop()
                raise
            for dec in self._decodes.values():
                dec.start()
            self._batcher = batcher
            for mid, b in extra_batchers:
                self._extra[mid]["batcher"] = b
            self._started_t = time.monotonic()
            import atexit

            atexit.register(self.stop)
            # telemetry pipeline: the env-gated TS recorder plus the
            # SLO evaluator when the config declares objectives (both
            # no-ops without PADDLE_TPU_TS_DIR)
            _timeseries.maybe_start_recorder()
            _slo.maybe_start_evaluator(
                spec_path=getattr(self.config, "slo_spec", None))
            _events.emit("serve_start", port=bound,
                         buckets=list(self._engine.policy.buckets)
                         if self._engine is not None else [],
                         decode=bool(self._decodes),
                         models=self._model_ids(),
                         qos=self._qos is not None,
                         max_queue=self.config.max_queue,
                         max_wait_ms=self.config.max_wait_ms)
            self._maybe_start_watcher()
            return bound

    def _make_batcher(self, engine: Engine, cfg: ServingConfig) -> Batcher:
        return Batcher(
            engine.run_batch, engine.policy,
            max_queue=cfg.max_queue,
            max_wait_ms=cfg.max_wait_ms,
            timeout_s=cfg.timeout_s,
            output_batched=engine.output_batched,
            qos=self._qos)

    def drain(self, timeout: float = 30.0):
        """Graceful drain, the fleet's scale-in half-step (SERVING.md
        §Fleet): the listener STAYS UP — so the router's health probe
        sees state "draining" (503) and in-flight streams finish — but
        new work is rejected with 503 + Retry-After, and this call
        blocks until pending predict batches and decode generations
        completed (or `timeout` passed). Call stop() afterwards to tear
        the listener down. Idempotent."""
        with self._lock:
            if self._draining or self._started_t is None:
                already = True
            else:
                self._draining = True
                already = False
            batchers = self._all_batchers()
            decodes = list(self._decodes.values())
        if not already:
            _events.emit("serve_drain",
                         queue_depth=sum(b.depth() for b in batchers))
        # ONE deadline across every engine: `timeout` bounds the whole
        # drain, not each stage (a supervisor sizing its SIGKILL grace
        # against drain_timeout_s must not be off by 2x)
        deadline = time.monotonic() + float(timeout)
        for batcher in batchers:
            # stop() is the drain: no new admissions, pending batches
            # finish, the thread joins
            batcher.stop(timeout=max(0.0, deadline - time.monotonic()))
        for decode in decodes:
            decode.drain(timeout_s=max(0.0,
                                       deadline - time.monotonic()))

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def _retry_after(self) -> Optional[Dict[str, str]]:
        """Retry-After header for 503 replies while draining (predicts
        rejected mid-drain should be re-sent to another replica now and
        back here only after the drain completes)."""
        return {"Retry-After": "1"} if self.draining() else None

    def state(self) -> str:
        """One-word serving state for the health probe: "warming" until
        every bucket/phase is warm, "serving" while traffic flows,
        "draining" after drain() began, "stopped" before start / after
        stop / when the decode engine was stopped underneath us."""
        with self._lock:
            if self._started_t is None:
                return "stopped"
            if self._draining:
                return "draining"
            batchers = self._all_batchers()
            decodes = list(self._decodes.values())
            engines = self._all_engines()
        if any(d._closed for d in decodes):
            return "stopped"
        if any(b.draining() for b in batchers):
            return "draining"
        if self.config.warmup and (
                any(not e.warmed for e in engines)
                or any(not d.warmed for d in decodes)):
            return "warming"
        return "serving"

    def load(self) -> Dict:
        """The cheap load probe behind GET /v1/load: queue depth +
        in-flight work as one scalar, touching only counters (no bucket
        table, no KV stats — the router polls this per replica per
        interval)."""
        depth = sum(b.depth() for b in self._all_batchers())
        inflight = sum(b.inflight() for b in self._all_batchers())
        for decode in self._decodes.values():
            d_wait, d_active = decode.load()
            depth += d_wait
            inflight += d_active
        return {"load": float(depth + inflight), "inflight": inflight,
                "queue_depth": depth, "state": self.state(),
                "models": self._model_ids()}

    def stop(self):
        """Stop accepting (listener down first), drain the batcher so
        in-flight requests finish, then emit `serve_stop`. Idempotent;
        unregisters its atexit hook so stopped servers are collectable."""
        # the registry watcher joins OUTSIDE the lock: its poll loop
        # takes the lock for hot-swaps, so joining under it deadlocks
        self._watch_stop.set()
        watcher = self._watch_thread
        if watcher is not None and watcher.is_alive():
            watcher.join(timeout=10.0)
        self._watch_thread = None
        # the whole teardown runs under the lock so a concurrent start()
        # cannot interleave (and e.g. have its fresh batcher killed or
        # its "bound" port be the one being closed)
        with self._lock:
            started = self._started_t is not None
            self._started_t = None
            import atexit

            atexit.unregister(self.stop)
            self._http.stop()
            self._stop_slots_locked()
            if not started:
                return  # safety path: a start() that raised mid-way
            counts = self._counts()
        _events.emit("serve_stop", ok=counts["ok"],
                     rejected=counts["rejected"],
                     timeout=counts["timeout"])

    def _counts(self) -> Dict[str, int]:
        """THIS server's outcomes, summed over model slots (the
        Prometheus counter is process-global; batchers keep
        per-instance counts)."""
        out = {o: 0 for o in ("ok", "rejected", "timeout", "error")}
        for b in self._all_batchers():
            for k, v in b.outcome_counts().items():
                out[k] = out.get(k, 0) + v
        return out

    def _stop_slots_locked(self):
        """Stop every slot's batcher and decode engine (caller holds
        Server._lock). The typed default-slot references double as the
        lockgraph witness for the ledgered order: Server._lock wraps
        the inner component locks during teardown."""
        if self._batcher is not None:
            self._batcher.stop()
        if self._decode is not None:
            self._decode.stop()
        for batcher in self._all_batchers():
            if batcher is not self._batcher:
                batcher.stop()
        for decode in self._decodes.values():
            if decode is not self._decode:
                decode.stop()

    def port(self) -> Optional[int]:
        return self._http.port()

    # -- model slots (multi-model surface) -----------------------------

    def _all_batchers(self):
        out = [] if self._batcher is None else [self._batcher]
        out.extend(s["batcher"] for s in self._extra.values()
                   if s["batcher"] is not None)
        return out

    def _all_engines(self):
        out = [] if self._engine is None else [self._engine]
        out.extend(s["engine"] for s in self._extra.values())
        return out

    def _model_ids(self):
        ids = set(self._extra) | set(self._decodes)
        if self._engine is not None:
            ids.add(self._default_id)
        return sorted(ids)

    def _slot(self, model: Optional[str]):
        """(engine, batcher) for a model id; None model = the default
        slot. Raises KeyError for an unknown id."""
        mid = self._default_id if model is None else str(model)
        if mid == self._default_id and mid not in self._extra:
            # the default slot, possibly empty (decode-only server)
            return self._engine, self._batcher
        slot = self._extra[mid]
        return slot["engine"], slot["batcher"]

    def _decode_for(self, model: Optional[str]) -> Optional[DecodeEngine]:
        if model is None:
            return self._decode
        return self._decodes.get(str(model))

    def models(self) -> list:
        """The /v1/models rows: one per model slot (predict and/or
        decode), with the served program's digest, the adopted registry
        version, and warm state. Slot pointers are snapshotted under
        the server lock but read AFTER it: outcome_counts() takes the
        batcher condition, and holding Server._lock across another
        component's lock would widen the lock order for a status
        read."""
        slots = []
        with self._lock:
            for mid in self._model_ids():
                try:
                    eng, batcher = self._slot(mid)
                except KeyError:
                    eng, batcher = None, None
                slots.append((mid, self._versions.get(mid), eng,
                              batcher, self._decodes.get(mid)))
        rows = []
        for mid, version, eng, batcher, dec in slots:
            row = {"id": mid, "version": version,
                   "default": mid == self._default_id}
            if eng is not None:
                row.update(
                    kind="predict",
                    digest=eng._model_digest(),
                    warmed=eng.warmed,
                    warmstart_adopted=eng.warmstart_adopted,
                    buckets=[int(b) for b in eng.policy.buckets])
                if batcher is not None:
                    row["requests"] = batcher.outcome_counts()
            if dec is not None:
                row["decode"] = {
                    "warmed": dec.warmed,
                    "warmstart_adopted": dec.warmstart_adopted,
                    "digest": dec._model_digest()}
                row.setdefault("kind", "decode")
            rows.append(row)
        return rows

    # -- zero-downtime hot-swap ----------------------------------------

    def hot_swap(self, model_id: Optional[str] = None, *,
                 model_dir: Optional[str] = None,
                 warmstart: Optional[str] = None,
                 version: Optional[int] = None) -> Dict:
        """Replace one predict slot's engine with one built from a new
        artifact, without dropping traffic: the replacement engine
        builds and WARMS before the slot pointer moves (with an adopted
        warmstart this is deserialization, zero fresh compiles), new
        requests flow to it from the swap instant, and the old slot's
        batcher then drains so every in-flight request completes —
        zero failed requests. Returns the swap record (also emitted as
        a `model_swap` event)."""
        mid = self._default_id if model_id is None else str(model_id)
        if mid == self._default_id and self._engine is not None:
            old_cfg = self.config
        elif mid in self._extra:
            old_cfg = self._extra[mid]["config"]
        else:
            raise KeyError(f"unknown model slot {mid!r}; serving "
                           f"{self._model_ids()}")
        import copy

        new_cfg = copy.copy(old_cfg)
        if model_dir is not None:
            new_cfg.model_dir = model_dir
        new_cfg.warmstart = warmstart
        t0 = time.monotonic()
        # the expensive part happens OFF the serving path: the old
        # engine keeps answering while this one builds and warms
        new_engine = Engine(new_cfg)
        if new_cfg.warmup:
            new_engine.warmup()
        new_batcher = None
        with self._lock:
            started = self._started_t is not None
            if started:
                new_batcher = self._make_batcher(new_engine, new_cfg)
            if mid == self._default_id and self._engine is not None:
                old_batcher = self._batcher
                self.config = new_cfg
                self._engine = new_engine
                self._batcher = new_batcher
            else:
                slot = self._extra[mid]
                old_batcher = slot["batcher"]
                self._extra[mid] = {"config": new_cfg,
                                    "engine": new_engine,
                                    "batcher": new_batcher}
            if version is not None:
                self._versions[mid] = int(version)
        # drain the displaced batcher AFTER the pointer moved: its
        # in-flight and queued requests complete against the OLD engine
        # (their feeds were validated against its signature set) while
        # new arrivals already land on the new one
        if old_batcher is not None:
            old_batcher.stop()
        record = {
            "model": mid, "version": version,
            "digest": new_engine._model_digest(),
            "warmstart_adopted": new_engine.warmstart_adopted,
            "swap_s": round(time.monotonic() - t0, 3)}
        MODEL_SWAPS.inc(model=mid)
        if version is not None:
            from .registry import MODEL_VERSION

            MODEL_VERSION.set(int(version), model=mid)
        _events.emit("model_swap", **record)
        return record

    def attach_registry(self, registry, model_ids=None,
                        poll_s: float = 1.0):
        """Watch a `registry.ModelRegistry` and hot-swap slots as new
        versions publish. `model_ids` bounds the watch (default: this
        server's predict slots). The watcher starts with the server
        (or immediately if already started) and stops with it. A slot
        already serving the published program digest just records the
        version — no redundant swap."""
        self._registry = registry
        self._watch_ids = None if model_ids is None \
            else [str(m) for m in model_ids]
        self._watch_poll_s = float(poll_s)
        self._maybe_start_watcher()

    def _maybe_start_watcher(self):
        if self._registry is None or self._watch_thread is not None \
                or self._started_t is None:
            return
        self._watch_stop.clear()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, name="paddle-tpu-registry-watch",
            daemon=True)
        self._watch_thread.start()

    def _watch_ids_now(self):
        if self._watch_ids is not None:
            return self._watch_ids
        ids = [] if self._engine is None else [self._default_id]
        ids.extend(self._extra)
        return ids

    def _watch_loop(self):
        while not self._watch_stop.wait(self._watch_poll_s):
            for mid in self._watch_ids_now():
                try:
                    self._adopt_if_new(mid)
                except Exception as e:
                    # a bad publish must not kill the watcher (the
                    # current engine keeps serving); surface it
                    _events.emit("model_swap_failed", model=mid,
                                 error=f"{type(e).__name__}: "
                                       f"{str(e)[:200]}")

    def _adopt_if_new(self, mid: str):
        reg = self._registry
        ver = reg.version(mid)
        if ver is None or ver <= self._versions.get(mid, 0):
            return
        entry = reg.resolve(mid)   # digest-verified blob
        try:
            eng, _ = self._slot(mid)
        except KeyError:
            eng = None
        if eng is not None and entry.get("model_digest") is not None \
                and entry["model_digest"] == eng._model_digest() \
                and eng.warmstart_adopted:
            # same program, already warm from an adopted artifact:
            # record the version, skip the redundant rebuild
            with self._lock:
                self._versions[mid] = ver
            return
        self.hot_swap(mid, model_dir=entry.get("model_dir"),
                      warmstart=entry["path"], version=ver)

    # -- request path --------------------------------------------------

    def submit(self, feeds: Dict[str, np.ndarray],
               timeout_s: Optional[float] = None,
               model: Optional[str] = None,
               tenant: Optional[str] = None) -> Dict[str, np.ndarray]:
        """In-process entry to the batched path (the HTTP handler and
        embedded deployments share it). `model` picks the slot (None =
        default); `tenant` flows to QoS admission."""
        try:
            engine, batcher = self._slot(model)
        except KeyError:
            raise ValueError(f"unknown model {str(model)!r}; serving "
                             f"{self._model_ids()}")
        if batcher is None:
            raise ServerClosed("server not started"
                               if engine is not None else
                               "no predict engine on this server "
                               "(decode-only deployment)")
        return batcher.submit(feeds, timeout_s=timeout_s, tenant=tenant)

    def status(self) -> Dict:
        up = None if self._started_t is None \
            else round(time.monotonic() - self._started_t, 3)
        batcher = self._batcher
        probe = self.load()
        st = {
            "uptime_s": up,
            "port": self._http.port(),
            "state": probe["state"],
            "load": probe["load"],
            "inflight": probe["inflight"],
            "queue_depth": batcher.depth() if batcher else 0,
            "max_queue": self.config.max_queue,
            "max_wait_ms": self.config.max_wait_ms,
            "timeout_s": self.config.timeout_s,
            "requests": self._counts(),
            "memory": _memwatch.status_block(),
            "models": probe["models"],
        }
        if self._qos is not None:
            st["qos"] = self._qos.spec_dict()
        if self._engine is not None:
            st.update(self._engine.status())
        if self._decode is not None:
            st["decode"] = self._decode.status()
        for mid, dec in self._decodes.items():
            if dec is not self._decode:
                st.setdefault("decodes", {})[mid] = dec.status()
        return st
