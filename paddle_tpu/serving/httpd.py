"""JSON-over-HTTP serving frontend + the `Server` that ties the
subsystem together (engine + batcher + HTTP, one object to start/stop).

Routes (schema documented in SERVING.md §HTTP API):

  POST /v1/predict   {"feeds": {name: nested-list}, "timeout_s": opt}
                     → 200 {"outputs": {name: nested-list}, "batch": n}
                     → 400 malformed request / bad shapes
                     → 503 queue full or draining (admission control —
                       the client should back off or retry elsewhere)
                     → 504 request missed its deadline
                     → 500 engine error
  POST /v1/generate  {"ids": [tok,...], "max_new_tokens": N,
                      "stream": true|false, "timeout_s": opt}
                     token generation on the continuous-batching decode
                     engine (SERVING.md §Continuous batching). With
                     stream=true (default): a chunked
                     application/x-ndjson body, one {"token": t} line
                     per generated token as the scheduler emits it,
                     closed by {"done": true, "finish_reason": ...,
                     "tokens": n, "ttft_ms": x}. With stream=false: one
                     JSON reply carrying the full token list. 503 when
                     the decode queue is full, 404 when the server has
                     no decode engine attached.
  GET  /v1/status    queue depth, buckets, request/batch counters,
                     decode queue/slot-occupancy/TTFT block, uptime —
                     the operator's one-look view
  GET  /v1/load      the router's cheap load probe (SERVING.md §Fleet):
                     {"load": scalar, "inflight": n, "queue_depth": q,
                     "state": ...} touching only the batcher/decode
                     counters — power-of-two-choices picks must not pay
                     a full status() walk per poll
  GET  /v1/healthz   readiness, with a real serving-state signal for
                     the fleet router's health ejection: 200 only while
                     state == "serving"; 503 with {"state": "warming"}
                     before every bucket/phase is warmed, {"state":
                     "draining"} after drain() began (scale-in), and
                     {"state": "stopped"} once the decode engine or
                     batcher is gone. (The process-wide anomaly-aware
                     probe stays on the observability server,
                     PADDLE_TPU_METRICS_PORT.)

Built on `observability.httpbase` — same silent logging, locked
idempotent start/stop, daemon threading, and atexit discipline as the
/metrics endpoint. Feed dtypes need not be declared client-side: the
Predictor casts to the model's declared feed dtypes, so plain JSON
numbers round-trip.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional
from urllib.parse import urlparse

import numpy as np

from ..observability import events as _events
from ..observability import httpbase as _base
from ..observability import memwatch as _memwatch
from ..observability import slo as _slo
from ..observability import timeseries as _timeseries
from ..observability import tracing as _tracing
from ..observability.metrics import _json_safe
from .decode import DecodeEngine
from .batcher import (Batcher, EngineError, QueueFullError,
                      RequestTimeout, ServerClosed)
from .engine import Engine, ServingConfig

__all__ = ["Server"]


class _ServingHandler(_base.QuietHandler):
    server_version = "paddle-tpu-serving"
    # chunked transfer (the /v1/generate stream) needs HTTP/1.1; all
    # non-chunked replies already send explicit Content-Length, which
    # 1.1 keep-alive requires
    protocol_version = "HTTP/1.1"
    serving: "Server" = None  # bound per-Server via a subclass

    _tctx = None  # per-request TraceContext, set at the top of do_*

    def _json_reply(self, code: int, payload: Dict, headers=None):
        # strict-JSON discipline (same as metrics.dump): a model output
        # containing NaN/Inf must not make json.dumps emit bare NaN
        # tokens that RFC-8259 clients reject — non-finite floats become
        # strings ("nan"/"inf"/"-inf"), documented in SERVING.md
        hdrs = dict(headers or {})
        # every /v1/* reply carries the request id + traceparent so the
        # caller (and the fleet router's logs) can join against the
        # trace sink and the JSONL event log (SERVING.md §HTTP API)
        hdrs.update(_tracing.response_headers(self._tctx))
        self._reply(code, "application/json",
                    json.dumps(_json_safe(payload)) + "\n",
                    extra_headers=hdrs)

    def do_GET(self):  # noqa: N802 - stdlib naming
        try:
            self._tctx = _tracing.begin_request(self.headers)
            path = urlparse(self.path).path
            if path == "/v1/status":
                self._json_reply(200, self.serving.status())
            elif path == "/v1/load":
                self._json_reply(200, self.serving.load())
            elif path == "/v1/healthz":
                state = self.serving.state()
                self._json_reply(
                    200 if state == "serving" else 503,
                    {"status": "ok" if state == "serving"
                     else "unavailable", "state": state})
            else:
                self._reply(404, "text/plain",
                            "not found; routes: POST /v1/predict, "
                            "GET /v1/status /v1/load /v1/healthz\n")
        except _base.CLIENT_GONE:
            pass

    # -- token streaming (/v1/generate) --------------------------------

    def _chunk(self, line: str):
        data = line.encode("utf-8")
        self.wfile.write(f"{len(data):x}\r\n".encode())
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()

    def _do_generate(self, payload: Dict):
        from .batcher import QueueFullError, ServerClosed

        decode = self.serving._decode
        if decode is None:
            self._json_reply(404, {"error": "no decode engine attached "
                                            "to this server"})
            return
        # the request-root span: decode.submit below captures the child
        # context, so queue-wait/prefill/TTFT spans recorded later by
        # the scheduler thread land under this request's trace
        with _tracing.trace_span("http.generate", cat="serve",
                                 ctx=self._tctx):
            self._generate_traced(payload, decode)

    def _generate_traced(self, payload: Dict, decode):
        ids = payload.get("ids")
        if not isinstance(ids, (list, tuple)) or not ids:
            self._json_reply(400, {"error": 'missing/empty "ids" list'})
            return
        max_new = payload.get("max_new_tokens", 16)
        stream = bool(payload.get("stream", True))
        timeout = payload.get("timeout_s")
        try:
            handle = decode.submit(ids, max_new_tokens=int(max_new))
        except (QueueFullError, ServerClosed) as e:
            self._json_reply(503, {"error": str(e)},
                             headers=self.serving._retry_after())
            return
        except (ValueError, TypeError) as e:
            self._json_reply(400, {"error": str(e)})
            return
        if not stream:
            try:
                toks = handle.result(timeout_s=timeout)
            except Exception as e:
                # the reply is an error, so nobody will ever read the
                # rest of this generation — free its slot/blocks now
                decode.cancel(handle)
                self._json_reply(500, {"error": f"{type(e).__name__}: "
                                                f"{e}"})
                return
            info = handle.info
            self._json_reply(200, {
                "tokens": toks, "finish_reason": info["finish_reason"],
                "ttft_ms": round(info["ttft_s"] * 1000, 3)
                if info["ttft_s"] is not None else None})
            return
        # streaming: chunked ndjson, one line per token as it lands
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-cache")
        for name, value in _tracing.response_headers(self._tctx).items():
            self.send_header(name, value)
        self.end_headers()
        n = 0
        try:
            for tok in handle.tokens(timeout_s=timeout):
                self._chunk(json.dumps({"token": int(tok)}) + "\n")
                n += 1
            info = handle.info
            self._chunk(json.dumps(_json_safe({
                "done": True, "tokens": n,
                "finish_reason": info["finish_reason"],
                "ttft_ms": round(info["ttft_s"] * 1000, 3)
                if info["ttft_s"] is not None else None})) + "\n")
        except _base.CLIENT_GONE:
            # the reader hung up mid-stream: abandon the generation so
            # its decode slot and KV blocks free NOW instead of after
            # max_new_tokens of unread work
            decode.cancel(handle)
            return
        except Exception as e:
            decode.cancel(handle)
            # headers are gone; the error must travel in-band
            try:
                self._chunk(json.dumps({
                    "done": True, "error": f"{type(e).__name__}: {e}",
                    "tokens": n}) + "\n")
            except _base.CLIENT_GONE:
                return
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()
        # one generation per connection: chunked keep-alive reuse buys
        # nothing here and a half-read stream must not poison the next
        # request on the socket
        self.close_connection = True

    def do_POST(self):  # noqa: N802 - stdlib naming
        try:
            # extract-or-start the request's trace context (W3C
            # traceparent in, X-Request-Id/traceparent out); the active
            # span threads through batcher/decode/engine spans
            self._tctx = _tracing.begin_request(self.headers)
            path = urlparse(self.path).path
            if path == "/v1/profile":
                # on-demand capture on the SERVING port: the fleet
                # router can profile a replica under live traffic
                # through the same address it routes inference to.
                # This handler thread blocks for the window; the
                # ThreadingHTTPServer keeps /v1/predict flowing.
                from ..observability.httpd import handle_profile_request

                code, body = handle_profile_request(self)
                self._reply(code, "application/json", body)
                return
            if path not in ("/v1/predict", "/v1/generate"):
                self._reply(404, "text/plain",
                            "not found; POST routes: /v1/predict, "
                            "/v1/generate, /v1/profile\n")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(length))
            except (ValueError, TypeError):
                self._json_reply(400, {"error": "body must be JSON"})
                return
            if path == "/v1/generate":
                if not isinstance(payload, dict):
                    self._json_reply(400, {"error": "body must be a "
                                                    "JSON object"})
                    return
                self._do_generate(payload)
                return
            with _tracing.trace_span("http.predict", cat="serve",
                                     ctx=self._tctx):
                self._do_predict(payload)
        except _base.CLIENT_GONE:
            pass

    def _do_predict(self, payload):
        try:
            # chaos hook for latency-SLO testing (serve_bench --fleet
            # gate 5): when PADDLE_TPU_SLOW_SHIM_FILE names an existing
            # file, every predict sleeps the float it contains — a slow
            # replica that can be injected and lifted mid-life by
            # creating/removing the file, no restart needed
            shim = os.environ.get("PADDLE_TPU_SLOW_SHIM_FILE")
            if shim:
                try:
                    with open(shim) as f:
                        delay = float(f.read().strip() or 0.0)
                except (OSError, ValueError):
                    delay = 0.0
                if delay > 0:
                    time.sleep(delay)
            feeds = payload.get("feeds") if isinstance(payload, dict) \
                else None
            if not isinstance(feeds, dict) or not feeds:
                self._json_reply(400, {"error":
                                       'missing/empty "feeds" object'})
                return
            try:
                arrays = {str(k): np.asarray(v) for k, v in feeds.items()}
            except (ValueError, TypeError):
                self._json_reply(400, {"error": "feeds must be rectangular "
                                               "numeric arrays"})
                return
            timeout = payload.get("timeout_s")
            try:
                outs = self.serving.submit(arrays, timeout_s=timeout)
            except (QueueFullError, ServerClosed) as e:
                # draining replicas add Retry-After so the fleet router
                # (and any well-behaved client) re-sends elsewhere NOW
                # and re-polls this replica after the drain window
                self._json_reply(503, {"error": str(e)},
                                 headers=self.serving._retry_after())
                return
            except RequestTimeout as e:
                self._json_reply(504, {"error": str(e)})
                return
            except EngineError as e:
                # model/engine failure is the server's fault — a 400
                # would make clients retry a request that cannot succeed
                self._json_reply(500, {"error": str(e)})
                return
            except ValueError as e:
                # pre-enqueue validation (empty/ragged/oversize feeds)
                self._json_reply(400, {"error": str(e)})
                return
            except Exception as e:
                self._json_reply(500, {"error": f"{type(e).__name__}: {e}"})
                return
            batch = next(iter(arrays.values())).shape[0] \
                if next(iter(arrays.values())).ndim else 1
            self._json_reply(200, {
                "outputs": {k: np.asarray(v).tolist()
                            for k, v in outs.items()},
                "batch": int(batch)})
        except _base.CLIENT_GONE:
            pass


class Server:
    """The dynamic-batching TPU inference server: build with a
    ServingConfig (or hand in an existing Predictor), `start()` to warm
    the buckets and begin listening, `stop()` to drain and shut down.
    Both are idempotent; stop is also registered atexit so tests and
    crashing deployments never leak the listener or batcher thread."""

    def __init__(self, config: ServingConfig,
                 predictor=None, decode=None):
        """`decode`, when given, is a `decode.DecodeEngine`; the server
        then also answers POST /v1/generate and folds the decode block
        into /v1/status. A decode-only server (no model_dir, no
        predictor) skips the predict engine entirely — /v1/predict
        answers 503."""
        self.config = config
        # annotated so tools/lockgraph.py can type the attribute (the
        # value is a constructor parameter it cannot infer from)
        self._decode: Optional[DecodeEngine] = decode
        self._engine = None \
            if (decode is not None and config.model_dir is None
                and predictor is None) \
            else Engine(config, predictor=predictor)
        self._batcher: Optional[Batcher] = None
        handler = type("_BoundServingHandler", (_ServingHandler,),
                       {"serving": self})
        self._http = _base.HTTPServerHandle(
            handler, thread_name="paddle-tpu-serving-http")
        # deferred import: the analysis package must not load during
        # package bootstrap; constructors only run after it
        from ..analysis import lockcheck as _lockcheck

        self._lock = _lockcheck.Lock("serving.httpd.Server._lock")
        self._started_t: Optional[float] = None
        self._draining = False

    # -- lifecycle -----------------------------------------------------

    def start(self, port: Optional[int] = None) -> int:
        """Warm the buckets, start the batcher and the HTTP listener.
        Returns the bound port; a second call returns it unchanged."""
        with self._lock:
            if self._started_t is not None:
                return self._http.port()
            self._draining = False
            # thread-spawn ordering is the leak discipline: everything
            # that can FAIL (warmups, the bind) happens before anything
            # that starts a thread, except the batcher — whose
            # constructor spawns — which is therefore created last
            # before the bind and stopped if the bind raises. The
            # decode scheduler starts only after the bind succeeds, so
            # a failed start never leaves it running (and never kills
            # the caller's engine, whose stop() is terminal).
            if self._decode is not None and self.config.warmup \
                    and not self._decode.warmed:
                self._decode.warmup()
            batcher = None
            if self._engine is not None:
                if self.config.warmup:
                    self._engine.warmup()
                batcher = Batcher(
                    self._engine.run_batch, self._engine.policy,
                    max_queue=self.config.max_queue,
                    max_wait_ms=self.config.max_wait_ms,
                    timeout_s=self.config.timeout_s,
                    output_batched=self._engine.output_batched)
            try:
                bound = self._http.start(
                    self.config.port if port is None else port,
                    host=self.config.host)
            except BaseException:
                if batcher is not None:
                    batcher.stop()  # failed bind must not leak the thread
                raise
            if self._decode is not None:
                self._decode.start()
            self._batcher = batcher
            self._started_t = time.monotonic()
            import atexit

            atexit.register(self.stop)
            # telemetry pipeline: the env-gated TS recorder plus the
            # SLO evaluator when the config declares objectives (both
            # no-ops without PADDLE_TPU_TS_DIR)
            _timeseries.maybe_start_recorder()
            _slo.maybe_start_evaluator(
                spec_path=getattr(self.config, "slo_spec", None))
            _events.emit("serve_start", port=bound,
                         buckets=list(self._engine.policy.buckets)
                         if self._engine is not None else [],
                         decode=self._decode is not None,
                         max_queue=self.config.max_queue,
                         max_wait_ms=self.config.max_wait_ms)
            return bound

    def drain(self, timeout: float = 30.0):
        """Graceful drain, the fleet's scale-in half-step (SERVING.md
        §Fleet): the listener STAYS UP — so the router's health probe
        sees state "draining" (503) and in-flight streams finish — but
        new work is rejected with 503 + Retry-After, and this call
        blocks until pending predict batches and decode generations
        completed (or `timeout` passed). Call stop() afterwards to tear
        the listener down. Idempotent."""
        with self._lock:
            if self._draining or self._started_t is None:
                already = True
            else:
                self._draining = True
                already = False
            batcher, decode = self._batcher, self._decode
        if not already:
            _events.emit("serve_drain",
                         queue_depth=batcher.depth() if batcher else 0)
        # ONE deadline across both engines: `timeout` bounds the whole
        # drain, not each stage (a supervisor sizing its SIGKILL grace
        # against drain_timeout_s must not be off by 2x)
        deadline = time.monotonic() + float(timeout)
        if batcher is not None:
            # stop() is the drain: no new admissions, pending batches
            # finish, the thread joins
            batcher.stop(timeout=timeout)
        if decode is not None:
            decode.drain(timeout_s=max(0.0,
                                       deadline - time.monotonic()))

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def _retry_after(self) -> Optional[Dict[str, str]]:
        """Retry-After header for 503 replies while draining (predicts
        rejected mid-drain should be re-sent to another replica now and
        back here only after the drain completes)."""
        return {"Retry-After": "1"} if self.draining() else None

    def state(self) -> str:
        """One-word serving state for the health probe: "warming" until
        every bucket/phase is warm, "serving" while traffic flows,
        "draining" after drain() began, "stopped" before start / after
        stop / when the decode engine was stopped underneath us."""
        with self._lock:
            if self._started_t is None:
                return "stopped"
            if self._draining:
                return "draining"
            batcher, decode = self._batcher, self._decode
        if decode is not None and decode._closed:
            return "stopped"
        if batcher is not None and batcher.draining():
            return "draining"
        if self._engine is not None and not self._engine.warmed \
                and self.config.warmup:
            return "warming"
        if decode is not None and not decode.warmed \
                and self.config.warmup:
            return "warming"
        return "serving"

    def load(self) -> Dict:
        """The cheap load probe behind GET /v1/load: queue depth +
        in-flight work as one scalar, touching only counters (no bucket
        table, no KV stats — the router polls this per replica per
        interval)."""
        batcher, decode = self._batcher, self._decode
        depth = batcher.depth() if batcher is not None else 0
        inflight = batcher.inflight() if batcher is not None else 0
        if decode is not None:
            d_wait, d_active = decode.load()
            depth += d_wait
            inflight += d_active
        return {"load": float(depth + inflight), "inflight": inflight,
                "queue_depth": depth, "state": self.state()}

    def stop(self):
        """Stop accepting (listener down first), drain the batcher so
        in-flight requests finish, then emit `serve_stop`. Idempotent;
        unregisters its atexit hook so stopped servers are collectable."""
        # the whole teardown runs under the lock so a concurrent start()
        # cannot interleave (and e.g. have its fresh batcher killed or
        # its "bound" port be the one being closed)
        with self._lock:
            started = self._started_t is not None
            self._started_t = None
            import atexit

            atexit.unregister(self.stop)
            self._http.stop()
            if self._batcher is not None:
                self._batcher.stop()
            if self._decode is not None:
                self._decode.stop()
            if not started:
                return  # safety path: a start() that raised mid-way
            counts = self._counts()
        _events.emit("serve_stop", ok=counts["ok"],
                     rejected=counts["rejected"],
                     timeout=counts["timeout"])

    def _counts(self) -> Dict[str, int]:
        """THIS server's outcomes (the Prometheus counter is process-
        global; the batcher keeps per-instance counts)."""
        b = self._batcher
        return b.outcome_counts() if b is not None else \
            {o: 0 for o in ("ok", "rejected", "timeout", "error")}

    def port(self) -> Optional[int]:
        return self._http.port()

    # -- request path --------------------------------------------------

    def submit(self, feeds: Dict[str, np.ndarray],
               timeout_s: Optional[float] = None) -> Dict[str, np.ndarray]:
        """In-process entry to the batched path (the HTTP handler and
        embedded deployments share it)."""
        batcher = self._batcher
        if batcher is None:
            raise ServerClosed("server not started"
                               if self._engine is not None else
                               "no predict engine on this server "
                               "(decode-only deployment)")
        return batcher.submit(feeds, timeout_s=timeout_s)

    def status(self) -> Dict:
        up = None if self._started_t is None \
            else round(time.monotonic() - self._started_t, 3)
        batcher = self._batcher
        probe = self.load()
        st = {
            "uptime_s": up,
            "port": self._http.port(),
            "state": probe["state"],
            "load": probe["load"],
            "inflight": probe["inflight"],
            "queue_depth": batcher.depth() if batcher else 0,
            "max_queue": self.config.max_queue,
            "max_wait_ms": self.config.max_wait_ms,
            "timeout_s": self.config.timeout_s,
            "requests": self._counts(),
            "memory": _memwatch.status_block(),
        }
        if self._engine is not None:
            st.update(self._engine.status())
        if self._decode is not None:
            st["decode"] = self._decode.status()
        return st
