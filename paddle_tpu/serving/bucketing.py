"""Shape-bucket policy: map arbitrary request batch sizes onto a small
fixed set of compiled batch sizes.

The XLA engine compiles one executable per input signature, so serving
traffic whose batch size varies per request (bs=1..64) would compile up
to 64 executables — a recompile storm exactly when latency matters
most. The standard fix (Clipper NSDI'17, TF-Serving's batching layer)
is to round every batch up to the nearest of a few configured "bucket"
sizes, pad the feed rows, run the bucket-shaped executable, and slice
the outputs back to the true batch. Powers of two up to `max_batch`
bound both the signature count (log2) and the padding waste (<2x).

Stdlib+numpy only — shared by the synchronous `inference.Predictor`
(opt-in via `AnalysisConfig.enable_bucketing()`) and the serving
batcher/engine, so both paths agree on which signatures exist and the
AOT warmup set stays small and closed.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["BucketPolicy", "common_batch", "DEFAULT_MAX_BATCH"]

DEFAULT_MAX_BATCH = 64


def common_batch(feeds: Dict[str, object]) -> Optional[int]:
    """Leading dim shared by every feed array, or None when feeds
    disagree (or any is rank-0) — in which case bucketing does not
    apply and the caller falls back to exact-shape dispatch."""
    n = None
    for v in feeds.values():
        a = np.asarray(v)
        if a.ndim == 0:
            return None
        if n is None:
            n = int(a.shape[0])
        elif int(a.shape[0]) != n:
            return None
    return n


def _pow2_buckets(max_batch: int):
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


class BucketPolicy:
    """A sorted set of allowed batch sizes plus the pad/slice helpers
    that move a request batch in and out of its bucket."""

    def __init__(self, max_batch: int = DEFAULT_MAX_BATCH,
                 buckets: Optional[Sequence[int]] = None):
        if buckets is not None:
            bs = sorted({int(b) for b in buckets})
            if not bs or bs[0] < 1:
                raise ValueError(f"buckets must be positive ints, got "
                                 f"{tuple(buckets)}")
            self.buckets = tuple(bs)
        else:
            if int(max_batch) < 1:
                raise ValueError(f"max_batch must be >= 1, got {max_batch}")
            self.buckets = _pow2_buckets(int(max_batch))
        self.max_batch = self.buckets[-1]

    def bucket_for(self, n: int) -> Optional[int]:
        """Smallest bucket >= n, or None when n exceeds the largest
        bucket (the caller then compiles the exact shape, or — in the
        batcher — never builds such a batch in the first place)."""
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def pad_batch(self, arr, bucket: int) -> np.ndarray:
        """Pad axis 0 up to `bucket` rows by repeating the last real row.
        Edge-replication rather than zeros: a zero row can poison ops
        like log/division with NaN/Inf that then trip the health layer,
        while a repeated real row is always in-distribution. No copy
        when the array is already bucket-sized."""
        arr = np.asarray(arr)
        n = arr.shape[0]
        if n == bucket:
            return arr
        if n > bucket:
            raise ValueError(f"batch {n} does not fit bucket {bucket}")
        pad = np.repeat(arr[-1:], bucket - n, axis=0)
        return np.concatenate([arr, pad], axis=0)

    def slice_batch(self, arr, n: int) -> np.ndarray:
        """Undo pad_batch: the first n rows (no copy when nothing was
        padded)."""
        arr = np.asarray(arr)
        if arr.ndim == 0 or arr.shape[0] == n:
            return arr
        return arr[:n]
