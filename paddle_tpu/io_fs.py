"""Filesystem shim: local + HDFS-style remote FS behind one interface.

Reference: paddle/fluid/framework/io/fs.{h,cc} — `fs_open_read`,
`fs_exists`, `fs_list`, `fs_mkdir`, ... dispatch on the path prefix
(`hdfs:` or `afs:` → shell out to `hadoop fs`; otherwise local), with
transparent gzip via converter pipes, and framework/io/shell.{h,cc} for
the pipe plumbing. The Dataset/Fleet stack uses it for file-list
sharding and checkpoint upload.

Here the same dispatch lives in Python (the native datafeed already does
its own local reads + pipe_command); HDFS commands are gated on the
`hadoop` binary and raise a clear error when it is absent (zero-egress
environments)."""

from __future__ import annotations

import glob as _glob
import gzip
import io
import os
import shutil
import subprocess
from typing import IO, List


def _is_remote(path: str) -> bool:
    return path.startswith(("hdfs:", "afs:"))


class LocalFS:
    """reference: fs.cc localfs_* (fs_select_internal local branch)."""

    def open_read(self, path: str, mode: str = "r") -> IO:
        # transparent gzip, like localfs_open_read_path's converter pipe
        if path.endswith(".gz"):
            return io.TextIOWrapper(gzip.open(path, "rb")) \
                if "b" not in mode else gzip.open(path, "rb")
        return open(path, mode)

    def open_write(self, path: str, mode: str = "w") -> IO:
        if path.endswith(".gz"):
            return io.TextIOWrapper(gzip.open(path, "wb")) \
                if "b" not in mode else gzip.open(path, "wb")
        return open(path, mode)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def list(self, path: str) -> List[str]:
        if os.path.isdir(path):
            return sorted(os.path.join(path, p) for p in os.listdir(path))
        return sorted(_glob.glob(path))

    def mkdir(self, path: str):
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src: str, dst: str):
        shutil.move(src, dst)

    def touch(self, path: str):
        open(path, "a").close()


class HdfsFS:
    """reference: fs.cc hdfs_* — every call shells `hadoop fs` with the
    configured ugi (fs.cc hdfs_command)."""

    def __init__(self, hadoop_bin: str = "hadoop", ugi: str = ""):
        self.hadoop_bin = hadoop_bin
        self.ugi = ugi
        if shutil.which(hadoop_bin) is None:
            raise RuntimeError(
                f"'{hadoop_bin}' not found on PATH — HDFS paths need a "
                f"hadoop client (this environment has none)")

    def _cmd(self, *args: str) -> List[str]:
        cmd = [self.hadoop_bin, "fs"]
        if self.ugi:
            cmd += ["-D", f"hadoop.job.ugi={self.ugi}"]
        return cmd + list(args)

    def _run(self, *args: str) -> str:
        out = subprocess.run(self._cmd(*args), capture_output=True,
                             text=True)
        if out.returncode != 0:
            raise RuntimeError(f"hadoop fs {' '.join(args)} failed: "
                               f"{out.stderr.strip()}")
        return out.stdout

    def open_read(self, path: str, mode: str = "r") -> IO:
        # read fully and check the exit status — a streaming pipe would
        # report a missing file as empty data
        out = subprocess.run(self._cmd("-cat", path), capture_output=True)
        if out.returncode != 0:
            raise RuntimeError(f"hadoop fs -cat {path} failed: "
                               f"{out.stderr.decode().strip()}")
        return io.StringIO(out.stdout.decode()) if "b" not in mode \
            else io.BytesIO(out.stdout)

    def exists(self, path: str) -> bool:
        return subprocess.run(self._cmd("-test", "-e", path),
                              capture_output=True).returncode == 0

    def list(self, path: str) -> List[str]:
        out = self._run("-ls", path)
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                files.append(parts[-1])
        return sorted(files)

    def mkdir(self, path: str):
        self._run("-mkdir", "-p", path)

    def remove(self, path: str):
        self._run("-rm", "-r", path)

    def mv(self, src: str, dst: str):
        self._run("-mv", src, dst)

    def touch(self, path: str):
        self._run("-touchz", path)


def fs_select(path: str, hadoop_bin: str = "hadoop", ugi: str = ""):
    """Pick the filesystem for a path (reference: fs.cc
    fs_select_internal)."""
    if _is_remote(path):
        return HdfsFS(hadoop_bin=hadoop_bin, ugi=ugi)
    return LocalFS()


def fs_open_read(path: str, mode: str = "r") -> IO:
    return fs_select(path).open_read(path, mode)


def fs_exists(path: str) -> bool:
    return fs_select(path).exists(path)


def fs_list(path: str) -> List[str]:
    return fs_select(path).list(path)


def fs_mkdir(path: str):
    fs_select(path).mkdir(path)
