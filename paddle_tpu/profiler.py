"""Profiler (reference: python/paddle/fluid/profiler.py:228 context manager
→ C++ host profiler + CUPTI DeviceTracer, SURVEY §5 'Tracing/profiling').

TPU-native: jax.profiler captures both host and device timelines into
XPlane/perfetto traces — the role of profiler.proto + tools/timeline.py.
`RecordEvent`-style op annotation maps to jax.profiler.TraceAnnotation;
the host-side span record lands in the unified observability span store
(observability/tracing.py), so `export_chrome_tracing` emits ONE trace
holding RecordEvent host spans, executor/trainer step-telemetry spans,
and the jax device timeline."""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
import time
from collections import defaultdict
from typing import Dict, Optional

import jax

from .observability import tracing as _tracing

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "RecordEvent", "cuda_profiler", "npu_profiler",
           "export_chrome_tracing", "capture_profile", "ProfilerBusyError",
           "PROFILE_DIR_ENV", "MAX_CAPTURE_SECONDS"]

_trace_dir: Optional[str] = None
_host_events = defaultdict(list)
_active = False


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default"):
    """reference: profiler.py:228 — `with profiler.profiler('All'):`"""
    start_profiler(state, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def start_profiler(state="All", profile_path="/tmp/profile", tracer_option=None):
    global _trace_dir, _active
    if _active:
        raise RuntimeError(
            "start_profiler called while a trace is already active; call "
            "stop_profiler() first (nested/overlapping jax traces are not "
            "supported)")
    _trace_dir = profile_path if os.path.isdir(profile_path) or not \
        os.path.splitext(profile_path)[1] else os.path.dirname(profile_path)
    os.makedirs(_trace_dir or ".", exist_ok=True)
    jax.profiler.start_trace(_trace_dir)
    _active = True


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Safe no-op when no trace was started — a teardown path may call it
    unconditionally."""
    global _active
    if _active:
        jax.profiler.stop_trace()
        _active = False
    _print_host_events(sorted_key)


def reset_profiler():
    """Clear ALL host-side profiler state: the aggregate event table, the
    unified span store, and the remembered trace dir (so one test's trace
    path cannot leak into the next export)."""
    global _trace_dir
    _host_events.clear()
    _tracing.clear_spans()
    _trace_dir = None


def trace_dir() -> Optional[str]:
    """Directory the current/last jax trace wrote into (None after
    reset)."""
    return _trace_dir


def _print_host_events(sorted_key=None):
    if not _host_events:
        return
    rows = []
    for name, times in _host_events.items():
        total = sum(times)
        rows.append((name, len(times), total, total / len(times)))
    if sorted_key in (None, "total"):
        rows.sort(key=lambda r: -r[2])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r[1])
    print(f"{'Event':40s} {'Calls':>8s} {'Total(ms)':>12s} {'Avg(ms)':>10s}")
    for name, calls, total, avg in rows:
        print(f"{name:40s} {calls:8d} {total * 1e3:12.3f} {avg * 1e3:10.3f}")


class RecordEvent:
    """reference: platform/profiler.h:81 RecordEvent RAII — host-side named
    span + device TraceAnnotation. The host span is recorded with
    cat="host" in the unified store."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        return self

    def __exit__(self, *a):
        self._ann.__exit__(*a)
        dur = time.perf_counter() - self._t0
        _host_events[self.name].append(dur)
        _tracing.record_span(self.name, self._t0, dur, cat="host")
        return False


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """reference: profiler.py:39 — accelerator-profiler passthrough."""
    with profiler(profile_path=output_file or "/tmp/profile"):
        yield


npu_profiler = cuda_profiler


# ---------------------------------------------------------------------------
# On-demand bounded capture (the POST /v1/profile backend)
# ---------------------------------------------------------------------------

PROFILE_DIR_ENV = "PADDLE_TPU_PROFILE_DIR"
MAX_CAPTURE_SECONDS = 120.0
MIN_CAPTURE_SECONDS = 0.05

_capture_lock = threading.Lock()


class ProfilerBusyError(RuntimeError):
    """A capture (or a manually started trace) is already running.
    The jax profiler supports exactly one active trace per process, so
    concurrent /v1/profile requests must 409, not queue — a queued
    capture would measure a different window than the caller asked
    about."""


def capture_profile(seconds: float,
                    out_dir: Optional[str] = None) -> Dict[str, object]:
    """One bounded profiling window: jax host+device trace for
    `seconds`, then a merged chrome trace plus the live perf/memory
    attribution snapshot, written into a fresh artifact directory.

    Returns {"dir", "trace", "perf", "seconds"} — `trace` is the merged
    chrome://tracing JSON (unified span store + jax device timeline),
    `perf` a JSON sidecar holding the perfwatch MFU/step-time snapshot
    and the memwatch owner table taken at window close.

    Raises ProfilerBusyError when a capture or a user-started
    start_profiler() trace is active. Blocks the calling thread for the
    window — HTTP servers routing here are threaded, so the process
    keeps serving while the trace runs.
    """
    global _active
    seconds = min(max(float(seconds), MIN_CAPTURE_SECONDS),
                  MAX_CAPTURE_SECONDS)
    if not _capture_lock.acquire(blocking=False):
        raise ProfilerBusyError("a profile capture is already running")
    try:
        base = os.environ.get(PROFILE_DIR_ENV)
        if out_dir is None:
            if base:
                os.makedirs(base, exist_ok=True)
            out_dir = tempfile.mkdtemp(prefix="paddle-tpu-profile-",
                                       dir=base or None)
        try:
            start_profiler(profile_path=out_dir)
        except RuntimeError as e:
            raise ProfilerBusyError(str(e)) from e
        t0 = time.time()
        try:
            time.sleep(seconds)
        finally:
            # stop directly rather than via stop_profiler(): the
            # aggregate host-event table printing belongs to the
            # interactive API, not an HTTP handler's stdout
            jax.profiler.stop_trace()
            _active = False
        trace_path = _tracing.export_trace(
            os.path.join(out_dir, "trace.json"), trace_dir=out_dir)
        perf_path = os.path.join(out_dir, "perf.json")
        from .observability import events as _events
        from .observability import memwatch as _memwatch
        from .observability import perfwatch as _perfwatch
        from .observability import telemetry as _telemetry

        perf = {
            "window_seconds": seconds,
            "started_at": t0,
            "perfwatch": _perfwatch.snapshot(),
            "memory": _memwatch.status_block(),
            "host_blocked_seconds_total":
                _telemetry.host_blocked_total(),
        }
        from .resilience.atomic import json_dump as _json_dump
        _json_dump(perf, perf_path, indent=2, sort_keys=True,
                   default=str)
        _events.emit("profile", dir=out_dir, seconds=seconds,
                     trace=trace_path)
        return {"dir": out_dir, "trace": trace_path, "perf": perf_path,
                "seconds": seconds}
    finally:
        _capture_lock.release()


def export_chrome_tracing(path, events=None):
    """Write ONE chrome://tracing JSON file (reference: tools/timeline.py:131
    converted profiler.proto to chrome trace): the unified span store
    (RecordEvent host spans, cat="host"; step telemetry, cat="step") plus
    the jax.profiler device timeline when a trace dir is known.

    `events`, if given, is the legacy list of (name, start_s, dur_s)
    tuples and is exported verbatim instead of the span store."""
    spans = None
    if events is not None:
        spans = [_tracing.Span(name, start, dur, "host", 0, None)
                 for name, start, dur in events]
    return _tracing.export_trace(path, trace_dir=_trace_dir, spans=spans)
