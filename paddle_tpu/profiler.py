"""Profiler (reference: python/paddle/fluid/profiler.py:228 context manager
→ C++ host profiler + CUPTI DeviceTracer, SURVEY §5 'Tracing/profiling').

TPU-native: jax.profiler captures both host and device timelines into
XPlane/perfetto traces — the role of profiler.proto + tools/timeline.py.
`RecordEvent`-style op annotation maps to jax.profiler.TraceAnnotation;
the host-side span record lands in the unified observability span store
(observability/tracing.py), so `export_chrome_tracing` emits ONE trace
holding RecordEvent host spans, executor/trainer step-telemetry spans,
and the jax device timeline."""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Optional

import jax

from .observability import tracing as _tracing

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "RecordEvent", "cuda_profiler", "npu_profiler",
           "export_chrome_tracing"]

_trace_dir: Optional[str] = None
_host_events = defaultdict(list)
_active = False


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default"):
    """reference: profiler.py:228 — `with profiler.profiler('All'):`"""
    start_profiler(state, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def start_profiler(state="All", profile_path="/tmp/profile", tracer_option=None):
    global _trace_dir, _active
    if _active:
        raise RuntimeError(
            "start_profiler called while a trace is already active; call "
            "stop_profiler() first (nested/overlapping jax traces are not "
            "supported)")
    _trace_dir = profile_path if os.path.isdir(profile_path) or not \
        os.path.splitext(profile_path)[1] else os.path.dirname(profile_path)
    os.makedirs(_trace_dir or ".", exist_ok=True)
    jax.profiler.start_trace(_trace_dir)
    _active = True


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Safe no-op when no trace was started — a teardown path may call it
    unconditionally."""
    global _active
    if _active:
        jax.profiler.stop_trace()
        _active = False
    _print_host_events(sorted_key)


def reset_profiler():
    """Clear ALL host-side profiler state: the aggregate event table, the
    unified span store, and the remembered trace dir (so one test's trace
    path cannot leak into the next export)."""
    global _trace_dir
    _host_events.clear()
    _tracing.clear_spans()
    _trace_dir = None


def trace_dir() -> Optional[str]:
    """Directory the current/last jax trace wrote into (None after
    reset)."""
    return _trace_dir


def _print_host_events(sorted_key=None):
    if not _host_events:
        return
    rows = []
    for name, times in _host_events.items():
        total = sum(times)
        rows.append((name, len(times), total, total / len(times)))
    if sorted_key in (None, "total"):
        rows.sort(key=lambda r: -r[2])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r[1])
    print(f"{'Event':40s} {'Calls':>8s} {'Total(ms)':>12s} {'Avg(ms)':>10s}")
    for name, calls, total, avg in rows:
        print(f"{name:40s} {calls:8d} {total * 1e3:12.3f} {avg * 1e3:10.3f}")


class RecordEvent:
    """reference: platform/profiler.h:81 RecordEvent RAII — host-side named
    span + device TraceAnnotation. The host span is recorded with
    cat="host" in the unified store."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        return self

    def __exit__(self, *a):
        self._ann.__exit__(*a)
        dur = time.perf_counter() - self._t0
        _host_events[self.name].append(dur)
        _tracing.record_span(self.name, self._t0, dur, cat="host")
        return False


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """reference: profiler.py:39 — accelerator-profiler passthrough."""
    with profiler(profile_path=output_file or "/tmp/profile"):
        yield


npu_profiler = cuda_profiler


def export_chrome_tracing(path, events=None):
    """Write ONE chrome://tracing JSON file (reference: tools/timeline.py:131
    converted profiler.proto to chrome trace): the unified span store
    (RecordEvent host spans, cat="host"; step telemetry, cat="step") plus
    the jax.profiler device timeline when a trace dir is known.

    `events`, if given, is the legacy list of (name, start_s, dur_s)
    tuples and is exported verbatim instead of the span store."""
    spans = None
    if events is not None:
        spans = [_tracing.Span(name, start, dur, "host", 0, None)
                 for name, start, dur in events]
    return _tracing.export_trace(path, trace_dir=_trace_dir, spans=spans)
