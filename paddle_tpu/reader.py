"""DataLoader / PyReader (reference: python/paddle/fluid/reader.py —
DataLoader.from_generator :73, GeneratorLoader :298, PyReader :569).

The reference pushes LoDTensors into a C++ LoDTensorBlockingQueue consumed by
a graph-embedded `read` op with double-buffering to GPU
(operators/reader/buffered_reader.cc). The TPU-native pipeline keeps the
same shape: a background thread runs the user generator into a bounded
host queue (core/async_exec.Prefetcher — producer errors propagate to
the iterating consumer, and the thread is joined when iteration stops
early), and with `use_double_buffer` + places a second Prefetcher stage
runs `jax.device_put` (sharded over the active SPMD mesh) into a
bounded double buffer, so batch N+1 is on device while step N computes
and batch N+2 is being collated on the host.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from .core.async_exec import (DevicePrefetcher, Prefetcher,
                              device_prefetch_wanted)
from .core.framework import Variable

__all__ = ["DataLoader", "PyReader", "GeneratorLoader",
           "ElasticShardPlan", "elastic_epoch_permutation"]

# reuse the reference's decorator library semantics
from .reader_decorators import (  # noqa: F401,E402
    batch, buffered, cache, chain, compose, firstn, map_readers,
    multiprocess_reader, shuffle, xmap_readers)


# ---------------------------------------------------------------------------
# Elastic data sharding (RESILIENCE.md §Elasticity)
# ---------------------------------------------------------------------------


def elastic_epoch_permutation(n_examples: int, epoch: int,
                              seed: int = 0) -> np.ndarray:
    """Per-epoch example shuffle that is WORLD-SIZE-INDEPENDENT: the
    permutation is keyed on (seed, epoch) only, so every worker — and a
    worker that joins mid-epoch — derives the identical global order.
    That independence is what lets a membership change re-split the
    stream without moving, losing, or double-seeing any example."""
    rs = np.random.RandomState(
        (int(seed) * 1_000_003 + int(epoch) * 7_919 + 1) & 0x7FFFFFFF)
    return rs.permutation(int(n_examples))


class ElasticShardPlan:
    """Deterministic assignment of the global example stream to workers,
    keyed on (epoch, global step, world size) — nothing else.

    The global stream is consumed `global_batch` examples per global
    step: step s covers epoch positions [p, p + global_batch) where
    p = (s % steps_per_epoch) * global_batch, mapped through the
    world-size-independent `elastic_epoch_permutation` for that epoch
    (trailing examples that don't fill a batch are dropped, the
    reference's drop_last semantics). Within a step the batch is split
    contiguously across the `world_size` workers in rank order, rank
    r taking `global_batch // W` examples (+1 for the first
    `global_batch % W` ranks).

    Invariant (the elastic contract): for EVERY world size W,
    `⋃_r worker_indices(s, r, W) == batch_indices(s)` — exactly, in
    order. A membership change between steps therefore re-splits the
    stream with no example lost or double-seen, and the concatenated
    global batch is bit-identical to the fixed-membership run, which is
    what makes the loss trajectory comparable across resizes
    (tools/chaos_bench.py --elastic proves it end to end).
    """

    def __init__(self, n_examples: int, global_batch: int, *,
                 seed: int = 0, shuffle_each_epoch: bool = True):
        if global_batch < 1 or n_examples < global_batch:
            raise ValueError(
                f"need n_examples >= global_batch >= 1, got "
                f"{n_examples} / {global_batch}")
        self.n_examples = int(n_examples)
        self.global_batch = int(global_batch)
        self.seed = int(seed)
        self.shuffle_each_epoch = bool(shuffle_each_epoch)
        self.steps_per_epoch = self.n_examples // self.global_batch
        self._perm_cache = {}
        # identity order shared across epochs — built once, not one
        # fresh n_examples-long arange per step on the hot data path
        self._identity = None if shuffle_each_epoch \
            else np.arange(self.n_examples)

    def epoch_of(self, step: int) -> int:
        return int(step) // self.steps_per_epoch

    def _perm(self, epoch: int) -> np.ndarray:
        if not self.shuffle_each_epoch:
            return self._identity
        if epoch not in self._perm_cache:
            # tiny cache: an elastic resize replays at most the current
            # and neighbouring epochs
            if len(self._perm_cache) > 4:
                self._perm_cache.clear()
            self._perm_cache[epoch] = elastic_epoch_permutation(
                self.n_examples, epoch, self.seed)
        return self._perm_cache[epoch]

    def batch_indices(self, step: int) -> np.ndarray:
        """Global example indices consumed at `step` — identical for
        every world size by construction."""
        step = int(step)
        pos = (step % self.steps_per_epoch) * self.global_batch
        return self._perm(self.epoch_of(step))[pos:pos + self.global_batch]

    def worker_counts(self, world_size: int) -> List[int]:
        if world_size < 1:
            raise ValueError("world_size must be >= 1")
        q, rem = divmod(self.global_batch, int(world_size))
        return [q + (1 if r < rem else 0) for r in range(int(world_size))]

    def worker_indices(self, step: int, rank: int,
                       world_size: int) -> np.ndarray:
        """`rank`'s slice of the step's global batch under `world_size`
        live workers: the contiguous split of batch_indices(step)."""
        counts = self.worker_counts(world_size)
        if not 0 <= int(rank) < len(counts):
            raise ValueError(f"rank {rank} out of range for world "
                             f"{world_size}")
        start = sum(counts[:int(rank)])
        return self.batch_indices(step)[start:start + counts[int(rank)]]


class GeneratorLoader:
    """reference: reader.py:298."""

    def __init__(self, feed_list: Sequence[Variable], capacity: int = 64,
                 iterable: bool = True, return_list: bool = False,
                 use_double_buffer: bool = True):
        self._feed_list = list(feed_list)
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._generator: Optional[Callable] = None
        self._places = None
        self._batched = False
        self._use_double_buffer = bool(use_double_buffer)

    @property
    def feed_list(self):
        return list(self._feed_list)

    # -- configuration (reference API) --------------------------------------

    def set_sample_generator(self, reader, batch_size, drop_last=True, places=None):
        def batched():
            buf = []
            for sample in reader():
                if not isinstance(sample, (list, tuple)):
                    sample = (sample,)
                buf.append(sample)
                if len(buf) == batch_size:
                    yield self._collate(buf)
                    buf = []
            if buf and not drop_last:
                yield self._collate(buf)

        self._generator = batched
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        def gen():
            for samples in reader():
                yield self._collate(samples)

        self._generator = gen
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def gen():
            for batch_data in reader():
                if isinstance(batch_data, dict):
                    yield batch_data
                else:
                    yield {v.name: np.asarray(a)
                           for v, a in zip(self._feed_list, batch_data)}

        self._generator = gen
        self._places = places
        return self

    def _collate(self, samples):
        from .data_feeder import DataFeeder

        return DataFeeder(self._feed_list).feed(samples)

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        assert self._generator is not None, "call set_*_generator first"
        # host producer stage: the bounded background queue the
        # reference's LoDTensorBlockingQueue provides. Prefetcher owns
        # the lifecycle — a generator exception re-raises HERE (not a
        # silent hang/truncation), and the finally clause joins the
        # thread when the consumer stops iterating early.
        host = Prefetcher(self._generator(), depth=self._capacity,
                          stage="host")
        device = None
        if device_prefetch_wanted(self._places, self._use_double_buffer):
            # prefetch-to-device: batches go up via jax.device_put
            # (sharded over the active SPMD mesh) two batches ahead
            device = DevicePrefetcher(host, depth=2)
        try:
            yield from (device if device is not None else host)
        finally:
            if device is not None:
                device.close()
            host.close()

    # reference idiom: `for data in loader():`
    def __call__(self):
        return iter(self)

    # non-iterable (start/reset) mode used with graph readers in the
    # reference; provided for API parity
    def start(self):
        self._it = iter(self)

    def reset(self):
        self._it = None

    def next(self):
        return next(self._it)


class DataLoader:
    """reference: reader.py:73."""

    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False):
        return GeneratorLoader(feed_list or [], capacity, iterable, return_list,
                               use_double_buffer)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True,
                     use_double_buffer=False):
        from .dataset_loader import DatasetLoader

        return DatasetLoader(dataset, places, drop_last,
                             use_double_buffer=use_double_buffer)


class PyReader(GeneratorLoader):
    """reference: reader.py:569 (older API surface over the same loader)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list or [], capacity, iterable, return_list,
                         use_double_buffer)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
