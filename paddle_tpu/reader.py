"""DataLoader / PyReader (reference: python/paddle/fluid/reader.py —
DataLoader.from_generator :73, GeneratorLoader :298, PyReader :569).

The reference pushes LoDTensors into a C++ LoDTensorBlockingQueue consumed by
a graph-embedded `read` op with double-buffering to GPU
(operators/reader/buffered_reader.cc). The TPU-native pipeline keeps the
same shape: a background thread runs the user generator into a bounded
host queue (core/async_exec.Prefetcher — producer errors propagate to
the iterating consumer, and the thread is joined when iteration stops
early), and with `use_double_buffer` + places a second Prefetcher stage
runs `jax.device_put` (sharded over the active SPMD mesh) into a
bounded double buffer, so batch N+1 is on device while step N computes
and batch N+2 is being collated on the host.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from .core.async_exec import (DevicePrefetcher, Prefetcher,
                              device_prefetch_wanted)
from .core.framework import Variable

__all__ = ["DataLoader", "PyReader", "GeneratorLoader"]

# reuse the reference's decorator library semantics
from .reader_decorators import (  # noqa: F401,E402
    batch, buffered, cache, chain, compose, firstn, map_readers,
    multiprocess_reader, shuffle, xmap_readers)


class GeneratorLoader:
    """reference: reader.py:298."""

    def __init__(self, feed_list: Sequence[Variable], capacity: int = 64,
                 iterable: bool = True, return_list: bool = False,
                 use_double_buffer: bool = True):
        self._feed_list = list(feed_list)
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._generator: Optional[Callable] = None
        self._places = None
        self._batched = False
        self._use_double_buffer = bool(use_double_buffer)

    @property
    def feed_list(self):
        return list(self._feed_list)

    # -- configuration (reference API) --------------------------------------

    def set_sample_generator(self, reader, batch_size, drop_last=True, places=None):
        def batched():
            buf = []
            for sample in reader():
                if not isinstance(sample, (list, tuple)):
                    sample = (sample,)
                buf.append(sample)
                if len(buf) == batch_size:
                    yield self._collate(buf)
                    buf = []
            if buf and not drop_last:
                yield self._collate(buf)

        self._generator = batched
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        def gen():
            for samples in reader():
                yield self._collate(samples)

        self._generator = gen
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def gen():
            for batch_data in reader():
                if isinstance(batch_data, dict):
                    yield batch_data
                else:
                    yield {v.name: np.asarray(a)
                           for v, a in zip(self._feed_list, batch_data)}

        self._generator = gen
        self._places = places
        return self

    def _collate(self, samples):
        from .data_feeder import DataFeeder

        return DataFeeder(self._feed_list).feed(samples)

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        assert self._generator is not None, "call set_*_generator first"
        # host producer stage: the bounded background queue the
        # reference's LoDTensorBlockingQueue provides. Prefetcher owns
        # the lifecycle — a generator exception re-raises HERE (not a
        # silent hang/truncation), and the finally clause joins the
        # thread when the consumer stops iterating early.
        host = Prefetcher(self._generator(), depth=self._capacity,
                          stage="host")
        device = None
        if device_prefetch_wanted(self._places, self._use_double_buffer):
            # prefetch-to-device: batches go up via jax.device_put
            # (sharded over the active SPMD mesh) two batches ahead
            device = DevicePrefetcher(host, depth=2)
        try:
            yield from (device if device is not None else host)
        finally:
            if device is not None:
                device.close()
            host.close()

    # reference idiom: `for data in loader():`
    def __call__(self):
        return iter(self)

    # non-iterable (start/reset) mode used with graph readers in the
    # reference; provided for API parity
    def start(self):
        self._it = iter(self)

    def reset(self):
        self._it = None

    def next(self):
        return next(self._it)


class DataLoader:
    """reference: reader.py:73."""

    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False):
        return GeneratorLoader(feed_list or [], capacity, iterable, return_list,
                               use_double_buffer)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True,
                     use_double_buffer=False):
        from .dataset_loader import DatasetLoader

        return DatasetLoader(dataset, places, drop_last,
                             use_double_buffer=use_double_buffer)


class PyReader(GeneratorLoader):
    """reference: reader.py:569 (older API surface over the same loader)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list or [], capacity, iterable, return_list,
                         use_double_buffer)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
