"""Append-only JSONL event log: the discrete-occurrence companion to the
metrics registry.

Metrics answer "how much/how fast"; this log answers "what happened and
when": XLA compiles (a recompile storm is a sequence of `compile` events
seconds apart), trainer run summaries, tensor-health anomalies, and
checkpoint writes. Every event carries a process-monotonic `seq` and a
wall-clock `ts`, so a tail of the file reconstructs the run's story even
after the process died — the reason long TPU jobs keep such a log on
disk rather than only in memory.

Sinks:
  - an in-process ring (`recent()`), always on and bounded — this is what
    the /events HTTP route and tests read;
  - a JSONL file, appended when `PADDLE_TPU_EVENT_LOG` names a path (or,
    if unset, `PADDLE_TPU_METRICS_DIR` is set, in which case
    `<dir>/events.jsonl` is used). One `json.dumps` line per event,
    append-only: `tools/obsdump.py events` tails and pretty-prints it.

Rotation: with `PADDLE_TPU_EVENT_LOG_MAX_BYTES` set, the file sink
rolls over before an append would push the file past the cap —
events.jsonl → events.jsonl.1 (→ .2 …), keeping
`PADDLE_TPU_EVENT_LOG_KEEP` rotated files (default 3, oldest deleted) —
so an append-only log under fleet load stays bounded instead of growing
without limit. `obsdump events --follow` detects the rename (inode
change) and reopens the fresh file without dropping lines.

Trace join key: when the distributed-tracing layer (tracing.py) has a
sampled context active at emit time, the event gains a `trace_id` field
— the JSONL event log then joins against the trace sink without every
emitter threading ids by hand. The hook is injected via
`set_trace_provider` (observability/__init__.py wires it) so this
module stays stdlib-only and file-path importable.

Schema (stable, documented in PROFILE.md §Health):
  {"seq": int, "ts": float unix seconds, "kind": str, ...kind fields}

This module is stdlib-only by contract: tools/obsdump.py imports it by
file path without pulling in the framework or jax.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["emit", "recent", "clear", "log_path", "read_jsonl",
           "set_trace_provider", "MAX_EVENTS", "KINDS"]

# Known event kinds (emitters may add more; these are the documented core).
# serve_start/serve_stop bracket a serving.Server's lifetime (SERVING.md).
# restore/preempt/fault/recovery/rank_restart are the resilience layer's
# story of a faulty run (RESILIENCE.md): checkpoint restores (incl.
# corrupt-fallback skips), graceful-stop requests, injected faults,
# recovery-policy actions, and launcher rank restarts.
# rendezvous/resize/restore_resharded are the elastic layer's story of a
# world-size change (RESILIENCE.md §Elasticity): sealed generations,
# mesh re-formations, and cross-mesh checkpoint restores.
# ps_failover is the parameter-server tier's story of an outage
# (RESILIENCE.md §Parameter-server fault tolerance): breaker
# transitions, reconnects, snapshot restores at server boot, supervisor
# respawns, and counted gradient drops.
# fleet is the serving fleet tier's story (SERVING.md §Fleet): member
# joins/leaves, health ejections/readmissions, retry failovers, breaker
# transitions, autoscale decisions, replica respawns; serve_drain marks
# a replica's graceful scale-in drain.
# slo_alert is the SLO engine's story (PROFILE.md §Time series & SLOs):
# burn-rate alert state transitions (ok ↔ fast_burn/slow_burn) with the
# firing window's burn numbers attached.
# oom is the memory tier's post-mortem (PROFILE.md §Continuous
# profiling): a RESOURCE_EXHAUSTED intercepted on a dispatch path with
# the ranked per-owner live-buffer attribution attached; hbm_budget
# marks PADDLE_TPU_HBM_BUDGET_BYTES state transitions (warn/error);
# profile marks an on-demand /v1/profile capture window with its
# artifact dir.
KINDS = ("compile", "compile_cache", "step_summary", "anomaly",
         "checkpoint", "serve_start", "serve_stop", "serve_drain",
         "restore", "preempt",
         "fault", "recovery", "rank_restart", "pipeline_stall",
         "warmstart", "amp_overflow", "quantize", "analysis",
         "rendezvous", "resize", "restore_resharded", "ps_failover",
         "decode", "fleet", "slo_alert",
         "oom", "hbm_budget", "profile")

# Ring bound: a week-long run emitting a compile+summary event per minute
# stays far under this; anomaly storms get truncated to the latest window.
MAX_EVENTS = 4096

_lock = threading.Lock()
_file_lock = threading.Lock()  # file appends serialize separately: a
# slow disk must not block ring readers (/events) or other emitters'
# seq assignment
_ring: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=MAX_EVENTS)
_seq = 0


_trace_provider = None


def set_trace_provider(fn):
    """Install the callable emit() asks for the active sampled trace id
    (observability/__init__.py wires tracing.current_trace_id here;
    None uninstalls). Kept as injection so this module never imports
    its sibling — tools/obsdump.py loads it standalone by file path."""
    global _trace_provider
    _trace_provider = fn


def log_path() -> Optional[str]:
    """Resolved JSONL sink path, or None when file logging is off.
    Re-read from the env on every call so tests can monkeypatch."""
    p = os.environ.get("PADDLE_TPU_EVENT_LOG")
    if p:
        return p
    d = os.environ.get("PADDLE_TPU_METRICS_DIR")
    if d:
        return os.path.join(d, "events.jsonl")
    return None


def _rotate_cap() -> int:
    """PADDLE_TPU_EVENT_LOG_MAX_BYTES as an int (0/unset/malformed =
    rotation off)."""
    raw = os.environ.get("PADDLE_TPU_EVENT_LOG_MAX_BYTES")
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


def _rotate_keep() -> int:
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_EVENT_LOG_KEEP",
                                         "3")))
    except ValueError:
        return 3


def _maybe_rotate_locked(path: str, incoming: int):
    """Under _file_lock: roll the sink over when appending `incoming`
    bytes would push it past the cap. os.replace renames are atomic, so
    a concurrent reader sees either the old file (under its old inode —
    how `obsdump events --follow` finishes the tail before reopening)
    or the fresh one, never a mix.

    The sink is shared ACROSS processes in a fleet (every replica
    inherits PADDLE_TPU_EVENT_LOG), so the keep-chain shift is guarded
    by an OS-level flock on a sibling lockfile — two processes racing
    the cap would otherwise both rotate, shifting a seconds-old
    generation outward and deleting the oldest retained file early. The
    size is re-checked under the flock: the loser of the race sees the
    fresh (small) file and skips."""
    cap = _rotate_cap()
    if not cap:
        return
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size == 0 or size + incoming <= cap:
        return
    lockf = None
    try:
        import fcntl
        lockf = open(path + ".rotlock", "a")
        fcntl.flock(lockf, fcntl.LOCK_EX)
    except (ImportError, OSError):
        lockf = None  # non-POSIX / unwritable dir: best-effort rotate
    try:
        if lockf is not None:
            try:
                size = os.path.getsize(path)
            except OSError:
                return
            if size == 0 or size + incoming <= cap:
                return  # a peer process rotated while we waited
        keep = _rotate_keep()
        try:
            oldest = f"{path}.{keep}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(keep - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            os.replace(path, f"{path}.1")
        except OSError:
            pass  # lint-exempt:swallow: rotation is best-effort; the append below still lands
    finally:
        if lockf is not None:
            try:
                lockf.close()  # releases the flock
            except OSError:
                pass  # lint-exempt:swallow: lockfile close on teardown


def emit(kind: str, **fields) -> Dict[str, Any]:
    """Record one event: ring always, file when a sink is configured.
    Returns the event dict (with seq/ts filled in)."""
    global _seq
    with _lock:
        _seq += 1
        ev: Dict[str, Any] = {"seq": _seq, "ts": time.time(), "kind": kind}
        ev.update(fields)
        if _trace_provider is not None and "trace_id" not in ev:
            try:
                tid = _trace_provider()
            except Exception:
                tid = None
            if tid:
                ev["trace_id"] = tid
        _ring.append(ev)
    path = log_path()
    if path:
        # outside the ring lock: concurrent writers may land file lines
        # out of seq order, but each line is whole and carries its seq
        try:
            line = json.dumps(ev, default=str) + "\n"
            with _file_lock:
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                _maybe_rotate_locked(path, len(line))
                with open(path, "a") as f:
                    f.write(line)
        except OSError:
            pass  # a full/vanished disk must not kill the trainer
    return ev


def _tail(evs: List[Dict[str, Any]], n: Optional[int]):
    if n is None:
        return evs
    n = int(n)
    return evs[-n:] if n > 0 else []  # [-0:] would mean "everything"


def recent(n: int = 100, kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Last `n` events (oldest first), optionally filtered by kind."""
    with _lock:
        evs = list(_ring)
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return _tail(evs, n)


def clear():
    """Drop the in-memory ring (test hygiene; the file is append-only and
    never truncated here)."""
    with _lock:
        _ring.clear()


def read_jsonl(path: str, n: Optional[int] = None,
               kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse a JSONL event file: last `n` events, optionally filtered by
    kind. Malformed lines are skipped (a crash mid-append can truncate
    the final line). tools/obsdump.py's `events` subcommand carries its
    own single-file-handle variant of this logic so its --follow mode
    has no gap between the initial tail and the stream."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if kind is not None and ev.get("kind") != kind:
                continue
            out.append(ev)
    return _tail(out, n)
