"""Unified observability layer: metrics, telemetry, traces, health, HTTP.

Six pieces (see PROFILE.md §Observability and §Health for the
user-facing guide):

- metrics.py   — process-wide registry (counters/gauges/histograms with
                 labels), JSON + Prometheus exposition, env-gated periodic
                 dump (PADDLE_TPU_METRICS_DIR).
- tracing.py   — one span store for profiler.RecordEvent host spans and
                 step telemetry, merged with jax.profiler device traces
                 into a single chrome-trace export; also the distributed
                 trace-context layer (W3C traceparent + contextvars +
                 per-process JSONL sink, PADDLE_TPU_TRACE_DIR /
                 PADDLE_TPU_TRACE_SAMPLE — PROFILE.md §Distributed
                 tracing).
- telemetry.py — the metric vocabulary + record helpers the executor,
                 trainer, and SPMD/pipeline stacks call on their hot
                 paths (step timing, cache events, compiles, device
                 memory).
- health.py    — env-gated NaN/Inf/out-of-range scanning at the
                 framework's observation points
                 (PADDLE_TPU_CHECK_NUMERICS=0|1|2) + /healthz state.
- events.py    — append-only JSONL event log (compile / step_summary /
                 anomaly / checkpoint) with a bounded in-memory ring
                 (PADDLE_TPU_EVENT_LOG).
- httpd.py     — stdlib daemon thread serving /metrics, /healthz,
                 /events?n=K and /v1/slo live (PADDLE_TPU_METRICS_PORT).
- timeseries.py — env-gated background recorder appending delta-encoded
                 registry samples to per-process segmented JSONL sinks
                 (PADDLE_TPU_TS_DIR / PADDLE_TPU_TS_INTERVAL_S —
                 PROFILE.md §Time series & SLOs).
- aggregate.py — stdlib cross-process TS reader: merge by
                 (metric, labels), windowed rate()/increase()/quantile,
                 fleet roll-ups.
- slo.py       — declarative SLOs (availability / latency) evaluated by
                 a multi-window burn-rate alert state machine; slo_alert
                 events, burn-rate metrics, GET /v1/slo.
- httpbase.py  — shared stdlib-HTTP lifecycle (quiet handler, locked
                 idempotent start/stop, failed-bind caching, atexit);
                 also the base of the serving frontend
                 (paddle_tpu/serving/httpd.py, see SERVING.md).

`tools/obsdump.py` pretty-prints dumps, tails event logs, and rebuilds
traces offline.
"""

from . import metrics
from . import tracing
from . import telemetry
from . import events
from . import health
from . import httpd
from . import timeseries
from . import aggregate
from . import slo
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, bucket_quantile, counter,
    default_registry, dump, gauge, histogram, maybe_start_dump_thread,
    render_prometheus, reset, snapshot, stop_dump_thread,
)
from .timeseries import (  # noqa: F401
    Recorder, maybe_start_recorder, stop_recorder,
)
from .aggregate import TSStore, read_ts_dir  # noqa: F401
from .slo import (  # noqa: F401
    SLOEngine, maybe_start_evaluator, stop_evaluator,
)
from .tracing import (  # noqa: F401
    Span, TraceContext, begin_request, clear_spans, current_trace,
    export_trace, flush_trace_sink, get_spans, parse_traceparent,
    record_span, save_spans, span, start_trace, step_span, trace_headers,
    trace_span,
)
from .health import NumericsError, check_numerics  # noqa: F401

# the event log's trace join key: emit() asks this for the active
# sampled trace id (injected so events.py stays file-path importable)
events.set_trace_provider(tracing.current_trace_id)
from .httpd import (  # noqa: F401
    maybe_start_http_server, start_http_server, stop_http_server,
)

__all__ = [
    "metrics", "tracing", "telemetry", "events", "health", "httpd",
    "timeseries", "aggregate", "slo",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "counter",
    "bucket_quantile", "default_registry", "dump", "gauge", "histogram",
    "maybe_start_dump_thread", "render_prometheus", "reset", "snapshot",
    "stop_dump_thread",
    "Recorder", "maybe_start_recorder", "stop_recorder",
    "TSStore", "read_ts_dir",
    "SLOEngine", "maybe_start_evaluator", "stop_evaluator",
    "Span", "TraceContext", "begin_request", "clear_spans",
    "current_trace", "export_trace", "flush_trace_sink", "get_spans",
    "parse_traceparent", "record_span", "save_spans", "span",
    "start_trace", "step_span", "trace_headers", "trace_span",
    "NumericsError", "check_numerics",
    "maybe_start_http_server", "start_http_server", "stop_http_server",
]
