"""Unified observability layer: metrics registry, step telemetry, traces.

Three pieces (see PROFILE.md §Observability for the user-facing guide):

- metrics.py   — process-wide registry (counters/gauges/histograms with
                 labels), JSON + Prometheus exposition, env-gated periodic
                 dump (PADDLE_TPU_METRICS_DIR).
- tracing.py   — one span store for profiler.RecordEvent host spans and
                 step telemetry, merged with jax.profiler device traces
                 into a single chrome-trace export.
- telemetry.py — the metric vocabulary + record helpers the executor,
                 trainer, and SPMD/pipeline stacks call on their hot
                 paths.

`tools/obsdump.py` pretty-prints dumps and rebuilds traces offline.
"""

from . import metrics
from . import tracing
from . import telemetry
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, counter, default_registry,
    dump, gauge, histogram, maybe_start_dump_thread, render_prometheus,
    reset, snapshot, stop_dump_thread,
)
from .tracing import (  # noqa: F401
    Span, clear_spans, export_trace, get_spans, record_span, save_spans,
    span,
)

__all__ = [
    "metrics", "tracing", "telemetry",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "counter",
    "default_registry", "dump", "gauge", "histogram",
    "maybe_start_dump_thread", "render_prometheus", "reset", "snapshot",
    "stop_dump_thread",
    "Span", "clear_spans", "export_trace", "get_spans", "record_span",
    "save_spans", "span",
]
