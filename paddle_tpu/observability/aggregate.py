"""Cross-process time-series aggregation: read a `PADDLE_TPU_TS_DIR`
written by any number of recorder pids (timeseries.py) and evaluate
windowed expressions over the merged history — `increase()`, `rate()`,
latest-gauge roll-ups, merged histogram tables and bucket quantiles.

Stdlib-only and file-path importable, like tracing's readers: this is
the module `tools/obsdump.py top` loads WITHOUT the framework (and the
jax stack behind it) to render a fleet dashboard from disk. Sibling
modules (metrics.py for the shared `bucket_quantile`) are resolved
through `_sibling()`: the normal relative import inside the package, a
spec_from_file_location fallback when loaded standalone.

Semantics:
  * A window is `now - window_s < ts <= now` over record wall-clock
    stamps; `now` defaults to the newest record in the store (so
    offline analysis of an old dir still has a full window).
  * Counter/histogram samples are per-interval DELTAS (the recorder's
    encoding), so increase() is a plain sum over the window — no
    monotonic-reset heuristics needed here; the writer already handled
    resets.
  * Roll-ups SUM across pids and label sets by default; `labels=` keeps
    only series whose labels contain every given pair, `by=` groups the
    result by one label's values.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["read_ts_dir", "TSStore", "bucket_quantile"]


def _sibling(name: str):
    """Import a sibling observability module whether this file was
    imported as part of the package or loaded by file path (obsdump)."""
    if __package__:
        from importlib import import_module

        return import_module(f".{name}", __package__)
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), name + ".py")
    spec = importlib.util.spec_from_file_location(f"_pt_obs_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bucket_quantile = _sibling("metrics").bucket_quantile


def read_ts_dir(directory: str) -> List[dict]:
    """Every record from every `ts-*.jsonl` segment in `directory`,
    sorted by timestamp. Malformed lines (a reader racing a non-atomic
    writer, a truncated copy) are skipped, not fatal."""
    records = []
    for path in sorted(glob.glob(os.path.join(directory, "ts-*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "ts" in rec:
                        records.append(rec)
        except OSError:
            continue  # segment deleted by retention mid-scan
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records


def _labels_match(labels: Dict[str, str],
                  want: Optional[Dict[str, str]]) -> bool:
    if not want:
        return True
    return all(str(labels.get(k)) == str(v) for k, v in want.items())


class TSStore:
    """An in-memory merge of one TS dir. Load once, query many — the
    SLO evaluator reloads per tick; obsdump --watch reloads per frame."""

    def __init__(self, records: List[dict]):
        self.records = sorted(records, key=lambda r: r.get("ts", 0.0))

    @classmethod
    def load(cls, directory: str) -> "TSStore":
        return cls(read_ts_dir(directory))

    def latest_ts(self) -> Optional[float]:
        return self.records[-1]["ts"] if self.records else None

    def pids(self) -> List[int]:
        return sorted({int(r.get("pid", 0)) for r in self.records})

    def names(self) -> List[str]:
        out = set()
        for rec in self.records:
            for s in rec.get("samples", ()):
                out.add(s.get("name"))
        return sorted(n for n in out if n)

    def _iter(self, name: str, kind: str, window_s: float,
              now: Optional[float], labels: Optional[Dict[str, str]]):
        if now is None:
            now = self.latest_ts()
        if now is None:
            return
        lo = now - float(window_s)
        for rec in self.records:
            ts = rec.get("ts", 0.0)
            if ts <= lo or ts > now:
                continue
            for s in rec.get("samples", ()):
                if s.get("name") != name or s.get("kind") != kind:
                    continue
                if not _labels_match(s.get("labels", {}), labels):
                    continue
                yield rec, s

    # -- expressions ---------------------------------------------------

    def increase(self, name: str, window_s: float,
                 now: Optional[float] = None,
                 labels: Optional[Dict[str, str]] = None,
                 by: Optional[str] = None):
        """Total counter growth over the window, summed across pids and
        label sets. With `by=<label>`: {label_value: growth}."""
        if by is None:
            return float(sum(
                s.get("delta", 0.0) for _, s in
                self._iter(name, "counter", window_s, now, labels)))
        out: Dict[str, float] = {}
        for _, s in self._iter(name, "counter", window_s, now, labels):
            k = str(s.get("labels", {}).get(by, ""))
            out[k] = out.get(k, 0.0) + float(s.get("delta", 0.0))
        return out

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None,
             labels: Optional[Dict[str, str]] = None,
             by: Optional[str] = None):
        """increase / window — events per second over the window."""
        inc = self.increase(name, window_s, now, labels, by)
        w = max(1e-9, float(window_s))
        if isinstance(inc, dict):
            return {k: v / w for k, v in inc.items()}
        return inc / w

    def gauge_latest(self, name: str, window_s: float = float("inf"),
                     now: Optional[float] = None,
                     labels: Optional[Dict[str, str]] = None,
                     by: Optional[str] = None):
        """Fleet roll-up of a gauge: the latest reading per (pid, label
        set) inside the window, summed (queue depths, replica counts —
        additive point-in-time state). With `by=`: grouped sums."""
        latest: Dict[Tuple, Tuple[float, float, Dict]] = {}
        for rec, s in self._iter(name, "gauge", window_s, now, labels):
            key = (rec.get("pid"),
                   tuple(sorted(s.get("labels", {}).items())))
            ts = rec.get("ts", 0.0)
            prev = latest.get(key)
            if prev is None or ts >= prev[0]:
                latest[key] = (ts, float(s.get("value", 0.0)),
                               s.get("labels", {}))
        if by is None:
            return float(sum(v for _, v, _ in latest.values()))
        out: Dict[str, float] = {}
        for _, v, lab in latest.values():
            k = str(lab.get(by, ""))
            out[k] = out.get(k, 0.0) + v
        return out

    def hist_increase(self, name: str, window_s: float,
                      now: Optional[float] = None,
                      labels: Optional[Dict[str, str]] = None) -> Dict:
        """Histogram growth over the window merged across pids/labels:
        {"count", "sum", "buckets": [(le, n), ...]} with per-bin counts
        (the shape bucket_quantile takes)."""
        count, total = 0, 0.0
        bins: Dict[float, float] = {}
        for _, s in self._iter(name, "histogram", window_s, now, labels):
            count += int(s.get("count_delta", 0))
            total += float(s.get("sum_delta", 0.0))
            for le, n in s.get("bucket_deltas", ()):
                le = float(le)
                bins[le] = bins.get(le, 0.0) + float(n)
        return {"count": count, "sum": total,
                "buckets": sorted(bins.items())}

    def quantile(self, q: float, name: str, window_s: float,
                 now: Optional[float] = None,
                 labels: Optional[Dict[str, str]] = None):
        """Windowed histogram quantile (fleet-merged), via the shared
        bucket interpolation. None when the window saw no observations."""
        h = self.hist_increase(name, window_s, now, labels)
        return bucket_quantile(q, h["buckets"], h["count"])
