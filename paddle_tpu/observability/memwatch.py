"""Owner-tagged HBM accounting + OOM forensics.

The PR 2 live-memory gauge (paddle_tpu_device_live_bytes) answers "how
much" but not "whose": before a model can safely exceed one chip
(ROADMAP item 1) the KV pool, parameters and optimizer state each need
their own budget line. This module attributes the existing rate-limited
`jax.live_arrays()` sweep to registered owners:

  - the decode engine registers its KV pools and params
    (serving/decode.py), TrainState instances register params/optimizer
    state (parallel/train.py) — registration is a PROVIDER callable
    returning the owner's current arrays, so donated buffers that are
    replaced every step stay correctly attributed;
  - compiled executables report their memory_analysis() generated-code
    bytes through core/executor's dispatch registry (device-resident
    but not jax arrays, so they ride alongside the live-array total
    rather than inside it);
  - everything unmatched lands in owner="other".

Gauges: paddle_tpu_hbm_bytes{owner} / paddle_tpu_hbm_buffers{owner},
paddle_tpu_hbm_watermark_bytes (high watermark of the live total),
paddle_tpu_executable_bytes, paddle_tpu_hbm_budget_bytes.

Budget: PADDLE_TPU_HBM_BUDGET_BYTES (int; unset = no budget). Crossing
85% logs a warning + `hbm_budget` event (level=warn); crossing 100%
logs an error + event (level=error). Transitions only — a sweep per
step must not spam the log.

OOM forensics: `oom_guard(kind)` / `maybe_handle_oom` wrap the dispatch
paths (core/executor._JitDispatch, the fetch epilogue, the decode
scheduler). A RESOURCE_EXHAUSTED escaping the device turns into a
ranked per-owner live-buffer report in the log + an `oom` event before
re-raising — a post-mortem instead of a bare stack trace.

Import-light by contract (stdlib at import; jax deferred into the
sweep): core/executor.py imports this at module load.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from . import events as _events
from . import metrics as _m

__all__ = ["register_provider", "register_bytes_provider",
           "unregister_provider",
           "set_executables_provider", "sweep", "report", "last_report",
           "status_block", "budget_bytes", "watermark_bytes",
           "is_oom", "maybe_handle_oom", "oom_guard", "reset"]

log = logging.getLogger("paddle_tpu.observability.memwatch")

BUDGET_ENV = "PADDLE_TPU_HBM_BUDGET_BYTES"
WARN_FRACTION = 0.85
# sweeps triggered through status endpoints / forced paths still walk
# every live array; keep an internal floor so a tight status-poll loop
# cannot turn the walk into a per-request cost
_MIN_INTERVAL_S = 1.0

HBM_BYTES = _m.gauge(
    "paddle_tpu_hbm_bytes",
    "Live device-buffer bytes attributed to their owner (kv_pool | "
    "params | optimizer | other) by the rate-limited jax.live_arrays "
    "sweep; owners sum to paddle_tpu_device_live_bytes",
    labelnames=("owner",))
HBM_BUFFERS = _m.gauge(
    "paddle_tpu_hbm_buffers",
    "Live device-array count per owner", labelnames=("owner",))
HBM_WATERMARK = _m.gauge(
    "paddle_tpu_hbm_watermark_bytes",
    "High watermark of total live device-buffer bytes since process "
    "start (ratchet; never decreases)")
HBM_BUDGET = _m.gauge(
    "paddle_tpu_hbm_budget_bytes",
    "Configured HBM budget (PADDLE_TPU_HBM_BUDGET_BYTES); 0 = no "
    "budget")
EXECUTABLE_BYTES = _m.gauge(
    "paddle_tpu_executable_bytes",
    "memory_analysis() generated-code bytes summed over live compiled "
    "executables (device-resident, outside the live-array total)")
OOMS = _m.counter(
    "paddle_tpu_oom_total",
    "RESOURCE_EXHAUSTED errors intercepted on a dispatch path, by "
    "dispatch kind — each also dumps a ranked per-owner report and an "
    "`oom` event", labelnames=("kind",))

_lock = threading.Lock()
# insertion-ordered: attribution precedence when providers overlap
_providers: "Dict[int, tuple]" = {}   # handle -> (owner, fn)
# byte-providers: owners whose bytes live INSIDE other owners' arrays
# (e.g. prefix_cache blocks inside the kv_pool buffers) — reported as
# their own row but NOT added to the live-array total
_bytes_providers: "Dict[int, tuple]" = {}   # handle -> (owner, fn)
_next_handle = [0]
_exec_provider: List[Optional[Callable[[], tuple]]] = [None]
_watermark = [0.0]
_budget_state = ["ok"]                # ok | warn | error
_last_sweep_t = [0.0]
_last: List[Optional[Dict[str, Any]]] = [None]

TOP_N = 12


def register_provider(owner: str, fn: Callable[[], Iterable]) -> int:
    """Register a callable returning the owner's CURRENT arrays (called
    at sweep time, so buffers replaced by donation stay attributed).
    Returns a handle for unregister_provider. Providers must be cheap
    and exception-safe is not required — a raising provider is skipped
    for that sweep."""
    with _lock:
        _next_handle[0] += 1
        h = _next_handle[0]
        _providers[h] = (owner, fn)
    return h


def register_bytes_provider(owner: str,
                            fn: Callable[[], tuple]) -> int:
    """Register a callable returning `(bytes, count)` for an owner
    whose footprint is a SLICE of arrays someone else already owns —
    the prefix cache's retained blocks live inside the kv_pool
    buffers. The owner gets its own gauge/report row (like
    executable_bytes it rides ALONGSIDE the live-array total, never
    summed into it). Returns a handle for unregister_provider."""
    with _lock:
        _next_handle[0] += 1
        h = _next_handle[0]
        _bytes_providers[h] = (owner, fn)
    return h


def unregister_provider(handle: int):
    with _lock:
        _providers.pop(handle, None)
        _bytes_providers.pop(handle, None)


def set_executables_provider(fn: Callable[[], tuple]):
    """Install the callable returning (code_bytes_total, n_executables)
    for live compiled executables. Injection (not an import) so this
    module never imports core/executor — which imports IT at load."""
    _exec_provider[0] = fn


def budget_bytes() -> Optional[int]:
    raw = os.environ.get(BUDGET_ENV)
    if not raw:
        return None
    try:
        v = int(float(raw))
    except ValueError:
        return None
    return v if v > 0 else None


def watermark_bytes() -> int:
    return int(_watermark[0])


def reset():
    """Tests: drop providers, watermark and budget state."""
    with _lock:
        _providers.clear()
        _bytes_providers.clear()
    _watermark[0] = 0.0
    _budget_state[0] = "ok"
    _last_sweep_t[0] = 0.0
    _last[0] = None


def _owned_ids() -> Dict[int, str]:
    """id(array) -> owner, from every registered provider. First
    registration wins on overlap."""
    with _lock:
        provs = list(_providers.values())
    owned: Dict[int, str] = {}
    for owner, fn in provs:
        try:
            arrays = fn()
        except Exception:  # lint-exempt:swallow: a dead provider (engine stopped mid-sweep) skips one sweep
            continue
        for a in arrays or ():
            owned.setdefault(id(a), owner)
    return owned


def sweep(force: bool = False, top: bool = False
          ) -> Optional[Dict[str, Any]]:
    """Walk jax.live_arrays(), attribute to owners, refresh the gauges
    and budget state. Rate-limited unless `force`; returns the report
    dict (None when rate-limited or jax is unusable). With `top`, the
    report carries the TOP_N largest buffers ranked."""
    now = time.monotonic()
    if not force and now - _last_sweep_t[0] < _MIN_INTERVAL_S:
        return _last[0]
    _last_sweep_t[0] = now
    try:
        import jax

        live = jax.live_arrays()
    except Exception:
        return None
    owned = _owned_ids()
    owners: Dict[str, int] = {}
    counts: Dict[str, int] = {}
    total = nbufs = 0
    top_rows: List[Dict[str, Any]] = []
    for a in live:
        nb = int(getattr(a, "nbytes", 0))
        owner = owned.get(id(a), "other")
        owners[owner] = owners.get(owner, 0) + nb
        counts[owner] = counts.get(owner, 0) + 1
        total += nb
        nbufs += 1
        if top:
            top_rows.append({
                "owner": owner, "nbytes": nb,
                "shape": list(getattr(a, "shape", ()) or ()),
                "dtype": str(getattr(a, "dtype", "?"))})
    # byte-providers: rows whose bytes live inside arrays counted
    # above (prefix_cache ⊂ kv_pool) — attributed, never re-totalled
    with _lock:
        bprovs = list(_bytes_providers.values())
    for owner, fn in bprovs:
        try:
            nb, cnt = fn()
        except Exception:  # lint-exempt:swallow: a dead provider (engine stopped mid-sweep) skips one sweep
            continue
        owners[owner] = owners.get(owner, 0) + int(nb)
        counts[owner] = counts.get(owner, 0) + int(cnt)
    exec_bytes = n_exec = 0
    if _exec_provider[0] is not None:
        try:
            exec_bytes, n_exec = _exec_provider[0]()
        except Exception:  # lint-exempt:swallow: executable introspection is optional
            pass
    if total > _watermark[0]:
        _watermark[0] = float(total)
    for owner in set(owners) | {"kv_pool", "params", "optimizer",
                                "other"}:
        HBM_BYTES.set(owners.get(owner, 0), owner=owner)
        HBM_BUFFERS.set(counts.get(owner, 0), owner=owner)
    HBM_WATERMARK.set_max(total)
    EXECUTABLE_BYTES.set(exec_bytes)
    # keep the PR 2 totals in lockstep with the attributed sweep
    from . import telemetry as _telemetry

    _telemetry.record_device_memory(total, nbufs)
    budget = budget_bytes()
    HBM_BUDGET.set(budget or 0)
    _check_budget(total, budget)
    rep: Dict[str, Any] = {
        "total_bytes": total, "buffers": nbufs,
        "owners": dict(sorted(owners.items(),
                              key=lambda kv: -kv[1])),
        "watermark_bytes": int(_watermark[0]),
        "budget_bytes": budget,
        "budget_state": _budget_state[0],
        "executable_bytes": int(exec_bytes),
        "executables": int(n_exec),
    }
    if top:
        top_rows.sort(key=lambda r: -r["nbytes"])
        rep["top"] = top_rows[:TOP_N]
    _last[0] = {k: v for k, v in rep.items() if k != "top"}
    return rep


def _check_budget(total: int, budget: Optional[int]):
    if not budget:
        _budget_state[0] = "ok"
        return
    frac = total / budget
    state = "error" if frac >= 1.0 else \
        "warn" if frac >= WARN_FRACTION else "ok"
    prev = _budget_state[0]
    if state == prev:
        return
    _budget_state[0] = state
    if state == "ok":
        return  # recovery: gauge readers see it; no log line needed
    word = "exceeded" if state == "error" else "nearly exhausted"
    msg = (f"HBM budget {word}: {total} live bytes vs budget {budget} "
           f"({frac:.0%})")
    (log.error if state == "error" else log.warning)("%s", msg)
    _events.emit("hbm_budget", level=state, total_bytes=int(total),
                 budget_bytes=int(budget), fraction=round(frac, 4))


def report(top: bool = True) -> Optional[Dict[str, Any]]:
    """Fresh forced sweep with the ranked buffer list."""
    return sweep(force=True, top=top)


def last_report() -> Optional[Dict[str, Any]]:
    return _last[0]


def status_block() -> Dict[str, Any]:
    """The /v1/status `memory` block: per-owner bytes, watermark,
    budget. Sweeps through the internal rate limit, so a status poll
    is a dict copy in the common case and a live walk at most once a
    second."""
    rep = sweep(force=False)
    if rep is None:
        rep = _last[0] or {"total_bytes": 0, "buffers": 0, "owners": {},
                           "watermark_bytes": int(_watermark[0]),
                           "budget_bytes": budget_bytes(),
                           "budget_state": _budget_state[0],
                           "executable_bytes": 0, "executables": 0}
    return dict(rep)


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                "Out of memory", "out of memory", "OOM")


def is_oom(exc: BaseException) -> bool:
    """True for device allocation failures: jax surfaces them as
    XlaRuntimeError with a RESOURCE_EXHAUSTED status (message text is
    the stable part of that contract across jax versions)."""
    if isinstance(exc, MemoryError):
        return True
    s = f"{type(exc).__name__}: {exc}"
    return any(m in s for m in _OOM_MARKERS)


def _format_report(rep: Dict[str, Any]) -> str:
    lines = [f"  total {rep['total_bytes']} bytes in "
             f"{rep['buffers']} buffers; watermark "
             f"{rep['watermark_bytes']}; budget "
             f"{rep['budget_bytes'] or 'none'}; executables "
             f"{rep['executable_bytes']} bytes"]
    for owner, nb in rep["owners"].items():
        pct = 100.0 * nb / max(1, rep["total_bytes"])
        lines.append(f"  {owner:<12s} {nb:>16d} bytes  {pct:5.1f}%")
    for row in rep.get("top", ()):
        lines.append(f"    {row['owner']:<10s} {row['nbytes']:>14d}  "
                     f"{row['dtype']} {row['shape']}")
    return "\n".join(lines)


def maybe_handle_oom(kind: str, exc: BaseException) -> bool:
    """If `exc` is a device OOM: count it, force an attributed sweep,
    log the ranked per-owner report and emit an `oom` event. The caller
    re-raises either way; returns whether it was handled."""
    if not is_oom(exc):
        return False
    OOMS.inc(kind=kind)
    rep = sweep(force=True, top=True)
    fields: Dict[str, Any] = {"dispatch_kind": kind,
                              "error": str(exc)[:300]}
    if rep is not None:
        log.error("RESOURCE_EXHAUSTED on dispatch kind=%s — live-buffer "
                  "forensics:\n%s", kind, _format_report(rep))
        fields.update(
            total_bytes=rep["total_bytes"], buffers=rep["buffers"],
            owners=rep["owners"],
            watermark_bytes=rep["watermark_bytes"],
            budget_bytes=rep["budget_bytes"],
            top=[{"owner": r["owner"], "nbytes": r["nbytes"],
                  "shape": r["shape"], "dtype": r["dtype"]}
                 for r in rep.get("top", ())[:5]])
    else:
        log.error("RESOURCE_EXHAUSTED on dispatch kind=%s (live-array "
                  "walk unavailable): %s", kind, exc)
    _events.emit("oom", **fields)
    return True


@contextlib.contextmanager
def oom_guard(kind: str):
    """Wrap a dispatch path: a RESOURCE_EXHAUSTED escaping the body is
    dumped as forensics (ranked owner report + `oom` event) and
    re-raised unchanged."""
    try:
        yield
    except BaseException as e:
        maybe_handle_oom(kind, e)
        raise
