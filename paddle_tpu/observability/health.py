"""Tensor-health layer: NaN/Inf and out-of-range detection on the values
the framework already has in hand.

Reference analogue: FLAGS_check_nan_inf (platform/flags.cc:44) +
debugger.py — the reference scans every op output when the flag is on.
Here the scan sites are the framework's natural observation points
(executor fetches and written states, trainer losses, SPMD fetches, the
optimizer's gradient global-norm), and an anomaly does three things:
increments `paddle_tpu_health_anomalies_total{kind,site}`, appends an
`anomaly` event to the JSONL event log (events.py), and — depending on
the level — warns or raises with the offending variable names.

Env gating (re-read on every call so tests can monkeypatch; the common
"unset" case is one dict lookup, so the disabled hot path stays free):

  PADDLE_TPU_CHECK_NUMERICS   0 = off (default)
                              1 = count + log + warn, training continues
                              2 = count + log + raise NumericsError
  PADDLE_TPU_HEALTH_MAX_ABS   optional float; finite values with
                              |x| > threshold count as kind="overrange"
                              (catches divergence BEFORE it hits Inf)

`status()` feeds the /healthz HTTP route: "ok" until the first anomaly
since process start (or `reset()`), then "degraded" with the last
anomaly attached.

Imports: stdlib + numpy only — no jax. Callers hand over host-readable
arrays (jax arrays cross via __array__, which blocks on the transfer;
that cost is only paid when checking is enabled).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import events as _events
from . import metrics as _m

__all__ = ["NumericsError", "check_level", "max_abs", "check_numerics",
           "record_grad_global_norm", "status", "anomaly_count", "reset",
           "introspection_enabled", "add_anomaly_listener",
           "remove_anomaly_listener"]

_log = logging.getLogger("paddle_tpu.health")

ANOMALIES = _m.counter(
    "paddle_tpu_health_anomalies_total",
    "Tensor-health anomalies (kind=nan|inf|overrange) by observation "
    "site (executor_fetch|executor_state|trainer_loss|spmd_fetch|"
    "optimizer_grad)", labelnames=("kind", "site"))
CHECKS = _m.counter(
    "paddle_tpu_health_checks_total",
    "check_numerics sweeps performed", labelnames=("site",))
GRAD_GLOBAL_NORM = _m.gauge(
    "paddle_tpu_health_grad_global_norm",
    "Global L2 norm of the last optimizer gradient set")
LAST_ANOMALY_TS = _m.gauge(
    "paddle_tpu_health_last_anomaly_ts",
    "Unix time of the most recent anomaly (0 = none since start)")


class NumericsError(RuntimeError):
    """Raised at PADDLE_TPU_CHECK_NUMERICS=2 (or FLAGS_check_nan_inf).
    Subclasses RuntimeError so legacy `pytest.raises(RuntimeError)`
    callers of the FLAGS path keep working."""

    def __init__(self, site: str, anomalies: List[Dict[str, Any]]):
        self.site = site
        self.anomalies = anomalies
        names = ", ".join(
            f"'{a['var']}' ({a['kind']})" for a in anomalies)
        super().__init__(
            f"check_numerics[{site}]: NaN/Inf or out-of-range values in "
            f"{names}")


def check_level() -> int:
    """0 = off, 1 = warn, 2 = raise. Malformed env reads as 0 — a typo
    in a launcher must not change training semantics."""
    raw = os.environ.get("PADDLE_TPU_CHECK_NUMERICS")
    if not raw:
        return 0
    try:
        return max(0, min(2, int(raw)))
    except ValueError:
        return 0


def max_abs() -> Optional[float]:
    raw = os.environ.get("PADDLE_TPU_HEALTH_MAX_ABS")
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def introspection_enabled() -> bool:
    """Whether the optional per-step introspection extras (device-buffer
    byte gauges) should run. Any observability env opt-in counts: if the
    user wired up scraping, dumping, event logging, or checking, they
    want the gauges; with nothing set, the hot path skips the work."""
    return bool(check_level()
                or os.environ.get("PADDLE_TPU_METRICS_DIR")
                or os.environ.get("PADDLE_TPU_METRICS_PORT")
                or os.environ.get("PADDLE_TPU_EVENT_LOG"))


# -- anomaly state (feeds /healthz) -----------------------------------------

_state_lock = threading.Lock()
_anomaly_count = 0
_last_anomaly: Optional[Dict[str, Any]] = None
_listeners: List[Any] = []


def add_anomaly_listener(fn):
    """Register `fn(event_dict)` to be called for every recorded
    anomaly — the hook recovery policies (resilience/policy.py) use to
    act on warn-level (level 1) anomalies that never raise. Listener
    exceptions are swallowed with a log line: a broken policy hook must
    not turn a warning into a crash."""
    with _state_lock:
        if fn not in _listeners:
            _listeners.append(fn)


def remove_anomaly_listener(fn):
    with _state_lock:
        try:
            _listeners.remove(fn)
        except ValueError:
            pass


def _classify(arr) -> List[Tuple[str, int]]:
    """(kind, bad-element-count) pairs for one float array."""
    import numpy as np

    out = []
    n_nan = int(np.isnan(arr).sum())
    if n_nan:
        out.append(("nan", n_nan))
    n_inf = int(np.isinf(arr).sum())
    if n_inf:
        out.append(("inf", n_inf))
    thresh = max_abs()
    if thresh is not None:
        # NaN comparisons are already False, so only Inf (|inf| > thresh
        # is True) needs subtracting to isolate finite overrange elements
        with np.errstate(invalid="ignore"):
            n_over = int((np.abs(arr) > thresh).sum()) - n_inf
        if n_over > 0:
            out.append(("overrange", n_over))
    return out


def check_numerics(site: str, named_values: Iterable[Tuple[str, Any]],
                   level: Optional[int] = None,
                   step: Optional[int] = None) -> List[Dict[str, Any]]:
    """Scan (name, array) pairs for NaN/Inf/out-of-range floats.

    Non-float and None values are skipped. Each offending variable
    yields one anomaly record per kind; all are counted and logged, then
    the batch warns (level 1) or raises NumericsError (level 2). Returns
    the anomaly records (empty when clean). `level` defaults to the env
    level — callers that force a raise (FLAGS_check_nan_inf) pass 2."""
    import numpy as np

    if level is None:
        level = check_level()
    if level <= 0:
        return []
    CHECKS.inc(site=site)
    anomalies: List[Dict[str, Any]] = []
    for name, val in named_values:
        if val is None:
            continue
        try:
            arr = np.asarray(val)
        except (TypeError, ValueError):
            continue
        if not np.issubdtype(arr.dtype, np.floating):
            # ml_dtypes floats (bfloat16/float8_*, the dominant TPU
            # training dtypes) are NOT np.floating subtypes; they must
            # not slip past the scan — upcast preserves NaN/Inf
            if "float" not in arr.dtype.name:
                continue
            arr = arr.astype(np.float32)
        for kind, n_bad in _classify(arr):
            anomalies.append({"var": str(name), "kind": kind,
                              "bad": n_bad, "size": int(arr.size)})
    if anomalies:
        _record_anomalies(site, anomalies, step=step)
        if level >= 2:
            raise NumericsError(site, anomalies)
        _log.warning(
            "check_numerics[%s]: %s", site,
            "; ".join(f"{a['var']}: {a['bad']}/{a['size']} {a['kind']}"
                      for a in anomalies))
    return anomalies


def _record_anomalies(site: str, anomalies: List[Dict[str, Any]],
                      step: Optional[int] = None):
    global _anomaly_count, _last_anomaly
    now = time.time()
    for a in anomalies:
        ANOMALIES.inc(kind=a["kind"], site=site)
        # the event's "kind" slot is the event type; the numeric kind
        # (nan|inf|overrange) travels as "anomaly"
        ev_fields = dict(site=site, var=a["var"], anomaly=a["kind"],
                         bad=a["bad"], size=a["size"])
        if step is not None:
            ev_fields["step"] = int(step)
        ev = _events.emit("anomaly", **ev_fields)
        with _state_lock:
            _anomaly_count += 1
            _last_anomaly = ev
            listeners = list(_listeners)
        for fn in listeners:  # outside the lock: a listener may read
            # health state (anomaly_count) without deadlocking
            try:
                fn(ev)
            except Exception:
                _log.exception("anomaly listener %r failed", fn)
    LAST_ANOMALY_TS.set(now)


def record_grad_global_norm(norm: float, site: str = "optimizer_grad",
                            n_params: int = 0,
                            level: Optional[int] = None):
    """Gauge the optimizer's gradient global L2 norm and treat a
    non-finite norm as an anomaly at `site` (a single NaN gradient
    element poisons the whole norm, so this one scalar covers every
    parameter's gradient)."""
    import math

    GRAD_GLOBAL_NORM.set(norm)
    if level is None:
        level = check_level()
    if level <= 0 or math.isfinite(norm):
        return
    kind = "nan" if math.isnan(norm) else "inf"
    anomalies = [{"var": "grad_global_norm", "kind": kind,
                  "bad": 1, "size": max(1, int(n_params))}]
    _record_anomalies(site, anomalies)
    if level >= 2:
        raise NumericsError(site, anomalies)
    _log.warning("check_numerics[%s]: gradient global norm is %s",
                 site, norm)


def anomaly_count() -> int:
    with _state_lock:
        return _anomaly_count


def status() -> Dict[str, Any]:
    """/healthz payload: ok until the first anomaly since start/reset()."""
    with _state_lock:
        degraded = _anomaly_count > 0
        out: Dict[str, Any] = {
            "status": "degraded" if degraded else "ok",
            "anomalies": _anomaly_count,
            "check_numerics": check_level(),
        }
        if _last_anomaly is not None:
            out["last_anomaly"] = dict(_last_anomaly)
    return out


def reset():
    """Clear the degraded state (test hygiene / operator acknowledge).
    Registry counters are left alone — they are cumulative by design."""
    global _anomaly_count, _last_anomaly
    with _state_lock:
        _anomaly_count = 0
        _last_anomaly = None
    LAST_ANOMALY_TS.set(0)
