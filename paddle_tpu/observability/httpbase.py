"""Shared stdlib-HTTP plumbing for in-process daemon servers.

Two servers live inside a paddle_tpu process: the observability
endpoint (`observability/httpd.py`, /metrics /healthz /events) and the
inference frontend (`serving/httpd.py`, /v1/predict /v1/status). Both
need the same lifecycle discipline — silent request logging, a locked
idempotent start that returns the bound port, failed-bind caching so an
env-gated hot path never retries the bind syscall every step, an
idempotent stop, and atexit cleanup — so that discipline lives here
once instead of being copy-drifted per server.

Stdlib-only by contract: this module is imported by the telemetry hot
path before the rest of the package finishes initializing.
"""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

__all__ = ["CLIENT_GONE", "QuietHandler", "HTTPServerHandle"]

# A scraper/client hanging up mid-reply is routine, not an error;
# handlers wrap their do_* bodies in `except CLIENT_GONE: pass`.
CLIENT_GONE = (BrokenPipeError, ConnectionResetError)


class QuietHandler(BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler that never writes to stderr and replies
    with explicit Content-Length (scrapes every few seconds must not
    spam logs, and chunked replies confuse minimal clients)."""

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def _reply(self, code: int, content_type: str, body: str,
               extra_headers=None):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)


class _DeepBacklogServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a deep listen backlog: socketserver's
    default of 5 drops SYNs under a concurrent-connect burst, turning
    overload into ~1s TCP retransmit stalls for EVERY caller — before
    QoS admission (which can only order connections the kernel
    accepted) gets a say."""

    request_queue_size = 128


class HTTPServerHandle:
    """Lifecycle for one ThreadingHTTPServer daemon thread.

    `start()` is idempotent (a second call returns the already-bound
    port), `stop()` is idempotent and joins the serve thread, and
    `maybe_start()` implements env-gated startup with failed-bind
    caching for callers on a hot path: a port that was taken once is
    not re-bound every step until `stop()` clears the marker.

    Binds 127.0.0.1 by default (overridable via `host_env`) — exposing
    process internals on all interfaces is an operator decision, not a
    default.
    """

    def __init__(self, handler_cls, thread_name: str,
                 port_env: Optional[str] = None,
                 host_env: Optional[str] = None,
                 default_host: str = "127.0.0.1"):
        self._handler_cls = handler_cls
        self._thread_name = thread_name
        self._port_env = port_env
        self._host_env = host_env
        self._default_host = default_host
        # This module is imported (and the observability handle
        # INSTANTIATED) while the package is still bootstrapping, so the
        # sanitizer factory is best-effort AND gated on the raw env var:
        # at level 0 (the default) nothing beyond stdlib is imported,
        # and during early init a failing analysis import degrades to
        # the raw primitive (the stdlib-only contract holds either way).
        self._lock = threading.Lock()
        if os.environ.get("PADDLE_TPU_LOCKCHECK", "0") not in ("", "0"):
            try:
                from ..analysis import lockcheck as _lockcheck

                self._lock = _lockcheck.Lock(
                    "observability.httpbase.HTTPServerHandle._lock")
            except ImportError:  # mid-bootstrap: plain primitive stays
                pass  # lint-exempt:swallow: best-effort instrumentation
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._atexit_registered = False
        self._start_failed = False

    def port(self) -> Optional[int]:
        """Bound port of the running server, or None when none is up."""
        with self._lock:
            if self._server is None:
                return None
            return self._server.server_address[1]

    def start(self, port: int = 0, host: Optional[str] = None) -> int:
        """Start the daemon serving thread (idempotent: a second call
        returns the already-bound port). port=0 binds an ephemeral port.
        Returns the actual bound port."""
        with self._lock:
            if self._server is not None:
                return self._server.server_address[1]
            if host is None and self._host_env:
                host = os.environ.get(self._host_env)
            host = host or self._default_host
            srv = _DeepBacklogServer((host, int(port)),
                                     self._handler_cls)
            srv.daemon_threads = True
            t = threading.Thread(target=srv.serve_forever,
                                 name=self._thread_name, daemon=True)
            t.start()
            self._server, self._thread = srv, t
            if not self._atexit_registered:
                import atexit

                atexit.register(self.stop)
                self._atexit_registered = True
            return srv.server_address[1]

    def maybe_start(self) -> bool:
        """Start the server iff `port_env` is set in the environment and
        none is running. Safe on a hot path: the unset case is a single
        env dict lookup, and a failed bind is remembered rather than
        retried every call."""
        if not self._port_env:
            return False
        raw = os.environ.get(self._port_env)
        if not raw:
            return False
        with self._lock:
            if self._server is not None:
                return True
            if self._start_failed:
                return False  # port was taken once; don't re-bind per step
        try:
            port = int(raw)
        except ValueError:
            return False  # malformed env must not kill the hot path
        if port < 0:
            return False
        try:
            self.start(port)
        except OSError:
            self._start_failed = True  # cleared by stop()
            return False  # port taken: keep running, serving is best-effort
        return True

    def stop(self):
        """Shut the server down and join its thread; idempotent, and
        clears the failed-bind marker so a later start can retry. Also
        unregisters the atexit hook — per-instance handles (one per
        serving.Server) must not pin stopped servers in memory for the
        process lifetime."""
        with self._lock:
            srv, self._server = self._server, None
            t, self._thread = self._thread, None
            self._start_failed = False
            if self._atexit_registered:
                import atexit

                atexit.unregister(self.stop)
                self._atexit_registered = False
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if t is not None and t.is_alive():
            t.join(timeout=5)
