"""Per-device-kind peak-throughput table — the ONE MFU denominator.

Before this module, the v5e peak lived hardcoded in three places
(bench.py PEAK_FLOPS, tools/rn50_bytes_table.py PEAK_TF/PEAK_BW,
tools/rn50_roofline.py) and a fourth consumer (the live
`paddle_tpu_mfu` gauge, observability/perfwatch.py) was about to add
one more. Bench-time MFU and serve-time MFU must divide by the SAME
number or the acceptance comparison between them is meaningless, so
the table lives here and everything imports it.

Numbers are public per-chip peak dense bf16 matmul throughput, HBM
bandwidth and capacity. `ici_bytes_per_s` is a one-direction aggregate
inter-chip figure used only for the collective-time ESTIMATE in the
step-time breakdown — it is labeled an estimate everywhere it
surfaces.

Stdlib-only by contract: perfwatch (imported by core/executor.py at
module load) pulls this in, and tools/obsdump.py loads observability
modules standalone by file path.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

__all__ = ["DevicePeak", "PEAKS", "DEFAULT_PEAK", "PLATFORM_PEAK_FLOPS",
           "lookup", "peak_flops", "platform_peak_flops"]


class DevicePeak(NamedTuple):
    """Peak per-chip figures. flops is dense bf16 (the training/serving
    number every MFU in this repo is quoted against)."""
    flops: float             # peak bf16 matmul FLOP/s per chip
    hbm_bytes_per_s: float   # HBM bandwidth
    hbm_bytes: float         # HBM capacity
    ici_bytes_per_s: float   # approx one-direction inter-chip aggregate


# Keyed by a lowercase substring of jax's device_kind ("TPU v5 lite",
# "TPU v4", ...). Order matters: first match wins, so more specific
# kinds precede generic ones.
PEAKS = (
    ("v5 lite", DevicePeak(197e12, 819e9, 16e9, 186e9)),   # v5e
    ("v5e", DevicePeak(197e12, 819e9, 16e9, 186e9)),
    ("v5p", DevicePeak(459e12, 2765e9, 95e9, 600e9)),
    ("v6 lite", DevicePeak(918e12, 1640e9, 32e9, 448e9)),  # v6e / Trillium
    ("v6e", DevicePeak(918e12, 1640e9, 32e9, 448e9)),
    ("v4", DevicePeak(275e12, 1228e9, 32e9, 268e9)),
    ("v3", DevicePeak(123e12, 900e9, 32e9, 70e9)),
    ("v2", DevicePeak(45e12, 700e9, 16e9, 62e9)),
)

# Unknown hardware (CPU test rigs, emulators): a deliberately generous
# 1 TF/s strawman so MFU stays finite and obviously-not-a-TPU numbers
# read as such instead of flattering anyone.
DEFAULT_PEAK = DevicePeak(1e12, 100e9, 8e9, 10e9)

# bench.py's historical platform-level map (it resolves by jax platform
# string before any device_kind is known). tpu maps to the v5e figure —
# the chip every BASELINE.json target is quoted for.
PLATFORM_PEAK_FLOPS = {"tpu": 197e12, "cpu": 1e12, "gpu": 100e12}


def lookup(device_kind: Optional[str]) -> DevicePeak:
    """Peak figures for a jax device_kind string (case-insensitive
    substring match); DEFAULT_PEAK when unknown."""
    dk = (device_kind or "").lower()
    for key, peak in PEAKS:
        if key in dk:
            return peak
    return DEFAULT_PEAK


def peak_flops(device_kind: Optional[str]) -> float:
    return lookup(device_kind).flops


def platform_peak_flops(platform: Optional[str]) -> float:
    """bench.py's denominator: jax platform string -> peak FLOP/s."""
    return PLATFORM_PEAK_FLOPS.get(platform or "", DEFAULT_PEAK.flops)
