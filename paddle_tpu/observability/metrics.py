"""Process-wide metrics registry: counters, gauges, histograms with labels.

Reference analogue: the profiler's event aggregation tables
(platform/profiler.cc DeviceTracer counters + the benchmark counters
scattered through operators/); here a single registry every subsystem
writes into, with JSON and Prometheus-text exposition so a serving
deployment can scrape the process and `tools/obsdump.py` can pretty-print
a dump offline.

Env gating (read lazily, so tests can monkeypatch):
  PADDLE_TPU_METRICS_DIR        if set, a daemon thread periodically writes
                                metrics.json + metrics.prom into this dir
  PADDLE_TPU_METRICS_INTERVAL_S dump period in seconds (default 60)
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_registry", "counter", "gauge", "histogram",
    "snapshot", "render_prometheus", "dump", "reset",
    "maybe_start_dump_thread", "stop_dump_thread",
    "exponential_buckets", "bucket_quantile",
]

# Seconds-scale latency buckets: 50us .. 60s covers a jit dispatch on a
# local backend through a cold compile on a tunneled one.
DEFAULT_BUCKETS = (
    50e-6, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
    5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def exponential_buckets(start: float, factor: float, count: int):
    """Prometheus-style bucket helper: `count` upper bounds starting at
    `start`, each `factor` x the previous — e.g. (1, 2, 8) → batch-size
    buckets 1,2,4,...,128 for the serving batch histogram."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    out, v = [], float(start)
    for _ in range(count):
        out.append(v)
        v *= factor
    return tuple(out)


def _label_key(labelnames: Sequence[str], labels: Dict[str, str]):
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared labelnames "
            f"{sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], object] = {}

    def _labels_dict(self, key: Tuple[str, ...]) -> Dict[str, str]:
        return dict(zip(self.labelnames, key))

    def clear(self):
        with self._lock:
            self._values.clear()


class Counter(_Metric):
    """Monotonically increasing count (steps, bytes, cache hits)."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0)

    def total(self) -> float:
        """Sum across every label set — the whole-process view a bench
        wants (e.g. host-blocked seconds regardless of site)."""
        with self._lock:
            return float(sum(self._values.values()))


class Gauge(_Metric):
    """Point-in-time value (cache entries, examples/sec, bubble fraction)."""

    kind = "gauge"

    def set(self, value: float, **labels):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1, **labels):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1, **labels):
        self.inc(-amount, **labels)

    def set_max(self, value: float, **labels):
        """Ratchet: keep the larger of the stored and offered value —
        the high-watermark pattern (HBM peak bytes) without a
        read-modify-write race at the call sites."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            cur = self._values.get(key, float("-inf"))
            if value > cur:
                self._values[key] = float(value)

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics): per label set
    keeps (count, sum, per-bucket counts); `le` buckets are cumulative at
    render time."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, **labels):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = {"count": 0, "sum": 0.0,
                      "buckets": [0] * len(self.buckets)}
                self._values[key] = st
            st["count"] += 1
            st["sum"] += float(value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["buckets"][i] += 1
                    break
            # values above the top bucket land only in +Inf (count)

    def time(self, **labels):
        """Context manager observing the with-block's wall seconds —
        the duration is recorded whether the block succeeds or raises
        (a failed save's latency is still a latency)."""
        import contextlib
        import time as _time

        @contextlib.contextmanager
        def _timer():
            t0 = _time.perf_counter()
            try:
                yield self
            finally:
                self.observe(_time.perf_counter() - t0, **labels)

        return _timer()

    def stats(self, **labels) -> Dict[str, float]:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                return {"count": 0, "sum": 0.0, "avg": 0.0}
            return {"count": st["count"], "sum": st["sum"],
                    "avg": st["sum"] / max(1, st["count"])}


class MetricsRegistry:
    """get-or-create registry; re-registration with a different kind or
    label set is a hard error (silent divergence would corrupt dumps)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # callables run before every snapshot/exposition: lazily-synced
        # sources (e.g. the span-ring drop counter, whose source module
        # is stdlib-only and cannot import this registry) publish here
        self._collect_hooks: list = []

    def add_collect_hook(self, fn):
        """Register `fn` to run at the top of every snapshot() (and so
        every /metrics render and file dump). Idempotent per callable."""
        with self._lock:
            if fn not in self._collect_hooks:
                self._collect_hooks.append(fn)

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric '{name}' already registered as "
                        f"{type(m).__name__}{m.labelnames}, requested "
                        f"{cls.__name__}{tuple(labelnames)}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def reset(self):
        """Zero every metric's values; registered metric OBJECTS survive
        (subsystems hold references to them)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()

    # -- exposition ----------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able view of every metric (the obsdump/dump format)."""
        out = {}
        with self._lock:
            hooks = list(self._collect_hooks)
        for fn in hooks:
            try:
                fn()
            except Exception:
                pass  # lint-exempt:swallow: a broken lazy source must not poison the whole exposition
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                series = []
                for key, val in sorted(m._values.items()):
                    entry = {"labels": m._labels_dict(key)}
                    if m.kind == "histogram":
                        entry.update(
                            count=val["count"], sum=val["sum"],
                            buckets=[
                                {"le": b, "count": c} for b, c in
                                zip(m.buckets, val["buckets"])])
                    else:
                        entry["value"] = val
                    series.append(entry)
            out[m.name] = {"type": m.kind, "help": m.help,
                           "series": series}
        return out

    def render_prometheus(self) -> str:
        return render_prometheus_snapshot(self.snapshot())

    def dump(self, directory: str) -> str:
        """Write metrics.json + metrics.prom into `directory` (tmp+rename
        so a scraper never reads a torn file). Returns the json path.
        Non-finite gauge values (a NaN grad-norm is a legitimate health
        reading) become strings — json.dumps would otherwise emit a bare
        `NaN` token that strict JSON parsers reject, breaking the whole
        dump exactly when divergence is being observed."""
        os.makedirs(directory, exist_ok=True)
        snap = self.snapshot()
        jpath = os.path.join(directory, "metrics.json")
        ppath = os.path.join(directory, "metrics.prom")
        for path, text in ((jpath, json.dumps(_json_safe(snap), indent=1)),
                           (ppath, render_prometheus_snapshot(snap))):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:  # atomic-exempt: tmp file, os.replace'd below
                f.write(text)
            os.replace(tmp, path)
        return jpath


def bucket_quantile(q: float, buckets, count: Optional[float] = None):
    """Histogram-bucket quantile estimate with linear interpolation
    inside the straddling bucket — THE one implementation shared by
    tools/obsdump.py, observability/aggregate.py, and the SLO engine
    (they all answer "what is p99 of this bucket table?" and must agree).

    `buckets` is a sequence of per-bin entries, each either a
    (le, count) pair or a {"le", "count"} dict (the snapshot() shape),
    with PER-BIN counts (not cumulative) and finite upper bounds in
    ascending order. `count` is the total observation count INCLUDING
    values above the top bucket (the implicit +Inf bin); when omitted it
    defaults to the sum of the given bins, i.e. no overflow.

    Returns None for an empty histogram. Quantiles that land in the
    +Inf overflow region clamp to the top finite bound — the honest
    answer "at least this much" rather than an invented extrapolation.
    """
    bins = []
    for b in buckets:
        if isinstance(b, dict):
            bins.append((float(b["le"]), float(b["count"])))
        else:
            bins.append((float(b[0]), float(b[1])))
    total = float(count) if count is not None \
        else sum(n for _, n in bins)
    if total <= 0:
        return None
    target = max(0.0, min(1.0, float(q))) * total
    prev_le, cum = 0.0, 0.0
    for le, n in bins:
        if cum + n >= target and n > 0:
            frac = (target - cum) / n
            return prev_le + frac * (le - prev_le)
        prev_le, cum = le, cum + n
    return prev_le  # target in the +Inf overflow: top finite bound


def _json_safe(obj):
    """Strict-JSON view of a snapshot: non-finite floats → strings
    ("nan"/"inf"/"-inf"), containers walked recursively."""
    if isinstance(obj, float):
        import math

        return obj if math.isfinite(obj) else repr(obj)
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_json_safe(v) for v in obj]
    return obj


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _sanitize_name(name: str) -> str:
    """Prometheus metric names are [a-zA-Z_:][a-zA-Z0-9_:]* — registry
    names that carry dots/dashes (or any other separator) are mapped to
    underscores at exposition time, so the JSON snapshot keeps the
    author's spelling while the text format stays parseable. Distinct
    raw names can collide after mapping; last-writer-wins per line is
    the accepted cost (don't name metrics `a.b` AND `a_b`)."""
    out = ["_" if not (c.isascii() and (c.isalnum() or c in "_:"))
           else c for c in name]
    if out and out[0].isdigit():
        out.insert(0, "_")
    return "".join(out)


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"'
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus_snapshot(snap: Dict[str, dict]) -> str:
    """Prometheus text exposition from a snapshot() dict. Module-level so
    tools/obsdump.py can render an offline metrics.json without importing
    the framework (and the jax stack behind it).

    Names are sanitized to the exposition charset (dots/dashes →
    underscores). Histograms render as three grouped families —
    `name_bucket` under the histogram TYPE, then `name_sum` and
    `name_count` each with their own # HELP/# TYPE (counter) block — so
    line-oriented scrapers that treat _sum/_count as standalone series
    still see typed, documented families."""
    lines = []
    for raw_name in sorted(snap):
        m = snap[raw_name]
        name = _sanitize_name(raw_name)
        if m["type"] == "histogram":
            if m.get("help"):
                lines.append(f"# HELP {name} {m['help']}")
            lines.append(f"# TYPE {name} histogram")
            for s in m["series"]:
                labels = s.get("labels", {})
                cum = 0
                for b in s["buckets"]:
                    cum += b["count"]
                    le = 'le="%g"' % b["le"]
                    lines.append(
                        f"{name}_bucket{_fmt_labels(labels, le)} {cum}")
                inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{_fmt_labels(labels, inf)} "
                    f"{s['count']}")
            lines.append(f"# HELP {name}_sum Sum of observations for "
                         f"{name}")
            lines.append(f"# TYPE {name}_sum counter")
            for s in m["series"]:
                lines.append(f"{name}_sum"
                             f"{_fmt_labels(s.get('labels', {}))} "
                             f"{s['sum']}")
            lines.append(f"# HELP {name}_count Count of observations "
                         f"for {name}")
            lines.append(f"# TYPE {name}_count counter")
            for s in m["series"]:
                lines.append(f"{name}_count"
                             f"{_fmt_labels(s.get('labels', {}))} "
                             f"{s['count']}")
        else:
            if m.get("help"):
                lines.append(f"# HELP {name} {m['help']}")
            lines.append(f"# TYPE {name} {m['type']}")
            for s in m["series"]:
                lines.append(f"{name}{_fmt_labels(s.get('labels', {}))} "
                             f"{s['value']}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Default registry + periodic env-gated dump
# ---------------------------------------------------------------------------

_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


def counter(name, help="", labelnames=()) -> Counter:
    return _default.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return _default.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
    return _default.histogram(name, help, labelnames, buckets)


def snapshot() -> Dict[str, dict]:
    return _default.snapshot()


def add_collect_hook(fn):
    _default.add_collect_hook(fn)


def render_prometheus() -> str:
    return _default.render_prometheus()


def dump(directory: Optional[str] = None) -> str:
    d = directory or os.environ.get("PADDLE_TPU_METRICS_DIR")
    if not d:
        raise ValueError("no directory given and PADDLE_TPU_METRICS_DIR "
                         "is unset")
    return _default.dump(d)


def reset():
    _default.reset()


_dump_thread: Optional[threading.Thread] = None
_dump_stop = threading.Event()
_dump_lock = threading.Lock()
_atexit_registered = False


def maybe_start_dump_thread() -> bool:
    """Start the periodic dump daemon iff PADDLE_TPU_METRICS_DIR is set
    and no dumper is running yet. Called from the telemetry hot-path
    helpers, so merely setting the env var before training is enough."""
    global _dump_thread, _atexit_registered
    d = os.environ.get("PADDLE_TPU_METRICS_DIR")
    if not d:
        return False
    with _dump_lock:
        if _dump_thread is not None and _dump_thread.is_alive():
            return True
        try:
            interval = float(os.environ.get(
                "PADDLE_TPU_METRICS_INTERVAL_S", "60"))
        except ValueError:
            interval = 60.0  # malformed env must not kill the hot path
        if interval <= 0:
            interval = 60.0  # 0/negative would busy-loop the dumper
        _dump_stop.clear()

        def loop():
            while not _dump_stop.wait(interval):
                try:
                    _default.dump(d)
                except OSError:
                    pass  # dir vanished mid-run; keep the trainer alive
            # final dump so short runs still leave a snapshot behind
            try:
                _default.dump(d)
            except OSError:
                pass

        _dump_thread = threading.Thread(
            target=loop, name="paddle-tpu-metrics-dump", daemon=True)
        _dump_thread.start()
        if not _atexit_registered:
            # daemon threads die silently at interpreter exit — without
            # this, a run shorter than the interval leaves no snapshot
            import atexit

            atexit.register(stop_dump_thread)
            _atexit_registered = True
        return True


def stop_dump_thread():
    global _dump_thread
    with _dump_lock:
        t, _dump_thread = _dump_thread, None
    if t is not None and t.is_alive():
        _dump_stop.set()
        t.join(timeout=5)
