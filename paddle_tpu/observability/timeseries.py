"""Metric time series: a background recorder turning the point-in-time
`MetricsRegistry` into durable, delta-encoded history on disk.

The registry (metrics.py) answers "what is the state NOW"; this module
answers "what happened over the last N seconds" — the raw material for
rates, windowed quantiles, fleet roll-ups (aggregate.py) and SLO
burn-rate alerting (slo.py). Each recording process periodically
snapshots the default registry and appends ONE JSONL record per
interval to a per-process segmented sink:

  {"ts": <wall s>, "pid": <pid>, "seq": n, "samples": [
     {"name": ..., "kind": "counter",   "labels": {...}, "delta": d},
     {"name": ..., "kind": "gauge",     "labels": {...}, "value": v},
     {"name": ..., "kind": "histogram", "labels": {...},
      "count_delta": c, "sum_delta": s, "bucket_deltas": [[le, d], ...]}
  ]}

Counters and histograms are DELTA-encoded against the previous sample
(zero-delta series and zero-delta bins are omitted), so a window sum
over records is exactly `increase()` and idle processes write near-empty
records. The first record of a recorder's life is marked
`"baseline": true` and carries gauges only: it primes the delta state
without attributing counts accrued BEFORE recording started to the
first interval. Gauges are re-emitted every record (last-wins point
reads need a value in every window). A counter/histogram that goes
backwards (process-internal reset) re-enters as `delta = current`,
Prometheus-rate style.

Sink discipline is PR 14's proven shape (tracing.py): per-process
`ts-<pid>-<rand>.jsonl` files published as atomic whole-file rewrites
via resilience/atomic.py so a concurrent reader never sees a torn line,
sealed at a fixed record count (amortized O(1) I/O per sample however
long the process lives), keep-N / total-bytes retention over THIS
process's sealed segments, and an atexit final sample + flush so a
process shorter than the interval still leaves history behind.

Env gating (default off; read by maybe_start_recorder):
  PADDLE_TPU_TS_DIR         sink directory; setting it turns recording on
  PADDLE_TPU_TS_INTERVAL_S  sample period in seconds (default 5)
  PADDLE_TPU_TS_KEEP        sealed segments to retain per process (16)
  PADDLE_TPU_TS_MAX_BYTES   total bytes across this process's segments
                            (0 = unlimited); oldest sealed deleted first
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from . import metrics as _metrics

__all__ = [
    "Recorder", "maybe_start_recorder", "stop_recorder",
    "current_recorder", "TS_DIR_ENV", "TS_INTERVAL_ENV",
]

TS_DIR_ENV = "PADDLE_TPU_TS_DIR"
TS_INTERVAL_ENV = "PADDLE_TPU_TS_INTERVAL_S"
TS_KEEP_ENV = "PADDLE_TPU_TS_KEEP"
TS_MAX_BYTES_ENV = "PADDLE_TPU_TS_MAX_BYTES"

DEFAULT_INTERVAL_S = 5.0
SEGMENT_SAMPLES = 240      # ~20 min of history per segment at 5s
KEEP_SEGMENTS = 16

_SAMPLES_TOTAL = _metrics.counter(
    "paddle_tpu_ts_samples_total",
    "Time-series records written by this process's recorder",
)


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Recorder:
    """Delta-encoding metrics recorder for one process. Construct with
    a sink directory, `start()` the background thread (or drive
    `sample_once()` by hand with an injected clock in tests), `stop()`
    to take a final sample and flush. Idempotent start/stop."""

    def __init__(self, directory: str, interval_s: float = DEFAULT_INTERVAL_S,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 segment_samples: int = SEGMENT_SAMPLES,
                 keep_segments: int = KEEP_SEGMENTS,
                 max_bytes: int = 0, clock=time.time):
        self.directory = directory
        self.interval_s = max(0.05, float(interval_s))
        self.registry = registry or _metrics.default_registry()
        self.segment_samples = max(1, int(segment_samples))
        self.keep_segments = max(1, int(keep_segments))
        self.max_bytes = max(0, int(max_bytes))
        self.clock = clock
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev: Dict[Tuple[str, Tuple], object] = {}
        self._seq = 0
        self._baselined = False
        self._lines: list = []       # current (unsealed) segment
        self._path = self._fresh_path()
        self._sealed: list = []      # this process's sealed segments

    def _fresh_path(self) -> str:
        return os.path.join(
            self.directory,
            f"ts-{os.getpid()}-{os.urandom(4).hex()}.jsonl")

    # -- delta encoding ------------------------------------------------

    def _diff(self, snap: Dict[str, dict], baseline: bool) -> list:
        samples = []
        for name in sorted(snap):
            m = snap[name]
            kind = m.get("type")
            for s in m.get("series", ()):
                labels = s.get("labels", {})
                key = (name, _series_key(labels))
                if kind == "gauge":
                    samples.append({"name": name, "kind": "gauge",
                                    "labels": labels,
                                    "value": s.get("value", 0.0)})
                elif kind == "counter":
                    cur = float(s.get("value", 0.0))
                    prev = self._prev.get(key)
                    self._prev[key] = cur
                    if baseline:
                        continue
                    delta = cur if (prev is None or cur < prev) \
                        else cur - prev
                    if delta:
                        samples.append({"name": name, "kind": "counter",
                                        "labels": labels, "delta": delta})
                elif kind == "histogram":
                    cur_c = int(s.get("count", 0))
                    cur_s = float(s.get("sum", 0.0))
                    bins = [(float(b["le"]), int(b["count"]))
                            for b in s.get("buckets", ())]
                    prev = self._prev.get(key)
                    self._prev[key] = (cur_c, cur_s, bins)
                    if baseline:
                        continue
                    if prev is None or cur_c < prev[0] \
                            or [le for le, _ in prev[2]] \
                            != [le for le, _ in bins]:
                        # new series or in-process reset: whole table
                        dc, ds = cur_c, cur_s
                        dbins = [(le, n) for le, n in bins if n]
                    else:
                        dc = cur_c - prev[0]
                        ds = cur_s - prev[1]
                        dbins = [(le, n - pn) for (le, n), (_, pn)
                                 in zip(bins, prev[2]) if n != pn]
                    if dc or dbins:
                        samples.append({
                            "name": name, "kind": "histogram",
                            "labels": labels, "count_delta": dc,
                            "sum_delta": ds,
                            "bucket_deltas": [[le, n] for le, n in dbins]})
        return samples

    # -- sink I/O ------------------------------------------------------

    def _write_locked(self) -> bool:
        from ..resilience.atomic import write_text

        try:
            os.makedirs(self.directory, exist_ok=True)
            write_text(self._path, "".join(self._lines))
            return True
        except OSError:
            return False  # full/vanished dir: keep buffering, retry next

    def _retain_locked(self):
        """Drop oldest sealed segments beyond keep-N / total-byte caps.
        Only THIS process's files are candidates — a shared fleet dir
        holds other pids' history this recorder must not collect."""
        while len(self._sealed) > self.keep_segments:
            self._unlink(self._sealed.pop(0))
        if not self.max_bytes:
            return
        sizes = []
        for p in self._sealed + [self._path]:
            try:
                sizes.append(os.path.getsize(p))
            except OSError:
                sizes.append(0)
        total = sum(sizes)
        while total > self.max_bytes and self._sealed:
            total -= sizes.pop(0)
            self._unlink(self._sealed.pop(0))

    @staticmethod
    def _unlink(path: str):
        try:
            os.unlink(path)
        except OSError:
            pass  # lint-exempt:swallow: already-gone segment is the goal state

    def sample_once(self, now: Optional[float] = None) -> int:
        """Snapshot → delta → append one record → publish the segment.
        Returns the number of metric samples in the record (gauges +
        nonzero deltas). Safe to call concurrently with the thread."""
        with self._lock:
            baseline = not self._baselined
            snap = self.registry.snapshot()
            samples = self._diff(snap, baseline)
            self._baselined = True
            rec = {"ts": self.clock() if now is None else now,
                   "pid": os.getpid(), "seq": self._seq,
                   "samples": samples}
            if baseline:
                rec["baseline"] = True
            self._seq += 1
            self._lines.append(
                json.dumps(_metrics._json_safe(rec)) + "\n")
            if self._write_locked() \
                    and len(self._lines) >= self.segment_samples:
                # sealed: the file on disk is complete; start fresh
                self._sealed.append(self._path)
                self._lines = []
                self._path = self._fresh_path()
                self._retain_locked()
            _SAMPLES_TOTAL.inc()
            return len(samples)

    # -- lifecycle -----------------------------------------------------

    def start(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()

            def loop():
                while not self._stop.wait(self.interval_s):
                    self.sample_once()
                # final sample so sub-interval processes still record
                self.sample_once()

            self._thread = threading.Thread(
                target=loop, name="paddle-tpu-ts-recorder", daemon=True)
            t = self._thread
        # synchronous baseline BEFORE the loop runs: delta state is
        # primed the moment start() returns, so a process shorter than
        # one interval still attributes everything after this point to
        # its final stop-time sample (instead of that sample being the
        # counter-less baseline)
        self.sample_once()
        t.start()

    def stop(self):
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            self._stop.set()
            t.join(timeout=5)
        else:
            # never started (or already joined): still flush a final
            # record so `with recorder: ...` style use leaves history
            self.sample_once()


# ---------------------------------------------------------------------------
# Env-gated module recorder (the telemetry hot-path helpers call this)
# ---------------------------------------------------------------------------

_recorder: Optional[Recorder] = None
_recorder_lock = threading.Lock()
_atexit_registered = False


def current_recorder() -> Optional[Recorder]:
    return _recorder


def _env_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, default))
    except ValueError:
        return default  # malformed env must not kill the hot path
    return v if v > 0 else default


def maybe_start_recorder() -> bool:
    """Start the background recorder iff PADDLE_TPU_TS_DIR is set and
    none is running yet — merely exporting the env var before boot is
    enough, same contract as the metrics dump thread and trace sink."""
    global _recorder, _atexit_registered
    d = os.environ.get(TS_DIR_ENV)
    if not d:
        return False
    with _recorder_lock:
        if _recorder is not None \
                and _recorder.directory == d \
                and _recorder._thread is not None \
                and _recorder._thread.is_alive():
            return True
        if _recorder is not None:
            _recorder.stop()  # env changed under us: reseat the sink
        _recorder = Recorder(
            d,
            interval_s=_env_float(TS_INTERVAL_ENV, DEFAULT_INTERVAL_S),
            keep_segments=int(_env_float(TS_KEEP_ENV, KEEP_SEGMENTS)),
            max_bytes=int(_env_float(TS_MAX_BYTES_ENV, 0)))
        _recorder.start()
        if not _atexit_registered:
            # daemon thread dies silently at interpreter exit; without
            # this a run shorter than the interval records nothing
            atexit.register(stop_recorder)
            _atexit_registered = True
        return True


def stop_recorder():
    """Final sample + flush + join. Idempotent; atexit-registered."""
    global _recorder
    with _recorder_lock:
        r, _recorder = _recorder, None
    if r is not None:
        r.stop()
