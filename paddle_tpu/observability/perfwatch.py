"""Live utilization attribution: windowed MFU + step-time breakdown.

bench.py computes MFU offline (flops-per-sample x samples/s / peak) and
prints it once; nothing answered "how fast is the hardware running RIGHT
NOW" for a live trainer or serving replica. This module is the live
counterpart: every hot dispatch path (Executor.run, SPMDRunner.run, the
decode engine's prefill/decode steps) records one `record_step` per
step, carrying the FLOPs its executable's cost_analysis() reported at
compile time (retained per signature by core/executor._JitDispatch).
A 60-second sliding window turns those into continuous gauges at
scrape/snapshot time:

  paddle_tpu_mfu{kind}            windowed FLOP/s / (n_devices x peak),
                                  peak from device_peaks.lookup() — the
                                  SAME denominator bench.py divides by
  paddle_tpu_flops_per_sec{kind}  the numerator, for dashboards that
                                  want absolute throughput
  paddle_tpu_steps_per_sec{kind}  windowed step rate
  paddle_tpu_tokens_per_sec_per_chip{kind}
                                  decode-path token throughput,
                                  chip-normalized (0 for token-free
                                  kinds)
  paddle_tpu_step_time_seconds_total{kind,component}
                                  cumulative step-time attribution:
                                  device | host_blocked | collective —
                                  device is wall minus the measured
                                  host-blocked wait minus the collective
                                  ESTIMATE (ring-allreduce payload over
                                  the device kind's ICI figure), so the
                                  three components sum to recorded wall
                                  time by construction

MFU decays toward zero when steps stop arriving (the window's elapsed
time keeps growing while its FLOPs stay fixed) — an idle replica reads
0, not its last busy number.

Stdlib-only by contract: core/executor.py imports this at module load,
and tools/obsdump.py loads observability modules standalone by file
path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from . import device_peaks as _peaks
from . import metrics as _m

__all__ = ["record_step", "snapshot", "mfu", "tokens_per_sec_per_chip",
           "estimate_collective_seconds", "reset", "WINDOW_S"]

WINDOW_S = 60.0

MFU = _m.gauge(
    "paddle_tpu_mfu",
    "Windowed model-FLOPs utilization by dispatch kind "
    "(step|chained|spmd|prefill|decode): cost_analysis() FLOPs summed "
    "over the last 60 s divided by elapsed time and the per-device-kind "
    "peak (observability/device_peaks.py — the same denominator "
    "bench.py uses). Decays to 0 when steps stop arriving",
    labelnames=("kind",))
FLOPS_PER_SEC = _m.gauge(
    "paddle_tpu_flops_per_sec",
    "Windowed achieved FLOP/s by dispatch kind (the paddle_tpu_mfu "
    "numerator before peak normalization)", labelnames=("kind",))
STEPS_PER_SEC = _m.gauge(
    "paddle_tpu_steps_per_sec",
    "Windowed step rate by dispatch kind", labelnames=("kind",))
TOKENS_PER_SEC = _m.gauge(
    "paddle_tpu_tokens_per_sec_per_chip",
    "Windowed decode-engine token throughput per chip by phase kind "
    "(prefill|decode); 0 for token-free kinds", labelnames=("kind",))
STEP_TIME = _m.counter(
    "paddle_tpu_step_time_seconds_total",
    "Cumulative per-step wall-time attribution by dispatch kind and "
    "component: device (compute, the residual), host_blocked (measured "
    "host wait on device results), collective (ring-allreduce ESTIMATE "
    "from payload bytes over the device kind's ICI bandwidth)",
    labelnames=("kind", "component"))


class _Window:
    """Per-kind sliding window of step records. Mutated only under the
    module lock; entries are (t, seconds, flops, tokens)."""

    __slots__ = ("entries", "device_kind", "n_devices")

    def __init__(self):
        self.entries: "deque" = deque()
        self.device_kind: Optional[str] = None
        self.n_devices = 1

    def prune(self, now: float):
        horizon = now - WINDOW_S
        while self.entries and self.entries[0][0] < horizon:
            self.entries.popleft()


_lock = threading.Lock()
_windows: Dict[str, _Window] = {}


def estimate_collective_seconds(device_kind: Optional[str],
                                n_devices: int, payload_bytes: int,
                                n_collectives: int) -> float:
    """Ring-allreduce lower-bound ESTIMATE of a step's collective time:
    2(n-1)/n x payload over the device kind's one-direction ICI figure.
    Zero when there is nothing to estimate (single device, no
    collective ops, unknown payload) — an estimate that cannot be
    grounded must not eat into the device-compute residual."""
    if n_devices <= 1 or n_collectives <= 0 or payload_bytes <= 0:
        return 0.0
    bw = _peaks.lookup(device_kind).ici_bytes_per_s
    if bw <= 0:
        return 0.0
    return 2.0 * (n_devices - 1) / n_devices * payload_bytes / bw


def record_step(kind: str, seconds: float, *,
                flops: Optional[float] = None, tokens: int = 0,
                host_blocked: float = 0.0,
                collective_seconds: float = 0.0,
                device_kind: Optional[str] = None, n_devices: int = 1,
                now: Optional[float] = None):
    """One completed hot-path step. `seconds` is the step's wall time;
    `host_blocked` the measured portion spent waiting on device
    results; `collective_seconds` the caller's collective estimate
    (see estimate_collective_seconds). `now` is injectable for tests;
    production callers leave it None (time.monotonic())."""
    if seconds < 0:
        return
    t = time.monotonic() if now is None else float(now)
    host = min(max(0.0, host_blocked), seconds)
    coll = min(max(0.0, collective_seconds), seconds - host)
    device = seconds - host - coll
    if device > 0:
        STEP_TIME.inc(device, kind=kind, component="device")
    if host > 0:
        STEP_TIME.inc(host, kind=kind, component="host_blocked")
    if coll > 0:
        STEP_TIME.inc(coll, kind=kind, component="collective")
    with _lock:
        w = _windows.get(kind)
        if w is None:
            w = _windows[kind] = _Window()
        if device_kind:
            w.device_kind = device_kind
        w.n_devices = max(1, int(n_devices))
        w.entries.append((t, float(seconds),
                          float(flops) if flops else 0.0, int(tokens)))
        w.prune(t)


def snapshot(now: Optional[float] = None) -> Dict[str, Dict]:
    """Windowed utilization per kind. Elapsed time is max(window span
    to `now`, busy seconds) so a single step still yields a finite
    rate and an idle tail decays the gauges."""
    t = time.monotonic() if now is None else float(now)
    out: Dict[str, Dict] = {}
    with _lock:
        for kind, w in _windows.items():
            w.prune(t)
            if not w.entries:
                out[kind] = {"mfu": 0.0, "flops_per_sec": 0.0,
                             "steps_per_sec": 0.0,
                             "tokens_per_sec_per_chip": 0.0,
                             "steps": 0, "n_devices": w.n_devices,
                             "device_kind": w.device_kind,
                             "peak_flops": _peaks.peak_flops(
                                 w.device_kind)}
                continue
            busy = sum(e[1] for e in w.entries)
            flops = sum(e[2] for e in w.entries)
            tokens = sum(e[3] for e in w.entries)
            elapsed = max(t - w.entries[0][0], busy, 1e-9)
            peak = _peaks.peak_flops(w.device_kind)
            fps = flops / elapsed
            out[kind] = {
                "mfu": fps / (w.n_devices * peak) if peak > 0 else 0.0,
                "flops_per_sec": fps,
                "steps_per_sec": len(w.entries) / elapsed,
                "tokens_per_sec_per_chip":
                    tokens / elapsed / w.n_devices,
                "steps": len(w.entries),
                "n_devices": w.n_devices,
                "device_kind": w.device_kind,
                "peak_flops": peak,
            }
    return out


def mfu(kind: str) -> float:
    return snapshot().get(kind, {}).get("mfu", 0.0)


def tokens_per_sec_per_chip(kind: str = "decode") -> float:
    return snapshot().get(kind, {}).get("tokens_per_sec_per_chip", 0.0)


def reset():
    """Drop all windows (tests)."""
    with _lock:
        _windows.clear()


def _publish():
    """Collect hook: refresh the gauges from the windows before every
    snapshot/exposition — the hot paths only append records, so an
    idle process still decays its MFU at scrape time."""
    for kind, st in snapshot().items():
        MFU.set(st["mfu"], kind=kind)
        FLOPS_PER_SEC.set(st["flops_per_sec"], kind=kind)
        STEPS_PER_SEC.set(st["steps_per_sec"], kind=kind)
        TOKENS_PER_SEC.set(st["tokens_per_sec_per_chip"], kind=kind)


_m.add_collect_hook(_publish)
