"""Unified span store + chrome-trace export + distributed trace context.

One process-wide span list replaces the profiler's ad-hoc `_host_spans`:
`profiler.RecordEvent` host spans (cat="host"), executor/trainer/SPMD step
telemetry (cat="step"), and any other subsystem annotation all land here,
and `export_trace` merges them with the jax.profiler device timeline
(the `*.trace.json.gz` chrome traces jax writes under
`plugins/profile/<run>/`) into ONE chrome://tracing / perfetto-loadable
JSON file — the role of the reference's profiler.proto + tools/timeline.py
converter.

Timestamps: span ts/dur are time.perf_counter() seconds (matching what
RecordEvent always recorded); exported values are microseconds. Device
events keep their own profiler epoch — perfetto renders them as separate
tracks, which is how the reference timeline showed host vs. CUPTI streams
too.

Distributed tracing (PROFILE.md §Distributed tracing): a `TraceContext`
(trace_id, span_id, parent_span_id, sampled — the W3C `traceparent` wire
format) rides a contextvar in-process, HTTP headers across the serving
tier (`begin_request`/`trace_headers`), and the PS RPC envelope
(ps/protocol.py TRACE_FIELD) across the parameter-server tier. Sampling
is head-based: the process that STARTS a trace rolls
`PADDLE_TPU_TRACE_SAMPLE` (0.0..1.0, default 0 = off) once; every
downstream hop honors the propagated flag, so a request is either traced
end-to-end or costs nothing anywhere. Sampled spans are tagged into the
in-memory ring (args trace_id/span_id/parent_span_id) AND persisted to a
per-process JSONL sink under `PADDLE_TPU_TRACE_DIR` (atomic whole-file
rewrites via resilience/atomic.py, so a concurrent reader never sees a
torn line); `tools/obsdump.py trace DIR --trace-id ID` reassembles the
cross-process tree. This module stays stdlib-only (obsdump imports it by
file path); resilience.atomic loads lazily inside the writers.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import contextvars
import glob
import gzip
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

__all__ = ["Span", "span", "record_span", "get_spans", "clear_spans",
           "dropped_spans", "save_spans", "export_trace",
           "merge_chrome_traces",
           "TraceContext", "parse_traceparent", "sample_rate",
           "start_trace", "current_trace", "current_trace_id",
           "activate", "begin_request", "trace_headers",
           "response_headers", "trace_span", "step_span",
           "record_span_ctx", "record_trace_span", "flush_trace_sink",
           "sink_path", "read_trace_dir", "build_trace_tree",
           "trace_summaries", "trace_records_to_chrome"]

# Bound host memory: a week-long trainer recording a span per step must
# not OOM the host. The store is a ring — the OLDEST spans are evicted
# (and counted in dropped_spans()), so profiling a late window of a long
# run still exports that window rather than stale day-one spans.
MAX_SPANS = 200_000


class Span(NamedTuple):
    name: str
    ts: float            # perf_counter seconds
    dur: float           # seconds
    cat: str             # "host" | "step" | subsystem-chosen
    tid: int             # recording thread ident
    args: Optional[Dict[str, Any]]


_lock = threading.Lock()
_spans: "collections.deque[Span]" = collections.deque()
_dropped = 0


def record_span(name: str, ts: float, dur: float, cat: str = "host",
                args: Optional[Dict[str, Any]] = None):
    global _dropped
    sp = Span(name, ts, dur, cat, threading.get_ident(), args)
    with _lock:
        _spans.append(sp)
        while len(_spans) > MAX_SPANS:
            _spans.popleft()
            _dropped += 1


# ---------------------------------------------------------------------------
# Distributed trace context (W3C traceparent)
# ---------------------------------------------------------------------------

TRACE_DIR_ENV = "PADDLE_TPU_TRACE_DIR"
TRACE_SAMPLE_ENV = "PADDLE_TPU_TRACE_SAMPLE"

# sampling decisions use a dedicated RNG so tests can seed it without
# perturbing anything else's randomness
_sample_rng = random.Random()


class TraceContext(NamedTuple):
    """One hop of a distributed trace: ids are lower-hex strings in the
    W3C trace-context widths (trace_id 32, span_id 16). `sampled` is the
    head-based decision made where the trace STARTED — downstream hops
    copy it from the wire instead of re-rolling, so one request is
    traced end-to-end or not at all."""

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    sampled: bool = False

    def header(self) -> str:
        """W3C `traceparent`: 00-<trace_id>-<span_id>-<flags>."""
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    def child(self) -> "TraceContext":
        """Fresh span id, this span as parent, same trace + decision."""
        return TraceContext(self.trace_id, _new_span_id(),
                            self.span_id, self.sampled)


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """Parse a `traceparent` header; None on anything malformed (an
    unparseable header means "start a fresh trace", never an error)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id.lower(), span_id.lower(), None,
                        bool(int(flags, 16) & 0x01))


def sample_rate() -> float:
    """Head-sampling probability from PADDLE_TPU_TRACE_SAMPLE (clamped
    to [0, 1]; unset/malformed = 0 = tracing off). Re-read per call so
    an operator (or the serve_bench overhead A/B) can flip it live."""
    raw = os.environ.get(TRACE_SAMPLE_ENV)
    if not raw:
        return 0.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 0.0


def start_trace(sampled: Optional[bool] = None) -> TraceContext:
    """Mint a new root context. sampled=None rolls `sample_rate()`
    once — the head-based decision every downstream hop inherits."""
    if sampled is None:
        rate = sample_rate()
        sampled = rate > 0.0 and _sample_rng.random() < rate
    return TraceContext(_new_trace_id(), _new_span_id(), None,
                        bool(sampled))


_current: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("paddle_tpu_trace", default=None)


def current_trace() -> Optional[TraceContext]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    """trace_id of the active SAMPLED context (None otherwise) — the
    event log's join key (events.py set_trace_provider)."""
    cur = _current.get()
    return cur.trace_id if cur is not None and cur.sampled else None


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """Make `ctx` the ambient context for the with-body (any thread)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def begin_request(headers) -> TraceContext:
    """Extract-or-start at a service edge: adopt the caller's
    `traceparent` (including its sampling decision) or mint a fresh
    root sampled by PADDLE_TPU_TRACE_SAMPLE. Always returns a context —
    the trace_id doubles as the X-Request-Id response header even for
    unsampled requests. `headers` is any .get()-able mapping (the
    stdlib handler's email.message.Message included)."""
    ctx = parse_traceparent(headers.get("traceparent")
                            if headers is not None else None)
    return ctx if ctx is not None else start_trace()


def trace_headers(ctx: Optional[TraceContext] = None) -> Dict[str, str]:
    """Outbound propagation headers for a downstream HTTP call ({} when
    no context is active). Unsampled contexts propagate too — the
    sampling decision was made at the head, and a downstream hop must
    not re-roll it."""
    cur = _current.get() if ctx is None else ctx
    if cur is None:
        return {}
    return {"traceparent": cur.header()}


def response_headers(ctx: Optional[TraceContext]) -> Dict[str, str]:
    """Reply headers every /v1/* response carries (SERVING.md §HTTP
    API): the request id for log correlation plus the traceparent so
    clients can read the ids + sampling decision back."""
    if ctx is None:
        return {}
    return {"X-Request-Id": ctx.trace_id, "traceparent": ctx.header()}


# -- per-process JSONL sink -------------------------------------------------

# The sink is SEGMENTED: each segment file is published as atomic
# whole-file rewrites (readers never see a torn line) and sealed once it
# reaches _SINK_SEGMENT_SPANS, after which a fresh trace-<pid>-<rand>
# file starts — so both the in-memory buffer and the per-flush rewrite
# cost stay bounded (amortized O(1) I/O per span) no matter how long a
# sampled process lives. read_trace_dir globs every segment.
_SINK_SEGMENT_SPANS = 4096
MAX_SINK_SPANS = 100_000   # backstop drop-oldest; unreachable with
# segmenting unless rolling keeps failing (unwritable dir)
_SINK_FLUSH_EVERY_S = 0.25
_SINK_FLUSH_EVERY_N = 256

_sink_lock = threading.Lock()        # buffer/bookkeeping access
_sink_flush_lock = threading.Lock()  # serializes writers: file content
# must never go backwards (an older snapshot landing after a newer one
# would silently drop the tail spans)
_sink_state = {"dir": None, "path": None, "pid": None,
               "lines": [], "flushed": 0, "last_flush": 0.0,
               "atexit": False}


def sink_path() -> Optional[str]:
    """Resolved sink file for THIS process, or None when
    PADDLE_TPU_TRACE_DIR is unset."""
    with _sink_lock:
        if _sink_state["dir"] != os.environ.get(TRACE_DIR_ENV) \
                or _sink_state["pid"] != os.getpid():
            return _sink_reset_locked()
        return _sink_state["path"]


def _sink_reset_locked() -> Optional[str]:
    d = os.environ.get(TRACE_DIR_ENV)
    _sink_state.update(dir=d, pid=os.getpid(), lines=[], flushed=0,
                       last_flush=0.0)
    _sink_state["path"] = None if not d else os.path.join(
        d, f"trace-{os.getpid()}-{os.urandom(4).hex()}.jsonl")
    return _sink_state["path"]


def _sink_append(rec: Dict[str, Any]):
    line = json.dumps(rec, default=str) + "\n"
    flush_now = roll_now = False
    with _sink_lock:
        if _sink_state["dir"] != os.environ.get(TRACE_DIR_ENV) \
                or _sink_state["pid"] != os.getpid():
            _sink_reset_locked()
        if _sink_state["path"] is None:
            return
        lines = _sink_state["lines"]
        lines.append(line)
        if len(lines) > MAX_SINK_SPANS:
            del lines[:len(lines) - MAX_SINK_SPANS]
            _sink_state["flushed"] = 0  # prefix changed: rewrite all
        if not _sink_state["atexit"]:
            _sink_state["atexit"] = True
            atexit.register(flush_trace_sink)
        now = time.monotonic()
        pending = len(lines) - _sink_state["flushed"]
        roll_now = len(lines) >= _SINK_SEGMENT_SPANS
        flush_now = pending >= _SINK_FLUSH_EVERY_N or \
            (pending > 0 and now - _sink_state["last_flush"]
             >= _SINK_FLUSH_EVERY_S)
    if roll_now:
        _sink_roll()
    elif flush_now:
        flush_trace_sink()


def _sink_write(path: str, lines: List[str]) -> bool:
    from ..resilience.atomic import write_text

    try:
        write_text(path, "".join(lines))
        return True
    except OSError:
        return False  # full disk etc: keep buffering, retry next flush


def flush_trace_sink():
    """Publish every buffered sampled span to the per-process sink
    segment (one atomic whole-file rewrite — a concurrent obsdump
    reassembly never reads a torn line). No-op without
    PADDLE_TPU_TRACE_DIR. Writers are serialized and `flushed` only
    advances AFTER a successful write: a failed write (or a racing
    older snapshot) can never strand tail spans as flushed-but-absent,
    so the atexit flush still publishes them."""
    with _sink_flush_lock:
        with _sink_lock:
            path = _sink_state["path"]
            lines = list(_sink_state["lines"])
            if path is None or len(lines) == _sink_state["flushed"]:
                return
        if not _sink_write(path, lines):
            return
        with _sink_lock:
            if _sink_state["path"] == path \
                    and _sink_state["flushed"] < len(lines):
                _sink_state["flushed"] = len(lines)
                _sink_state["last_flush"] = time.monotonic()


def _sink_roll():
    """Seal the current segment (final full write) and start a fresh
    trace-<pid>-<rand> file — the per-flush rewrite cost and the buffer
    are both bounded by _SINK_SEGMENT_SPANS. Spans appended while the
    seal was being written stay buffered for the new segment."""
    with _sink_flush_lock:
        with _sink_lock:
            path = _sink_state["path"]
            lines = list(_sink_state["lines"])
        if path is None or not lines:
            return
        if not _sink_write(path, lines):
            return  # unwritable: keep the segment open, retry later
        with _sink_lock:
            if _sink_state["path"] != path:
                return  # env/pid reset raced us; nothing to seal
            del _sink_state["lines"][:len(lines)]
            _sink_state["flushed"] = 0
            _sink_state["last_flush"] = time.monotonic()
            _sink_state["path"] = os.path.join(
                os.path.dirname(path),
                f"trace-{os.getpid()}-{os.urandom(4).hex()}.jsonl")


def record_span_ctx(ctx: Optional[TraceContext], name: str, dur: float,
                    cat: str = "trace", t0_perf: Optional[float] = None,
                    **args):
    """Record `ctx` itself as one finished span: tagged into the ring
    AND appended to the JSONL sink. No-op unless ctx is sampled — the
    zero-overhead contract for unsampled requests."""
    if ctx is None or not ctx.sampled:
        return
    t0 = time.perf_counter() - dur if t0_perf is None else t0_perf
    tagged = dict(args)
    tagged["trace_id"] = ctx.trace_id
    tagged["span_id"] = ctx.span_id
    if ctx.parent_span_id:
        tagged["parent_span_id"] = ctx.parent_span_id
    record_span(name, t0, dur, cat, tagged)
    _sink_append({
        "trace_id": ctx.trace_id, "span_id": ctx.span_id,
        "parent_span_id": ctx.parent_span_id, "name": name, "cat": cat,
        "ts": time.time() - dur, "dur": dur, "pid": os.getpid(),
        "tid": threading.get_ident(),
        "args": args or None})


def record_trace_span(name: str, parent: Optional[TraceContext],
                      dur: float, cat: str = "trace",
                      t0_perf: Optional[float] = None, **args
                      ) -> Optional[TraceContext]:
    """Mint a child of `parent` and record it retroactively (the
    batcher/decode scheduler shape: the span's duration is only known
    after the fact). Returns the child, or None when unsampled."""
    if parent is None or not parent.sampled:
        return None
    child = parent.child()
    record_span_ctx(child, name, dur, cat=cat, t0_perf=t0_perf, **args)
    return child


@contextlib.contextmanager
def trace_span(name: str, cat: str = "trace",
               ctx: Optional[TraceContext] = None, **args):
    """Span that participates in the distributed trace: mints a child
    of the ambient (or explicit `ctx`) context, makes it ambient for
    the body — nested spans and downstream propagation see it — and
    records it on exit. When no sampled context is active this is a
    near-free no-op (one contextvar read), yielding the unchanged
    context. Pass `ctx` explicitly to adopt a context captured on
    another thread (batcher lead request, PS server envelope)."""
    cur = ctx if ctx is not None else _current.get()
    if cur is None or not cur.sampled:
        yield cur
        return
    child = cur.child()
    token = _current.set(child)
    t0 = time.perf_counter()
    try:
        yield child
    finally:
        _current.reset(token)
        record_span_ctx(child, name, time.perf_counter() - t0,
                        cat=cat, t0_perf=t0, **args)


@contextlib.contextmanager
def span(name: str, cat: str = "host", **args):
    """Context-manager span recorded into the unified store. When a
    sampled trace context is active, the span additionally joins the
    distributed trace (child ids + JSONL sink) — the executor's step
    spans gain the active trace id through exactly this path."""
    cur = _current.get()
    if cur is not None and cur.sampled:
        with trace_span(name, cat=cat, ctx=cur, **args):
            yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_span(name, t0, time.perf_counter() - t0, cat,
                    args or None)


@contextlib.contextmanager
def step_span(name: str, cat: str = "step", **args):
    """`span()` that also STARTS a root trace when none is active and
    PADDLE_TPU_TRACE_SAMPLE is armed — the training path's trace
    origin: Executor.run / run_chained / run_stream windows wrap their
    dispatch in this, so PS RPCs issued inside the step inherit the
    step's trace id without any trainer changes."""
    token = None
    if _current.get() is None and sample_rate() > 0.0:
        token = _current.set(start_trace())
    try:
        with span(name, cat=cat, **args):
            yield
    finally:
        if token is not None:
            _current.reset(token)


def get_spans(cat: Optional[str] = None) -> List[Span]:
    with _lock:
        out = list(_spans)
    if cat is not None:
        out = [s for s in out if s.cat == cat]
    return out


def dropped_spans() -> int:
    with _lock:
        return _dropped


def clear_spans():
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------

# Host/step spans get stable synthetic pids so the tracks group cleanly in
# the viewer; device traces keep their own pids (offset on collision is
# unnecessary — jax pids are real OS pids, far from these).
_PID_BY_CAT = {"host": 1, "step": 2}


def spans_to_chrome_events(spans: Sequence[Span]) -> List[dict]:
    events = []
    tids: Dict[int, int] = {}
    for s in spans:
        tid = tids.setdefault(s.tid, len(tids))
        ev = {"name": s.name, "ph": "X",
              "pid": _PID_BY_CAT.get(s.cat, 3), "tid": tid,
              "ts": s.ts * 1e6, "dur": s.dur * 1e6, "cat": s.cat}
        if s.args:
            ev["args"] = {k: v for k, v in s.args.items()}
        events.append(ev)
    return events


def _load_chrome_trace(path: str) -> List[dict]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", []) if isinstance(data, dict) else data
    for e in events:
        e.setdefault("cat", "device")
    return events


def find_device_traces(trace_dir: str) -> List[str]:
    """The jax profiler writes plugins/profile/<run>/<host>.trace.json.gz;
    accept plain .trace.json too."""
    hits = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(trace_dir, pat),
                              recursive=True))
    return sorted(set(hits))


def merge_chrome_traces(event_lists: Sequence[Sequence[dict]]) -> dict:
    merged: List[dict] = []
    for evs in event_lists:
        merged.extend(evs)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


_warned_dropped = [False]


def export_trace(path: str, trace_dir: Optional[str] = None,
                 spans: Optional[Sequence[Span]] = None) -> str:
    """Write ONE chrome trace: the unified span store (host + step +
    whatever else was recorded) plus every jax device trace found under
    `trace_dir`. Returns `path`. Warns ONCE per process when the ring
    evicted spans — the export window is then missing its oldest spans
    and the reader should know rather than trust a silently truncated
    timeline (the same drop count feeds the
    paddle_tpu_spans_dropped_total counter)."""
    if dropped_spans() and not _warned_dropped[0]:
        _warned_dropped[0] = True
        import logging

        logging.getLogger("paddle_tpu.observability").warning(
            "export_trace: the span ring dropped %d span(s) (oldest "
            "evicted past MAX_SPANS=%d) — the exported window is "
            "incomplete at its start", dropped_spans(), MAX_SPANS)
    lists = [spans_to_chrome_events(
        spans if spans is not None else get_spans())]
    if trace_dir and os.path.isdir(trace_dir):
        for p in find_device_traces(trace_dir):
            try:
                lists.append(_load_chrome_trace(p))
            except (OSError, ValueError):
                continue  # truncated trace from a killed run: skip, keep ours
    trace = merge_chrome_traces(lists)
    from ..resilience.atomic import json_dump as _atomic_json_dump

    _atomic_json_dump(trace, path)
    return path


def save_spans(path: str) -> str:
    """Persist raw spans as JSON (spans.json in a run dir) so
    tools/obsdump.py can rebuild a trace offline."""
    from ..resilience.atomic import json_dump as _atomic_json_dump

    _atomic_json_dump([s._asdict() for s in get_spans()], path)
    return path


# ---------------------------------------------------------------------------
# Cross-process trace reassembly (the obsdump `trace --trace-id` backend)
# ---------------------------------------------------------------------------


def read_trace_dir(trace_dir: str) -> List[Dict[str, Any]]:
    """Every sampled-span record from every process sink under
    `trace_dir` (router + N replicas + PS servers each wrote their own
    trace-<pid>-<suffix>.jsonl). Malformed lines are skipped — a killed
    process can leave at most a torn tail, and the atomic-rewrite sink
    makes even that unlikely."""
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "trace-*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("trace_id") \
                            and rec.get("span_id"):
                        out.append(rec)
        except OSError:
            continue
    return out


def trace_summaries(records: Sequence[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """One row per trace_id (newest first): span count, distinct
    processes, the root span's name, start time, total duration."""
    by_tid: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        by_tid.setdefault(r["trace_id"], []).append(r)
    rows = []
    for tid, recs in by_tid.items():
        ids = {r["span_id"] for r in recs}
        roots = [r for r in recs
                 if not r.get("parent_span_id")
                 or r["parent_span_id"] not in ids]
        roots.sort(key=lambda r: r.get("ts", 0.0))
        t0 = min(r.get("ts", 0.0) for r in recs)
        t1 = max(r.get("ts", 0.0) + r.get("dur", 0.0) for r in recs)
        rows.append({
            "trace_id": tid, "spans": len(recs),
            "processes": len({r.get("pid") for r in recs}),
            "root": roots[0]["name"] if roots else "?",
            "start_ts": t0, "wall_ms": round((t1 - t0) * 1000, 3)})
    rows.sort(key=lambda r: r["start_ts"], reverse=True)
    return rows


def build_trace_tree(records: Sequence[Dict[str, Any]], trace_id: str
                     ) -> List[Dict[str, Any]]:
    """Reassemble one trace's span TREE across processes: nodes are the
    sink records plus a `children` list, linked on parent_span_id and
    ordered by wall-clock start. Spans whose parent was never recorded
    (an unflushed/killed process, or the parent lives in an untraced
    tier) surface as additional roots rather than vanishing."""
    nodes = {r["span_id"]: dict(r, children=[])
             for r in records if r.get("trace_id") == trace_id}
    roots: List[Dict[str, Any]] = []
    for node in nodes.values():
        parent = node.get("parent_span_id")
        if parent and parent in nodes and parent != node["span_id"]:
            nodes[parent]["children"].append(node)
        else:
            roots.append(node)

    def _sort(children):
        children.sort(key=lambda n: n.get("ts", 0.0))
        for c in children:
            _sort(c["children"])

    _sort(roots)
    return roots


def trace_records_to_chrome(records: Sequence[Dict[str, Any]]
                            ) -> List[dict]:
    """Sink records → chrome trace events. Unlike the in-process ring
    (perf_counter epoch per process), sink records carry wall-clock
    start times, so spans from different processes line up on one
    timeline; pids are the real OS pids."""
    events = []
    tids: Dict[tuple, int] = {}
    for r in records:
        pid = int(r.get("pid", 0))
        tid = tids.setdefault((pid, r.get("tid", 0)), len(tids))
        ev = {"name": r.get("name", "?"), "ph": "X", "pid": pid,
              "tid": tid, "ts": float(r.get("ts", 0.0)) * 1e6,
              "dur": float(r.get("dur", 0.0)) * 1e6,
              "cat": r.get("cat", "trace")}
        args = dict(r.get("args") or {})
        args["trace_id"] = r.get("trace_id")
        args["span_id"] = r.get("span_id")
        if r.get("parent_span_id"):
            args["parent_span_id"] = r["parent_span_id"]
        ev["args"] = args
        events.append(ev)
    return events
