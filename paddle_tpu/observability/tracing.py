"""Unified span store + chrome-trace export.

One process-wide span list replaces the profiler's ad-hoc `_host_spans`:
`profiler.RecordEvent` host spans (cat="host"), executor/trainer/SPMD step
telemetry (cat="step"), and any other subsystem annotation all land here,
and `export_trace` merges them with the jax.profiler device timeline
(the `*.trace.json.gz` chrome traces jax writes under
`plugins/profile/<run>/`) into ONE chrome://tracing / perfetto-loadable
JSON file — the role of the reference's profiler.proto + tools/timeline.py
converter.

Timestamps: span ts/dur are time.perf_counter() seconds (matching what
RecordEvent always recorded); exported values are microseconds. Device
events keep their own profiler epoch — perfetto renders them as separate
tracks, which is how the reference timeline showed host vs. CUPTI streams
too.
"""

from __future__ import annotations

import collections
import contextlib
import glob
import gzip
import json
import os
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

__all__ = ["Span", "span", "record_span", "get_spans", "clear_spans",
           "dropped_spans", "save_spans", "export_trace",
           "merge_chrome_traces"]

# Bound host memory: a week-long trainer recording a span per step must
# not OOM the host. The store is a ring — the OLDEST spans are evicted
# (and counted in dropped_spans()), so profiling a late window of a long
# run still exports that window rather than stale day-one spans.
MAX_SPANS = 200_000


class Span(NamedTuple):
    name: str
    ts: float            # perf_counter seconds
    dur: float           # seconds
    cat: str             # "host" | "step" | subsystem-chosen
    tid: int             # recording thread ident
    args: Optional[Dict[str, Any]]


_lock = threading.Lock()
_spans: "collections.deque[Span]" = collections.deque()
_dropped = 0


def record_span(name: str, ts: float, dur: float, cat: str = "host",
                args: Optional[Dict[str, Any]] = None):
    global _dropped
    sp = Span(name, ts, dur, cat, threading.get_ident(), args)
    with _lock:
        _spans.append(sp)
        while len(_spans) > MAX_SPANS:
            _spans.popleft()
            _dropped += 1


@contextlib.contextmanager
def span(name: str, cat: str = "host", **args):
    """Context-manager span recorded into the unified store."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_span(name, t0, time.perf_counter() - t0, cat,
                    args or None)


def get_spans(cat: Optional[str] = None) -> List[Span]:
    with _lock:
        out = list(_spans)
    if cat is not None:
        out = [s for s in out if s.cat == cat]
    return out


def dropped_spans() -> int:
    with _lock:
        return _dropped


def clear_spans():
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------

# Host/step spans get stable synthetic pids so the tracks group cleanly in
# the viewer; device traces keep their own pids (offset on collision is
# unnecessary — jax pids are real OS pids, far from these).
_PID_BY_CAT = {"host": 1, "step": 2}


def spans_to_chrome_events(spans: Sequence[Span]) -> List[dict]:
    events = []
    tids: Dict[int, int] = {}
    for s in spans:
        tid = tids.setdefault(s.tid, len(tids))
        ev = {"name": s.name, "ph": "X",
              "pid": _PID_BY_CAT.get(s.cat, 3), "tid": tid,
              "ts": s.ts * 1e6, "dur": s.dur * 1e6, "cat": s.cat}
        if s.args:
            ev["args"] = {k: v for k, v in s.args.items()}
        events.append(ev)
    return events


def _load_chrome_trace(path: str) -> List[dict]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", []) if isinstance(data, dict) else data
    for e in events:
        e.setdefault("cat", "device")
    return events


def find_device_traces(trace_dir: str) -> List[str]:
    """The jax profiler writes plugins/profile/<run>/<host>.trace.json.gz;
    accept plain .trace.json too."""
    hits = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(trace_dir, pat),
                              recursive=True))
    return sorted(set(hits))


def merge_chrome_traces(event_lists: Sequence[Sequence[dict]]) -> dict:
    merged: List[dict] = []
    for evs in event_lists:
        merged.extend(evs)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def export_trace(path: str, trace_dir: Optional[str] = None,
                 spans: Optional[Sequence[Span]] = None) -> str:
    """Write ONE chrome trace: the unified span store (host + step +
    whatever else was recorded) plus every jax device trace found under
    `trace_dir`. Returns `path`."""
    lists = [spans_to_chrome_events(
        spans if spans is not None else get_spans())]
    if trace_dir and os.path.isdir(trace_dir):
        for p in find_device_traces(trace_dir):
            try:
                lists.append(_load_chrome_trace(p))
            except (OSError, ValueError):
                continue  # truncated trace from a killed run: skip, keep ours
    trace = merge_chrome_traces(lists)
    from ..resilience.atomic import json_dump as _atomic_json_dump

    _atomic_json_dump(trace, path)
    return path


def save_spans(path: str) -> str:
    """Persist raw spans as JSON (spans.json in a run dir) so
    tools/obsdump.py can rebuild a trace offline."""
    from ..resilience.atomic import json_dump as _atomic_json_dump

    _atomic_json_dump([s._asdict() for s in get_spans()], path)
    return path
