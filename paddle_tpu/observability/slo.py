"""Declarative SLOs evaluated as multi-window multi-burn-rate alerts
over the recorded time series (timeseries.py → aggregate.py → here).

An objective is a JSON entry (spec file via PADDLE_TPU_SLO_SPEC /
`ServingConfig.slo_spec`, or a dict in tests):

  {"slos": [
    {"name": "predict-availability", "type": "availability",
     "target": 0.999,
     "errors": {"metric": "paddle_tpu_fleet_requests_total",
                "labels": {"outcome": "error"}},
     "total":  {"metric": "paddle_tpu_fleet_requests_total"}},
    {"name": "predict-latency", "type": "latency", "target": 0.95,
     "metric": "paddle_tpu_fleet_request_seconds",
     "threshold_s": 0.25}
  ]}

Both shapes reduce to one number per window: the BAD-event fraction.
Availability is errors/total over a ratio of two counter increases;
latency is re-framed the same way — the fraction of requests SLOWER
than threshold_s, with the shared bucket interpolation estimating the
split inside the straddling bucket. Burn rate = bad_fraction /
(1 - target): burn 1.0 consumes the error budget exactly at the rate
that exhausts it at the SLO period's end; burn 14.4 exhausts a 30-day
budget in ~2 days.

Alerting follows the Google-SRE multiwindow shape: a pair fires only
when BOTH its short and long windows exceed the pair's burn threshold
(the long window gives confidence, the short window makes recovery
reset fast). Defaults: fast = 5m/1h at 14.4x (page), slow = 30m/6h at
6x (ticket). `window_scale` shrinks every window uniformly so a bench
can exercise breach → fire → clear in seconds. State transitions emit
`slo_alert` events and count into `paddle_tpu_slo_alerts_total`;
the fast-window burn is exported as `paddle_tpu_slo_burn_rate`.

Stdlib-only and file-path importable (obsdump `slo` loads this without
the framework); siblings resolve through aggregate's `_sibling`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "DEFAULT_WINDOWS", "load_spec", "SLOEngine",
    "maybe_start_evaluator", "stop_evaluator", "current_engine",
    "status_snapshot",
]

_HERE = os.path.dirname(os.path.abspath(__file__))

if __package__:
    from . import aggregate as _aggregate
    from . import events as _events
    from . import metrics as _metrics
else:  # file-path loaded (tools/obsdump.py): bootstrap siblings
    import importlib.util as _ilu

    def _load(name):
        spec = _ilu.spec_from_file_location(
            f"_pt_obs_{name}", os.path.join(_HERE, name + ".py"))
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _aggregate = _load("aggregate")
    _events = _load("events")
    _metrics = _load("metrics")

TS_DIR_ENV = "PADDLE_TPU_TS_DIR"
SLO_SPEC_ENV = "PADDLE_TPU_SLO_SPEC"
SLO_INTERVAL_ENV = "PADDLE_TPU_SLO_INTERVAL_S"
SLO_WINDOW_SCALE_ENV = "PADDLE_TPU_SLO_WINDOW_SCALE"

# Google-SRE multiwindow pairs (SLO period 30d): page on fast burn,
# ticket on slow burn. Scaled uniformly by SLOEngine(window_scale=).
DEFAULT_WINDOWS = (
    {"name": "fast", "short_s": 300.0, "long_s": 3600.0, "burn": 14.4},
    {"name": "slow", "short_s": 1800.0, "long_s": 21600.0, "burn": 6.0},
)

_BURN_GAUGE = _metrics.gauge(
    "paddle_tpu_slo_burn_rate",
    "Fast-window burn rate per SLO (1.0 = budget-neutral)",
    labelnames=("slo",))
_ALERTS_TOTAL = _metrics.counter(
    "paddle_tpu_slo_alerts_total",
    "SLO alert state transitions", labelnames=("slo", "state"))


def load_spec(spec) -> List[dict]:
    """Normalize a spec (dict, or path to a JSON file) into validated
    slo dicts. Raises ValueError on a malformed objective — a silently
    dropped SLO is an unmonitored SLO."""
    if isinstance(spec, str):
        with open(spec) as f:
            spec = json.load(f)
    if not isinstance(spec, dict) or not isinstance(spec.get("slos"), list):
        raise ValueError('SLO spec must be {"slos": [...]}')
    out = []
    for i, s in enumerate(spec["slos"]):
        if not isinstance(s, dict) or not s.get("name"):
            raise ValueError(f"slos[{i}]: missing name")
        name, typ = s["name"], s.get("type")
        target = float(s.get("target", 0))
        if not 0 < target < 1:
            raise ValueError(f"slo {name!r}: target must be in (0, 1)")
        if typ == "availability":
            for k in ("errors", "total"):
                if not isinstance(s.get(k), dict) \
                        or not s[k].get("metric"):
                    raise ValueError(
                        f"slo {name!r}: availability needs "
                        f'{k}.metric')
        elif typ == "latency":
            if not s.get("metric") or "threshold_s" not in s:
                raise ValueError(
                    f"slo {name!r}: latency needs metric + threshold_s")
        else:
            raise ValueError(
                f"slo {name!r}: type must be availability|latency")
        for w in s.get("windows", ()):
            if not all(k in w for k in ("name", "short_s", "long_s",
                                        "burn")):
                raise ValueError(
                    f"slo {name!r}: window needs name/short_s/long_s/burn")
        out.append(dict(s, target=target))
    return out


def _good_below(hist: Dict, threshold: float) -> float:
    """Observations ≤ threshold in a merged per-bin bucket table,
    linearly interpolated inside the straddling bucket (the same
    assumption bucket_quantile makes, inverted)."""
    good, prev_le = 0.0, 0.0
    for le, n in hist["buckets"]:
        if le <= threshold:
            good += n
        else:
            if threshold > prev_le:
                good += n * (threshold - prev_le) / (le - prev_le)
            break
        prev_le = le
    return good


class SLOEngine:
    """Evaluate objectives against a TS dir; keep per-SLO alert state
    across evaluations. Drive `evaluate()` from the background
    evaluator, a bench loop, or a test with an injected clock."""

    def __init__(self, slos, ts_dir: str, clock=time.time,
                 window_scale: float = 1.0):
        self.slos = load_spec({"slos": list(slos)}) \
            if not isinstance(slos, dict) else load_spec(slos)
        self.ts_dir = ts_dir
        self.clock = clock
        self.window_scale = max(1e-9, float(window_scale))
        self._state: Dict[str, str] = {
            s["name"]: "ok" for s in self.slos}
        self._last: List[dict] = []

    def _windows(self, slo: dict) -> List[dict]:
        ws = slo.get("windows") or [dict(w) for w in DEFAULT_WINDOWS]
        return [{"name": w["name"],
                 "short_s": float(w["short_s"]) * self.window_scale,
                 "long_s": float(w["long_s"]) * self.window_scale,
                 "burn": float(w["burn"])} for w in ws]

    def _bad_fraction(self, slo: dict, store, window_s: float,
                      now: float) -> Optional[float]:
        """Bad-event fraction over the window; None = no traffic (no
        data is not an outage — burn stays 0 until requests flow)."""
        if slo["type"] == "availability":
            tot = store.increase(slo["total"]["metric"], window_s, now,
                                 slo["total"].get("labels"))
            if tot <= 0:
                return None
            err = store.increase(slo["errors"]["metric"], window_s, now,
                                 slo["errors"].get("labels"))
            return min(1.0, max(0.0, err / tot))
        hist = store.hist_increase(slo["metric"], window_s, now,
                                   slo.get("labels"))
        if hist["count"] <= 0:
            return None
        good = _good_below(hist, float(slo["threshold_s"]))
        return min(1.0, max(0.0, 1.0 - good / hist["count"]))

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass: reload the TS dir, compute every
        window's burn, step each SLO's alert state machine (emitting
        `slo_alert` on transitions), return the status rows. Windows
        anchor at the NEWEST recorded sample (not wall clock), so an
        offline dir evaluates the same as it did live; burn therefore
        freezes rather than decaying if recording stops."""
        store = _aggregate.TSStore.load(self.ts_dir)
        if now is None:
            now = store.latest_ts()
            if now is None:
                now = self.clock()
        rows = []
        for slo in self.slos:
            name = slo["name"]
            budget = 1.0 - slo["target"]
            windows, firing = [], []
            current = None
            for w in self._windows(slo):
                burns = {}
                for side, wsec in (("short", w["short_s"]),
                                   ("long", w["long_s"])):
                    bad = self._bad_fraction(slo, store, wsec, now)
                    burns[side] = 0.0 if bad is None else bad / budget
                    if side == "long" and w["name"] == "fast":
                        current = None if bad is None else 1.0 - bad
                fires = burns["short"] >= w["burn"] \
                    and burns["long"] >= w["burn"]
                if fires:
                    firing.append(w["name"])
                windows.append({"window": w["name"], "burn": w["burn"],
                                "short_s": w["short_s"],
                                "long_s": w["long_s"],
                                "burn_short": burns["short"],
                                "burn_long": burns["long"],
                                "firing": fires})
                if w["name"] == "fast":
                    _BURN_GAUGE.set(burns["short"], slo=name)
            state = "fast_burn" if "fast" in firing else \
                "slow_burn" if "slow" in firing else "ok"
            prev = self._state[name]
            if state != prev:
                self._state[name] = state
                _ALERTS_TOTAL.inc(slo=name, state=state)
                _events.emit("slo_alert", slo=name, state=state,
                             prev=prev, slo_type=slo["type"],
                             target=slo["target"],
                             windows=[w for w in windows if w["firing"]]
                             or windows[:1])
            rows.append({"name": name, "type": slo["type"],
                         "target": slo["target"], "state": state,
                         "current": current, "windows": windows})
        self._last = rows
        return rows

    def last(self) -> List[dict]:
        return self._last

    def state(self, name: str) -> str:
        return self._state[name]

    def max_burn_rate(self) -> float:
        """Scalar for the autoscaler: the worst confirmed fast burn
        across objectives — min(short, long) per SLO so a single noisy
        short window can't trigger scale-out on its own."""
        worst = 0.0
        for row in self._last:
            for w in row["windows"]:
                if w["window"] == "fast":
                    worst = max(worst, min(w["burn_short"],
                                           w["burn_long"]))
        return worst


# ---------------------------------------------------------------------------
# Env-gated background evaluator (serving boots this from ServingConfig)
# ---------------------------------------------------------------------------

_engine: Optional[SLOEngine] = None
_eval_thread: Optional[threading.Thread] = None
_eval_stop = threading.Event()
_eval_lock = threading.Lock()
_atexit_registered = False


def current_engine() -> Optional[SLOEngine]:
    return _engine


def maybe_start_evaluator(spec_path: Optional[str] = None) -> bool:
    """Start the background SLO evaluator iff a spec (argument or
    PADDLE_TPU_SLO_SPEC) AND PADDLE_TPU_TS_DIR are configured. The
    period is PADDLE_TPU_SLO_INTERVAL_S (default 5s); windows shrink by
    PADDLE_TPU_SLO_WINDOW_SCALE. A malformed spec disables evaluation
    rather than killing the server boot."""
    global _engine, _eval_thread, _atexit_registered
    spec = spec_path or os.environ.get(SLO_SPEC_ENV)
    ts_dir = os.environ.get(TS_DIR_ENV)
    if not spec or not ts_dir:
        return False
    with _eval_lock:
        if _eval_thread is not None and _eval_thread.is_alive():
            return True
        try:
            engine = SLOEngine(
                load_spec(spec) if isinstance(spec, str) else spec,
                ts_dir,
                window_scale=float(os.environ.get(
                    SLO_WINDOW_SCALE_ENV, "1") or 1))
        except (OSError, ValueError):
            return False
        try:
            interval = float(os.environ.get(SLO_INTERVAL_ENV, "5"))
        except ValueError:
            interval = 5.0
        if interval <= 0:
            interval = 5.0
        _engine = engine
        _eval_stop.clear()

        def loop():
            while not _eval_stop.wait(interval):
                try:
                    engine.evaluate()
                except OSError:
                    pass  # TS dir vanished mid-run; keep serving alive

        _eval_thread = threading.Thread(
            target=loop, name="paddle-tpu-slo-eval", daemon=True)
        _eval_thread.start()
        if not _atexit_registered:
            import atexit

            atexit.register(stop_evaluator)
            _atexit_registered = True
        return True


def stop_evaluator():
    global _engine, _eval_thread
    with _eval_lock:
        t, _eval_thread = _eval_thread, None
        _engine = None
    if t is not None and t.is_alive():
        _eval_stop.set()
        t.join(timeout=5)


def status_snapshot() -> Dict:
    """The GET /v1/slo payload: live engine state when the evaluator
    runs; a transient evaluation when only env is configured; an
    explanatory error otherwise."""
    eng = _engine
    if eng is not None:
        rows = eng.last() or eng.evaluate()
        return {"slos": rows, "ts_dir": eng.ts_dir,
                "window_scale": eng.window_scale}
    spec = os.environ.get(SLO_SPEC_ENV)
    ts_dir = os.environ.get(TS_DIR_ENV)
    if spec and ts_dir:
        try:
            eng = SLOEngine(
                load_spec(spec), ts_dir,
                window_scale=float(os.environ.get(
                    SLO_WINDOW_SCALE_ENV, "1") or 1))
            return {"slos": eng.evaluate(), "ts_dir": ts_dir,
                    "window_scale": eng.window_scale,
                    "transient": True}
        except (OSError, ValueError) as e:
            return {"error": f"{type(e).__name__}: {e}"}
    return {"error": "no SLO engine: set PADDLE_TPU_SLO_SPEC (or "
                     "ServingConfig.slo_spec) and PADDLE_TPU_TS_DIR"}
