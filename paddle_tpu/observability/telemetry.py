"""Step-telemetry metric definitions + record helpers for the hot paths.

Every framework subsystem funnels through these helpers instead of
touching the registry ad hoc, so the metric names/labels stay one
vocabulary (documented in PROFILE.md §Observability):

  executor  — step wall time, feed bytes, program-cache hits/misses
  trainer   — step/example throughput
  spmd      — per-mesh-axis step time + collective-op counts
  pipeline  — schedule shape (stages, microbatches, bubble fraction)

This module must stay import-light (stdlib only): core/executor.py
imports it at module load, before the rest of the package finishes
initializing.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

from . import events as _events
from . import httpd as _httpd
from . import metrics as _m
from . import perfwatch as _perfwatch
from . import timeseries as _timeseries

__all__ = [
    "executor_step", "feed_nbytes",
    "record_executor_step", "record_cache_event", "record_trainer_step",
    "record_trainer_run", "record_spmd_step", "record_pipeline_trace",
    "record_compile", "record_compile_cache", "record_device_memory",
    "record_amp", "record_analysis",
    "record_host_blocked", "record_dispatch_ready",
    "record_prefetch_depth", "record_prefetch_item",
    "record_async_inflight", "record_chained_eviction",
    "host_blocked_total",
]

EXEC_STEPS = _m.counter(
    "paddle_tpu_executor_steps_total",
    "Executor.run / run_chained invocations", labelnames=("mode",))
EXEC_STEP_SECONDS = _m.histogram(
    "paddle_tpu_executor_step_seconds",
    "End-to-end Executor step wall time (lookup+dispatch+fetch)",
    labelnames=("mode",))
EXEC_FEED_BYTES = _m.counter(
    "paddle_tpu_executor_feed_bytes_total",
    "Bytes of feed tensors handed to the executor")
EXEC_CACHE = _m.counter(
    "paddle_tpu_executor_cache_total",
    "Program-cache lookups from _lookup_step (event=hit|miss; a miss is "
    "a jit trace+compile)", labelnames=("event",))
EXEC_CACHE_ENTRIES = _m.gauge(
    "paddle_tpu_executor_cache_entries",
    "Live compiled-step entries across executors")

TRAINER_STEPS = _m.counter(
    "paddle_tpu_trainer_steps_total", "Trainer-loop steps")
TRAINER_EXAMPLES = _m.counter(
    "paddle_tpu_trainer_examples_total",
    "Examples consumed by trainer loops (leading feed dim)")
TRAINER_STEP_SECONDS = _m.histogram(
    "paddle_tpu_trainer_step_seconds", "Trainer-loop per-step wall time")
TRAINER_EXAMPLES_PER_SEC = _m.gauge(
    "paddle_tpu_trainer_examples_per_sec",
    "Throughput of the last trainer run (examples / wall seconds)")
TRAINER_RUNS = _m.counter(
    "paddle_tpu_trainer_runs_total",
    "train_from_dataset / worker epochs completed")

SPMD_STEPS = _m.counter(
    "paddle_tpu_spmd_steps_total", "SPMDRunner steps",
    labelnames=("axis",))
SPMD_STEP_SECONDS = _m.histogram(
    "paddle_tpu_spmd_step_seconds", "SPMDRunner per-step wall time",
    labelnames=("axis",))
SPMD_COLLECTIVES = _m.counter(
    "paddle_tpu_spmd_collectives_total",
    "Collective ops executed (static per-program count x steps)",
    labelnames=("axis", "op"))

PIPELINE_TRACES = _m.counter(
    "paddle_tpu_pipeline_traces_total",
    "pipeline_apply traces (jit retrace = new schedule/shape)",
    labelnames=("axis",))
PIPELINE_STAGES = _m.gauge(
    "paddle_tpu_pipeline_stages", "Stages in the last traced pipeline",
    labelnames=("axis",))
PIPELINE_MICROBATCHES = _m.gauge(
    "paddle_tpu_pipeline_microbatches",
    "Microbatches in the last traced pipeline", labelnames=("axis",))
PIPELINE_BUBBLE_FRACTION = _m.gauge(
    "paddle_tpu_pipeline_bubble_fraction",
    "GPipe bubble (S-1)/(n_micro+S-1) of the last traced pipeline",
    labelnames=("axis",))

COMPILES = _m.counter(
    "paddle_tpu_compiles_total",
    "XLA compiles by program kind (step|chained|sharded|spmd); a rising "
    "rate at steady state is a recompile storm", labelnames=("kind",))
COMPILE_SECONDS = _m.histogram(
    "paddle_tpu_compile_seconds",
    "Wall seconds per XLA trace+compile", labelnames=("kind",))
COMPILE_FLOPS = _m.gauge(
    "paddle_tpu_compile_flops",
    "cost_analysis() FLOPs estimate of the most recent compile",
    labelnames=("kind",))
COMPILE_CACHE = _m.counter(
    "paddle_tpu_compile_cache_total",
    "Persistent compile-cache (PADDLE_TPU_COMPILE_CACHE) outcomes by "
    "program kind: hit (deserialized, compile skipped), miss, store, "
    "corrupt (bad/mismatched entry dropped), store_error, evict",
    labelnames=("kind", "event"))
COMPILE_CACHE_BYTES = _m.counter(
    "paddle_tpu_compile_cache_bytes_total",
    "Bytes read on compile-cache hits / written on stores / dropped on "
    "evictions", labelnames=("kind", "event"))
AMP_EVENTS = _m.counter(
    "paddle_tpu_amp_total",
    "Dynamic loss-scaling outcomes under a mixed-precision policy: "
    "overflow (nonfinite grads detected), skip (the update those grads "
    "would have applied was dropped), growth (scale grew after a clean "
    "streak). A rising overflow rate at steady state means the scale "
    "is thrashing — lower init_loss_scale or widen growth_interval",
    labelnames=("event",))
AMP_LOSS_SCALE = _m.gauge(
    "paddle_tpu_amp_loss_scale",
    "Current dynamic loss scale (last host-observed value)")
ANALYSIS_RUNS = _m.counter(
    "paddle_tpu_analysis_runs_total",
    "Full static-analysis pass-suite walks (paddle_tpu/analysis). "
    "Validation results are cached per program version — a rising rate "
    "at steady state means the validation cache is not holding",
    labelnames=("where",))
ANALYSIS_FINDINGS = _m.counter(
    "paddle_tpu_analysis_findings_total",
    "Static-analysis findings by pass and severity "
    "(error|warning|info); PADDLE_TPU_VALIDATE=2 refuses to run a "
    "program with error-severity findings",
    labelnames=("pass", "severity"))
DEVICE_LIVE_BYTES = _m.gauge(
    "paddle_tpu_device_live_bytes",
    "Bytes held by live device buffers (jax.live_arrays sum); monotonic "
    "growth at steady state is a leak")
DEVICE_LIVE_BUFFERS = _m.gauge(
    "paddle_tpu_device_live_buffers",
    "Count of live device arrays")

# -- host-overlap pipeline (core/async_exec.py) -----------------------------
# The host-overlap story in three numbers: how long the host sat blocked
# on the device (should be ~0 when the pipeline hides transfers), how
# long a dispatched fetch took to become ready (device-side latency the
# host never has to see), and how full the prefetch buffer ran (0 depth
# at steady state = the consumer is input-bound).
HOST_BLOCKED_SECONDS = _m.counter(
    "paddle_tpu_host_blocked_seconds_total",
    "Wall seconds the host spent blocked waiting on device results or "
    "an empty prefetch queue, by site (executor_sync|fetch:*|"
    "prefetch:*)", labelnames=("site",))
DISPATCH_READY_SECONDS = _m.histogram(
    "paddle_tpu_dispatch_ready_seconds",
    "Latency from dispatch to the fetched values being ready on host",
    labelnames=("site",))
PREFETCH_DEPTH = _m.gauge(
    "paddle_tpu_prefetch_queue_depth",
    "Items buffered in a prefetch stage right after the last put/get",
    labelnames=("stage",))
PREFETCH_ITEMS = _m.counter(
    "paddle_tpu_prefetch_items_total",
    "Items that passed through a prefetch stage", labelnames=("stage",))
PIPELINE_STALLS = _m.counter(
    "paddle_tpu_pipeline_stalls_total",
    "Host blocks longer than PADDLE_TPU_STALL_EVENT_S (default 0.1s) — "
    "each also appends a pipeline_stall event", labelnames=("site",))
ASYNC_INFLIGHT = _m.gauge(
    "paddle_tpu_async_inflight_fetches",
    "Unresolved FetchHandles currently holding device buffers")
CHAINED_EVICTIONS = _m.counter(
    "paddle_tpu_chained_cache_evictions_total",
    "Chained-executable cache entries evicted by the per-program LRU "
    "bound (PADDLE_TPU_CHAINED_CACHE)")


def record_executor_step(mode: str, seconds: float, feed_bytes: int):
    EXEC_STEPS.inc(mode=mode)
    EXEC_STEP_SECONDS.observe(seconds, mode=mode)
    if feed_bytes:
        EXEC_FEED_BYTES.inc(feed_bytes)
    _m.maybe_start_dump_thread()
    _httpd.maybe_start_http_server()
    _timeseries.maybe_start_recorder()


def feed_nbytes(feed: Dict) -> int:
    return sum(int(getattr(v, "nbytes", 0)) for v in feed.values())


class _StepRecord:
    __slots__ = ("feed_bytes", "perf_kind", "flops", "device_kind",
                 "n_devices", "_host0")

    def __init__(self):
        self.feed_bytes = 0
        self.perf_kind: Optional[str] = None
        self.flops: Optional[float] = None
        self.device_kind: Optional[str] = None
        self.n_devices = 1
        self._host0 = HOST_BLOCKED_SECONDS.total()

    def set_feed(self, feed: Dict):
        self.feed_bytes = feed_nbytes(feed)

    def set_perf(self, kind: str, cost: Optional[Dict] = None,
                 device_kind: Optional[str] = None, n_devices: int = 1):
        """Arm the live-utilization record for this step: `kind` labels
        the paddle_tpu_mfu gauge; `cost` is the dispatch wrapper's
        retained cost_analysis dict (current_cost()). Without this call
        the step records wall time only, no MFU sample."""
        self.perf_kind = kind
        self.flops = (cost or {}).get("flops")
        self.device_kind = device_kind
        self.n_devices = max(1, int(n_devices))


@contextlib.contextmanager
def executor_step(mode: str):
    """One executor-step telemetry window (shared by Executor.run,
    run_chained, and CompiledProgram._run so the timing boundary and byte
    accounting cannot drift apart). Records only on clean exit — a step
    that raises is not a completed step. Call `set_feed(norm_feed)` once
    feeds are normalized; `set_perf(...)` once the compiled step is
    resolved to also land a live-MFU sample (perfwatch)."""
    rec = _StepRecord()
    t0 = time.perf_counter()
    yield rec
    seconds = time.perf_counter() - t0
    record_executor_step(mode, seconds, rec.feed_bytes)
    if rec.perf_kind is not None:
        # host-blocked attribution: the process-wide counter's delta
        # across this step — exact for the common single-executor
        # process, an upper-bound estimate under concurrent executors
        host = max(0.0, HOST_BLOCKED_SECONDS.total() - rec._host0)
        _perfwatch.record_step(
            rec.perf_kind, seconds, flops=rec.flops,
            host_blocked=min(host, seconds),
            device_kind=rec.device_kind, n_devices=rec.n_devices)


def record_cache_event(hit: bool, entries: int):
    EXEC_CACHE.inc(event="hit" if hit else "miss")
    EXEC_CACHE_ENTRIES.set(entries)


def record_trainer_step(seconds: float, examples: int):
    TRAINER_STEPS.inc()
    TRAINER_STEP_SECONDS.observe(seconds)
    if examples:
        TRAINER_EXAMPLES.inc(examples)


def record_trainer_run(total_seconds: float, examples: int):
    TRAINER_RUNS.inc()
    if total_seconds > 0 and examples:
        TRAINER_EXAMPLES_PER_SEC.set(examples / total_seconds)


def record_spmd_step(axis: str, seconds: float,
                     collectives: Optional[Dict[str, int]] = None):
    SPMD_STEPS.inc(axis=axis)
    SPMD_STEP_SECONDS.observe(seconds, axis=axis)
    for op, n in (collectives or {}).items():
        SPMD_COLLECTIVES.inc(n, axis=axis, op=op)
    _m.maybe_start_dump_thread()
    _httpd.maybe_start_http_server()
    _timeseries.maybe_start_recorder()


def record_compile(kind: str, seconds: float,
                   flops: Optional[float] = None,
                   out_bytes: Optional[int] = None,
                   meta: Optional[Dict] = None):
    """One XLA trace+compile: metrics + a `compile` event so a recompile
    storm is visible both as a rate and as a timeline."""
    COMPILES.inc(kind=kind)
    COMPILE_SECONDS.observe(seconds, kind=kind)
    fields: Dict = {"compile_kind": kind, "seconds": round(seconds, 6)}
    if flops is not None:
        COMPILE_FLOPS.set(flops, kind=kind)
        fields["flops"] = flops
    if out_bytes is not None:
        fields["out_bytes"] = int(out_bytes)
    if meta:
        fields.update(meta)
    _events.emit("compile", **fields)


def record_compile_cache(kind: str, event: str, nbytes: int = 0,
                         key: Optional[str] = None,
                         seconds: Optional[float] = None,
                         error: Optional[str] = None):
    """One persistent-compile-cache outcome: a hit is a compile that
    did NOT happen (its wall cost is deserialization I/O), so hits and
    misses land in their own counter family rather than polluting
    paddle_tpu_compiles_total — the recompile-storm signal stays
    honest. Every outcome also appends a `compile_cache` event so a
    restart's cache story is reconstructable from the JSONL log."""
    COMPILE_CACHE.inc(kind=kind, event=event)
    if nbytes:
        COMPILE_CACHE_BYTES.inc(nbytes, kind=kind, event=event)
    fields: Dict = {"compile_kind": kind, "event": event}
    if nbytes:
        fields["nbytes"] = int(nbytes)
    if key:
        fields["key"] = key[:16]  # enough to join with the cache file
    if seconds is not None:
        fields["seconds"] = round(seconds, 6)
    if error:
        fields["error"] = error
    _events.emit("compile_cache", **fields)


def record_amp(event: str, n: int = 1, step: Optional[int] = None,
               scale: Optional[float] = None):
    """`n` dynamic loss-scaling outcomes of kind `event`
    (overflow|growth|skip). Overflows additionally land in the JSONL
    log as `amp_overflow` events — a scale-thrash timeline is how a
    diverging mixed-precision run is diagnosed after the fact
    (tools/obsdump.py events --kind amp_overflow)."""
    if n <= 0:
        return
    AMP_EVENTS.inc(n, event=event)
    if scale is not None:
        AMP_LOSS_SCALE.set(float(scale))
    if event == "overflow":
        fields: Dict = {"count": int(n)}
        if step is not None:
            fields["step"] = int(step)
        if scale is not None:
            fields["scale"] = float(scale)
        _events.emit("amp_overflow", **fields)


def record_analysis(findings, n_ops: int, where: str, seconds: float):
    """One static-analysis pass-suite walk (paddle_tpu/analysis
    run_passes): per-pass/severity finding counts plus one `analysis`
    event summarizing the walk — a program failing validation on a
    fleet must be reconstructable from the JSONL log alone."""
    ANALYSIS_RUNS.inc(where=where)
    by_sev: Dict[str, int] = {}
    for f in findings:
        ANALYSIS_FINDINGS.inc(**{"pass": f.pass_name,
                                 "severity": f.severity})
        by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
    _events.emit("analysis", where=where, ops=int(n_ops),
                 seconds=round(seconds, 6),
                 errors=by_sev.get("error", 0),
                 warnings=by_sev.get("warning", 0),
                 infos=by_sev.get("info", 0))


def record_device_memory(nbytes: int, nbuffers: int):
    DEVICE_LIVE_BYTES.set(nbytes)
    DEVICE_LIVE_BUFFERS.set(nbuffers)


def _stall_event_threshold_s() -> float:
    import os

    raw = os.environ.get("PADDLE_TPU_STALL_EVENT_S")
    if not raw:
        return 0.1
    try:
        v = float(raw)
    except ValueError:
        return 0.1
    return v if v > 0 else 0.1


def record_host_blocked(site: str, seconds: float, stall: bool = True):
    """Wall time the host spent waiting on the device (or on an empty
    prefetch queue). Blocks past the stall threshold also count as
    pipeline stalls and land in the event log — a stall timeline is how
    an input-bound run is diagnosed after the fact. Pass stall=False
    for sites where blocking is the caller's NORMAL rhythm (the
    deliberately-synchronous fetch epilogue): its seconds still feed
    the host-overlap fraction, but a 150 ms sync step is not a stall
    and must not emit one event per step."""
    if seconds <= 0:
        return
    HOST_BLOCKED_SECONDS.inc(seconds, site=site)
    if stall and seconds >= _stall_event_threshold_s():
        PIPELINE_STALLS.inc(site=site)
        _events.emit("pipeline_stall", site=site,
                     seconds=round(seconds, 6))


def record_dispatch_ready(site: str, seconds: float):
    DISPATCH_READY_SECONDS.observe(seconds, site=site)


def record_prefetch_depth(stage: str, depth: int):
    PREFETCH_DEPTH.set(depth, stage=stage)


def record_prefetch_item(stage: str):
    PREFETCH_ITEMS.inc(stage=stage)


def record_async_inflight(n: int):
    ASYNC_INFLIGHT.set(n)


def record_chained_eviction():
    CHAINED_EVICTIONS.inc()


def host_blocked_total() -> float:
    """Process-wide host-blocked seconds across every site — what
    bench.py divides by wall time for the host-overlap fraction."""
    return HOST_BLOCKED_SECONDS.total()


def record_pipeline_trace(axis: str, stages: int, n_micro: int):
    PIPELINE_TRACES.inc(axis=axis)
    PIPELINE_STAGES.set(stages, axis=axis)
    PIPELINE_MICROBATCHES.set(n_micro, axis=axis)
    PIPELINE_BUBBLE_FRACTION.set(
        (stages - 1) / max(1, n_micro + stages - 1), axis=axis)


# -- span-ring drop visibility (ISSUE 15 satellite) -------------------------

SPANS_DROPPED = _m.counter(
    "paddle_tpu_spans_dropped_total",
    "Spans evicted oldest-first from the in-memory span ring "
    "(tracing.MAX_SPANS overflow) — a nonzero rate means exported "
    "traces are missing their oldest window")

_spans_dropped_synced = [0]


def sync_spans_dropped():
    """Publish tracing.dropped_spans() into the registry counter.
    Registered as a collect hook (runs before every /metrics render and
    snapshot), because tracing.py is stdlib-only by contract and cannot
    push into the registry itself."""
    from . import tracing as _tracing

    d = _tracing.dropped_spans()
    prev = _spans_dropped_synced[0]
    if d > prev:
        SPANS_DROPPED.inc(d - prev)
        _spans_dropped_synced[0] = d
    elif d < prev:
        _spans_dropped_synced[0] = d  # clear_spans() reset the source


_m.add_collect_hook(sync_spans_dropped)
