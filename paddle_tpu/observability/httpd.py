"""Live metrics endpoint: a stdlib http.server daemon thread serving the
process's observability state while it trains.

The PR 1 registry is scrapeable only via file dumps
(PADDLE_TPU_METRICS_DIR); a production deployment wants a live pull
target. Routes:

  GET /metrics      Prometheus text exposition of the default registry
  GET /healthz      JSON from health.status(); HTTP 200 while "ok",
                    503 once "degraded" (anomaly-aware, so a k8s
                    liveness/readiness probe sees divergence directly)
  GET /events?n=K[&kind=X]
                    last K events from the in-memory ring, one JSON
                    object per line (newline-delimited JSON)
  GET /v1/slo       SLO burn-rate status (PROFILE.md §Time series &
                    SLOs): per-objective state, windows and burn rates
                    from the background evaluator (or a transient
                    evaluation when only the env is configured)

Env gating: PADDLE_TPU_METRICS_PORT. Unset/empty → no server, no
socket. "0" → bind an ephemeral port (tests); any other integer → that
port. `maybe_start_http_server()` is called from the telemetry hot-path
helpers, so setting the env var before training is enough — nothing is
started at import time (guarded by tests/test_obs_import_cost.py).

Server lifecycle (locked idempotent start/stop, failed-bind caching,
atexit cleanup, 127.0.0.1 default bind overridable with
PADDLE_TPU_METRICS_HOST) lives in the shared `httpbase.HTTPServerHandle`
— the serving frontend (`paddle_tpu/serving/httpd.py`) reuses the same
base.
"""

from __future__ import annotations

import json
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import events as _events
from . import health as _health
from . import httpbase as _base
from . import metrics as _m

__all__ = ["start_http_server", "maybe_start_http_server",
           "stop_http_server", "server_port", "handle_profile_request"]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(_base.QuietHandler):
    server_version = "paddle-tpu-metrics"

    def do_GET(self):  # noqa: N802 - stdlib naming
        try:
            url = urlparse(self.path)
            if url.path == "/metrics":
                self._reply(200, PROM_CONTENT_TYPE,
                            _m.render_prometheus())
            elif url.path == "/healthz":
                st = _health.status()
                code = 200 if st["status"] == "ok" else 503
                self._reply(code, "application/json",
                            json.dumps(st) + "\n")
            elif url.path == "/events":
                q = parse_qs(url.query)
                try:
                    n = int(q.get("n", ["100"])[0])
                except ValueError:
                    n = 100
                kind = q.get("kind", [None])[0]
                lines = [json.dumps(e, default=str)
                         for e in _events.recent(n=n, kind=kind)]
                self._reply(200, "application/x-ndjson",
                            "\n".join(lines) + ("\n" if lines else ""))
            elif url.path == "/v1/slo":
                from . import slo as _slo

                st = _slo.status_snapshot()
                self._reply(200 if "error" not in st else 503,
                            "application/json",
                            json.dumps(_m._json_safe(st)) + "\n")
            else:
                self._reply(404, "text/plain",
                            "not found; routes: /metrics /healthz "
                            "/events?n=K /v1/slo "
                            "POST /v1/profile\n")
        except _base.CLIENT_GONE:
            pass  # scraper hung up mid-reply

    def do_POST(self):  # noqa: N802 - stdlib naming
        try:
            if urlparse(self.path).path != "/v1/profile":
                self._reply(404, "text/plain",
                            "not found; POST routes: /v1/profile\n")
                return
            code, body = handle_profile_request(self)
            self._reply(code, "application/json", body)
        except _base.CLIENT_GONE:
            pass  # caller hung up mid-capture


def handle_profile_request(handler) -> tuple:
    """Shared POST /v1/profile implementation: parse {"seconds": N}
    from the request body, run one bounded capture, reply with the
    artifact paths. Returns (http_code, json_body). Used by this
    metrics server AND the serving frontend (serving/httpd.py), so a
    fleet router can profile a replica through the same port it routes
    inference to. The handler thread blocks for the window —
    ThreadingHTTPServer keeps every other route live meanwhile."""
    try:
        n = int(handler.headers.get("Content-Length") or 0)
        req = json.loads(handler.rfile.read(n) or b"{}") if n else {}
        if not isinstance(req, dict):
            raise ValueError("body must be a JSON object")
        seconds = float(req.get("seconds", 1.0))
    except (ValueError, TypeError) as e:
        return 400, json.dumps(
            {"error": f"bad request: {e}"}) + "\n"
    # deferred: profiler pulls in jax; this module stays import-light
    from .. import profiler as _profiler

    try:
        out = _profiler.capture_profile(seconds)
    except _profiler.ProfilerBusyError as e:
        return 409, json.dumps({"error": str(e)}) + "\n"
    except Exception as e:
        return 500, json.dumps(
            {"error": f"capture failed: {e}"}) + "\n"
    return 200, json.dumps(out, default=str) + "\n"


_handle = _base.HTTPServerHandle(
    _Handler, thread_name="paddle-tpu-metrics-http",
    port_env="PADDLE_TPU_METRICS_PORT", host_env="PADDLE_TPU_METRICS_HOST")


def server_port() -> Optional[int]:
    """Bound port of the running server, or None when no server is up."""
    return _handle.port()


def start_http_server(port: int = 0, host: Optional[str] = None) -> int:
    """Start the daemon serving thread (idempotent: a second call returns
    the already-bound port). port=0 binds an ephemeral port. Returns the
    actual bound port."""
    return _handle.start(port, host)


def maybe_start_http_server() -> bool:
    """Start the server iff PADDLE_TPU_METRICS_PORT is set and none is
    running. Called from the telemetry hot-path helpers; the unset case
    is a single env dict lookup."""
    return _handle.maybe_start()


def stop_http_server():
    _handle.stop()
