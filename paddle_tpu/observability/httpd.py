"""Live metrics endpoint: a stdlib http.server daemon thread serving the
process's observability state while it trains.

The PR 1 registry is scrapeable only via file dumps
(PADDLE_TPU_METRICS_DIR); a production deployment wants a live pull
target. Routes:

  GET /metrics      Prometheus text exposition of the default registry
  GET /healthz      JSON from health.status(); HTTP 200 while "ok",
                    503 once "degraded" (anomaly-aware, so a k8s
                    liveness/readiness probe sees divergence directly)
  GET /events?n=K[&kind=X]
                    last K events from the in-memory ring, one JSON
                    object per line (newline-delimited JSON)

Env gating: PADDLE_TPU_METRICS_PORT. Unset/empty → no server, no
socket. "0" → bind an ephemeral port (tests); any other integer → that
port. `maybe_start_http_server()` is called from the telemetry hot-path
helpers, so setting the env var before training is enough — nothing is
started at import time (guarded by tests/test_obs_import_cost.py).

Stdlib-only module; binds 127.0.0.1 by default (override with
PADDLE_TPU_METRICS_HOST) — exposing process internals on all interfaces
is an operator decision, not a default.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import events as _events
from . import health as _health
from . import metrics as _m

__all__ = ["start_http_server", "maybe_start_http_server",
           "stop_http_server", "server_port"]

_lock = threading.Lock()
_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None
_atexit_registered = False
_start_failed = False  # remember a failed env-gated bind: the hot path
# calls maybe_start every step and must not retry the syscall forever

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-metrics"

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # scrapes every few seconds must not spam stderr

    def _reply(self, code: int, content_type: str, body: str):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - stdlib naming
        try:
            url = urlparse(self.path)
            if url.path == "/metrics":
                self._reply(200, PROM_CONTENT_TYPE,
                            _m.render_prometheus())
            elif url.path == "/healthz":
                st = _health.status()
                code = 200 if st["status"] == "ok" else 503
                self._reply(code, "application/json",
                            json.dumps(st) + "\n")
            elif url.path == "/events":
                q = parse_qs(url.query)
                try:
                    n = int(q.get("n", ["100"])[0])
                except ValueError:
                    n = 100
                kind = q.get("kind", [None])[0]
                lines = [json.dumps(e, default=str)
                         for e in _events.recent(n=n, kind=kind)]
                self._reply(200, "application/x-ndjson",
                            "\n".join(lines) + ("\n" if lines else ""))
            else:
                self._reply(404, "text/plain",
                            "not found; routes: /metrics /healthz "
                            "/events?n=K\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-reply


def server_port() -> Optional[int]:
    """Bound port of the running server, or None when no server is up."""
    with _lock:
        if _server is None:
            return None
        return _server.server_address[1]


def start_http_server(port: int = 0, host: Optional[str] = None) -> int:
    """Start the daemon serving thread (idempotent: a second call returns
    the already-bound port). port=0 binds an ephemeral port. Returns the
    actual bound port."""
    global _server, _thread, _atexit_registered
    with _lock:
        if _server is not None:
            return _server.server_address[1]
        host = host or os.environ.get("PADDLE_TPU_METRICS_HOST",
                                      "127.0.0.1")
        srv = ThreadingHTTPServer((host, int(port)), _Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="paddle-tpu-metrics-http", daemon=True)
        t.start()
        _server, _thread = srv, t
        if not _atexit_registered:
            import atexit

            atexit.register(stop_http_server)
            _atexit_registered = True
        return srv.server_address[1]


def maybe_start_http_server() -> bool:
    """Start the server iff PADDLE_TPU_METRICS_PORT is set and none is
    running. Called from the telemetry hot-path helpers; the unset case
    is a single env dict lookup."""
    global _start_failed
    raw = os.environ.get("PADDLE_TPU_METRICS_PORT")
    if not raw:
        return False
    with _lock:
        if _server is not None:
            return True
        if _start_failed:
            return False  # port was taken once; don't re-bind every step
    try:
        port = int(raw)
    except ValueError:
        return False  # malformed env must not kill the hot path
    if port < 0:
        return False
    try:
        start_http_server(port)
    except OSError:
        _start_failed = True  # cleared by stop_http_server()
        return False  # port taken: keep training, scraping is best-effort
    return True


def stop_http_server():
    global _server, _thread, _start_failed
    with _lock:
        srv, _server = _server, None
        t, _thread = _thread, None
        _start_failed = False
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if t is not None and t.is_alive():
        t.join(timeout=5)
