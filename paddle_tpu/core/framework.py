"""Python program-construction layer.

Mirrors the reference's python/paddle/fluid/framework.py (Variable :451,
Operator :1517, Block :1966, Program :3349) — the user-facing define-then-run
graph builder. Unlike the reference there is no C++ desc mirror: the dataclass
IR in core/ir.py *is* the single source of truth, and shape inference runs via
jax.eval_shape at append_op time (reference runs InferShape per op at build
and again at run time).
"""

from __future__ import annotations

import contextlib
import itertools
from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from . import ir, registry
from .ir import BlockDesc, OpDesc, ProgramDesc, VarDesc, VarType, normalize_dtype


# ---------------------------------------------------------------------------
# Op roles (reference: framework.py OpRole / op_role attr, used by transpilers)
# ---------------------------------------------------------------------------


class OpRole:
    Forward = 0x0000
    Backward = 0x0001
    Optimize = 0x0002
    RPC = 0x0003
    Dist = 0x0004
    LRSched = 0x0010
    Loss = 0x0100
    OpRoleVarAttrName = "op_role_var"
    AttrName = "op_role"


_global_seed = 0


def set_global_seed(seed: int):
    global _global_seed
    _global_seed = seed


def global_seed() -> int:
    return _global_seed


# ---------------------------------------------------------------------------
# unique_name (reference: python/paddle/fluid/unique_name.py)
# ---------------------------------------------------------------------------


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: Dict[str, itertools.count] = defaultdict(lambda: itertools.count(0))

    def __call__(self, key: str) -> str:
        return f"{self.prefix}{key}_{next(self.ids[key])}"


class _UniqueNameModule:
    """Exposed as `paddle_tpu.unique_name` with generate()/guard() parity."""

    def __init__(self):
        self.generator = UniqueNameGenerator()

    def generate(self, key: str) -> str:
        return self.generator(key)

    @contextlib.contextmanager
    def guard(self, new_generator: Optional[str] = None):
        old = self.generator
        self.generator = UniqueNameGenerator(new_generator or "")
        try:
            yield
        finally:
            self.generator = old


unique_name = _UniqueNameModule()


# ---------------------------------------------------------------------------
# Dygraph mode hook (tracer installed by paddle_tpu.dygraph)
# ---------------------------------------------------------------------------

_dygraph_tracer = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer is not None


def _set_dygraph_tracer(tracer):
    global _dygraph_tracer
    _dygraph_tracer = tracer


def _get_dygraph_tracer():
    return _dygraph_tracer


# ---------------------------------------------------------------------------
# Variable / Parameter
# ---------------------------------------------------------------------------


class Variable:
    """Graph variable handle (reference: framework.py:451)."""

    def __init__(self, block: "Block", desc: VarDesc):
        self.block = block
        self.desc = desc

    # -- desc accessors ------------------------------------------------------
    @property
    def name(self) -> str:
        return self.desc.name

    @property
    def shape(self):
        return tuple(self.desc.shape) if self.desc.shape is not None else None

    @property
    def dtype(self) -> str:
        return self.desc.dtype

    @property
    def type(self) -> str:
        return self.desc.type

    @property
    def persistable(self) -> bool:
        return self.desc.persistable

    @persistable.setter
    def persistable(self, v: bool):
        self.desc.persistable = v

    @property
    def stop_gradient(self) -> bool:
        return self.desc.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v: bool):
        self.desc.stop_gradient = v

    def __repr__(self):
        return (
            f"Variable(name={self.name}, shape={self.shape}, dtype={self.dtype}, "
            f"persistable={self.persistable})"
        )

    __str__ = __repr__

    # -- sugar (operator overloads appended by layers.math_op_patch) ---------
    def astype(self, dtype):
        from .. import layers

        return layers.cast(self, dtype)


class Parameter(Variable):
    """Trainable persistable variable (reference: framework.py:4293)."""

    def __init__(self, block, desc, trainable=True, optimize_attr=None,
                 regularizer=None, do_model_average=False, need_clip=True):
        super().__init__(block, desc)
        desc.persistable = True
        desc.is_parameter = True
        self.trainable = trainable
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}
        self.regularizer = regularizer
        self.do_model_average = do_model_average
        self.need_clip = need_clip


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


def _names(v) -> List[str]:
    if v is None:
        return [""]
    if isinstance(v, (list, tuple)):
        return [_name1(x) for x in v]
    return [_name1(v)]


def _name1(v) -> str:
    if v is None:
        return ""
    if isinstance(v, Variable):
        return v.name
    if isinstance(v, str):
        return v
    raise TypeError(f"expected Variable or str, got {type(v)}")


class Operator:
    """Graph op handle (reference: framework.py:1517). Appending an op infers
    output shapes/dtypes immediately and fills in the output VarDescs."""

    def __init__(self, block: "Block", desc: OpDesc):
        self.block = block
        self.desc = desc

    @property
    def type(self) -> str:
        return self.desc.type

    @property
    def attrs(self):
        return self.desc.attrs

    def attr(self, name):
        return self.desc.attrs.get(name)

    def set_attr(self, name, val):
        self.desc.attrs[name] = val
        # attr mutation changes compiled behavior — invalidate the
        # executor's compiled-step cache like every other mutation
        prog = getattr(self.block, "program", None)
        if prog is not None:
            prog._bump_version()

    _set_attr = set_attr  # reference-compat alias (framework.py Operator)

    def input(self, slot):
        return self.desc.inputs.get(slot, [])

    def output(self, slot):
        return self.desc.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return self.desc.input_names()

    @property
    def output_arg_names(self):
        return self.desc.output_names()

    def __repr__(self):
        return f"Operator(type={self.type}, inputs={self.desc.inputs}, outputs={self.desc.outputs})"


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


class Block:
    """reference: framework.py:1966."""

    def __init__(self, program: "Program", desc: BlockDesc):
        self.program = program
        self.desc = desc
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def idx(self) -> int:
        return self.desc.idx

    @property
    def parent_idx(self) -> int:
        return self.desc.parent_idx

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.desc.parent_idx < 0:
            return None
        return self.program.block(self.desc.parent_idx)

    # -- vars ----------------------------------------------------------------

    def create_var(self, name: Optional[str] = None, shape=None, dtype="float32",
                   type: str = VarType.DENSE_TENSOR, persistable: bool = False,
                   stop_gradient: bool = False, **kw) -> Variable:
        if in_dygraph_mode():
            # eager mode: layers get a VarBase placeholder the tracer fills
            from ..dygraph.varbase import VarBase

            return VarBase(None, name=name, stop_gradient=stop_gradient)
        if name is None:
            name = unique_name.generate("_generated_var")
        if name in self.vars:
            return self.vars[name]
        desc = VarDesc(
            name=name,
            shape=tuple(shape) if shape is not None else None,
            dtype=normalize_dtype(dtype),
            type=type,
            persistable=persistable,
            stop_gradient=stop_gradient,
        )
        self.desc.vars[name] = desc
        v = Variable(self, desc)
        self.vars[name] = v
        return v

    def create_parameter(self, name=None, shape=None, dtype="float32",
                         trainable=True, optimize_attr=None, regularizer=None,
                         do_model_average=False, need_clip=True, **kw) -> Parameter:
        # Parameters live in the *global* block (reference: Block.create_parameter
        # delegates to global block).
        gb = self.program.global_block()
        if name is None:
            name = unique_name.generate("_param")
        desc = VarDesc(name=name, shape=tuple(shape), dtype=normalize_dtype(dtype),
                       persistable=True, is_parameter=True, stop_gradient=False)
        gb.desc.vars[name] = desc
        p = Parameter(gb, desc, trainable=trainable, optimize_attr=optimize_attr,
                      regularizer=regularizer, do_model_average=do_model_average,
                      need_clip=need_clip)
        gb.vars[name] = p
        return p

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError(f"variable '{name}' not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops -----------------------------------------------------------------

    def append_op(self, type: str, inputs: Optional[Dict] = None,
                  outputs: Optional[Dict] = None, attrs: Optional[Dict] = None,
                  stop_gradient: bool = False) -> Operator:
        if in_dygraph_mode():
            return _dygraph_tracer.trace_op(type, inputs or {}, outputs or {}, attrs or {})
        desc = self._make_op_desc(type, inputs, outputs, attrs)
        self._infer_and_fill(desc)
        op = Operator(self, desc)
        self.desc.ops.append(desc)
        self.ops.append(op)
        self.program._bump_version()
        if stop_gradient:
            for n in desc.output_names():
                v = self._find_var_recursive(n)
                if v is not None:
                    v.desc.stop_gradient = True
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        desc = self._make_op_desc(type, inputs, outputs, attrs)
        self._infer_and_fill(desc)
        op = Operator(self, desc)
        self.desc.ops.insert(0, desc)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def _make_op_desc(self, type, inputs, outputs, attrs) -> OpDesc:
        ins = {k: _names(v) for k, v in (inputs or {}).items()}
        outs = {k: _names(v) for k, v in (outputs or {}).items()}
        attrs = dict(attrs or {})
        if OpRole.AttrName not in attrs:
            attrs[OpRole.AttrName] = _current_op_role()
        try:
            opdef = registry.get_op_def(type)
            if opdef.is_random and "__rng_uid__" not in attrs:
                # per-Program counter: two identically-built programs with the
                # same random_seed replay identical random streams
                attrs["__rng_uid__"] = self.program._next_rng_uid()
        except KeyError:
            pass  # allow structural ops unknown to the registry (feed/fetch)
        return OpDesc(type=type, inputs=ins, outputs=outs, attrs=attrs)

    def _infer_and_fill(self, desc: OpDesc):
        """Run generic shape inference and fill output var descs."""
        if not registry.has_op(desc.type):
            return
        if desc.sub_block_ids():
            # control-flow op whose outputs were shaped by the layer: skip —
            # eval_shape would trace the sub-block, which may contain
            # collectives that only lower under shard_map
            outs = [n for n in desc.output_names() if n]
            if all((v := self._find_var_recursive(n)) is not None
                   and v.desc.shape is not None for n in outs):
                return
        input_descs: Dict[str, VarDesc] = {}
        for n in desc.input_names():
            v = self._find_var_recursive(n)
            if v is None:
                raise ValueError(f"op {desc.type}: input var '{n}' not found")
            input_descs[n] = v.desc
        from .lowering import make_infer_lower_block_fn

        inferred = registry.infer_op_outputs(
            desc, input_descs,
            lower_block_fn=make_infer_lower_block_fn(self.program),
            program=self.program,
        )
        for n, sds in inferred.items():
            v = self._find_var_recursive(n)
            if v is None:
                v = self.create_var(name=n)
            v.desc.shape = tuple(int(s) for s in sds.shape)
            v.desc.dtype = normalize_dtype(sds.dtype)

    def __repr__(self):
        lines = [f"Block(idx={self.idx}, parent={self.parent_idx})"]
        for v in self.vars.values():
            lines.append(f"  var {v.name}: {v.shape} {v.dtype}"
                         + (" persistable" if v.persistable else ""))
        for op in self.ops:
            lines.append(f"  op {op.type}: {op.desc.inputs} -> {op.desc.outputs}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------


class Program:
    """reference: framework.py:3349."""

    def __init__(self):
        self.desc = ProgramDesc()
        self.blocks: List[Block] = [Block(self, self.desc.block(0))]
        self._current_block_idx = 0
        self.random_seed = 0
        self._is_test = False
        # arbitrary metadata bag (distributed strategies annotate here)
        self._attrs: Dict[str, Any] = {}
        self._version = 0  # bumped on every mutation → executor cache key
        self._rng_uid = itertools.count(1)

    def _next_rng_uid(self) -> int:
        return next(self._rng_uid)

    # -- blocks --------------------------------------------------------------

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        bdesc = self.desc.append_block(parent)
        b = Block(self, bdesc)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def _rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    # -- iteration helpers ---------------------------------------------------

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters()

    # -- clone / prune / serialization ---------------------------------------

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.desc = self.desc.clone()
        p.random_seed = self.random_seed
        p._attrs = dict(self._attrs)
        p._rebuild_from_desc()
        if for_test:
            p._is_test = True
            for b in p.blocks:
                for op in b.ops:
                    if "is_test" in op.attrs or op.type in _IS_TEST_OPS:
                        op.set_attr("is_test", True)
                    if op.type == "dropout":
                        op.set_attr("is_test", True)
        return p

    def _rebuild_from_desc(self):
        self.blocks = []
        for bdesc in self.desc.blocks:
            b = Block(self, bdesc)
            self.blocks.append(b)
        for b in self.blocks:
            for name, vdesc in b.desc.vars.items():
                if vdesc.is_parameter:
                    b.vars[name] = Parameter(b, vdesc)
                else:
                    b.vars[name] = Variable(b, vdesc)
            b.ops = [Operator(b, od) for od in b.desc.ops]
        self._current_block_idx = 0
        # resume uid allocation past any uid carried in the descs so random
        # ops appended after clone/deserialize don't replay existing streams
        max_uid = max((int(op.attrs.get("__rng_uid__", 0))
                       for b in self.desc.blocks for op in b.ops), default=0)
        self._rng_uid = itertools.count(max_uid + 1)
        self._version += 1

    def to_bytes(self) -> bytes:
        return self.desc.to_bytes()

    @staticmethod
    def parse_from_bytes(data: bytes) -> "Program":
        p = Program()
        p.desc = ProgramDesc.from_bytes(data)
        p._rebuild_from_desc()
        return p

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)

    # mutation marker used by executor program cache
    def _bump_version(self):
        self._version += 1


_IS_TEST_OPS = {"dropout", "batch_norm", "layer_norm_stats"}


# ---------------------------------------------------------------------------
# Default programs + guards (reference: framework.py:4427, program_guard :4507)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()
_op_role_stack: List[int] = []


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(p: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, p
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_start = switch_startup_program(startup_program) if startup_program is not None else None
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_start is not None:
            switch_startup_program(old_start)


def _current_op_role() -> int:
    return _op_role_stack[-1] if _op_role_stack else OpRole.Forward


@contextlib.contextmanager
def op_role_guard(role: int):
    _op_role_stack.append(role)
    try:
        yield
    finally:
        _op_role_stack.pop()


def grad_var_name(name: str) -> str:
    return ir.grad_var_name(name)
