"""SelectedRows — the sparse row-slice gradient value.

Reference: framework/selected_rows.h:32 — `{height, rows[], value[N,D]}`,
the representation embedding gradients take so optimizers touch only the
rows a batch used (math/selected_rows_functor.cc merge/add; sparse
branches in sgd_op/adam_op). TPU-native form: a registered pytree of
(rows, ids) with the table height static, flowing through the lowered
program like any other value — lookup_table's custom grad emits it when
`is_sparse`, the `sum` op concatenates row sets, and the sgd/momentum/
adam kernels apply true row-sparse updates (duplicates handled by a
sort + segment-sum merge, exactly the reference's merge_add + per-row
apply, but with static shapes for XLA).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


class SelectedRows:
    """rows [N, D] values at int32 ids [N] of a [height, D] table."""

    def __init__(self, rows: jax.Array, ids: jax.Array, height: int):
        self.rows = rows
        self.ids = ids
        self.height = int(height)

    @property
    def dtype(self):
        return self.rows.dtype

    def astype(self, dt) -> "SelectedRows":
        return SelectedRows(self.rows.astype(dt), self.ids, self.height)

    def to_dense(self) -> jax.Array:
        """Scatter-add into the dense [height, D] gradient."""
        out = jnp.zeros((self.height,) + self.rows.shape[1:],
                        self.rows.dtype)
        return out.at[self.ids].add(self.rows)

    def merged(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(ids, rows, is_first): duplicates summed into the FIRST
        occurrence slot (static shapes — the reference's merge_add).
        Non-first slots keep their id but carry zero rows and
        is_first=False; scatters should drop them via the masked-id
        trick (see masked_ids)."""
        order = jnp.argsort(self.ids)
        sid = self.ids[order]
        srows = self.rows[order]
        is_first = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sid[1:] != sid[:-1]])
        seg = jnp.cumsum(is_first) - 1
        summed = jax.ops.segment_sum(srows, seg,
                                     num_segments=self.ids.shape[0])
        rows = jnp.where(is_first[:, None], summed[seg], 0.0)
        return sid, rows.astype(self.rows.dtype), is_first

    def masked_ids(self, ids, keep) -> jax.Array:
        """ids with non-kept slots pushed out of bounds: scatters in
        mode='drop' then touch only the kept rows."""
        return jnp.where(keep, ids, self.height)


def _flatten(sr: SelectedRows):
    return (sr.rows, sr.ids), sr.height


def _unflatten(height, children):
    rows, ids = children
    return SelectedRows(rows, ids, height)


jax.tree_util.register_pytree_node(SelectedRows, _flatten, _unflatten)


def is_selected_rows(v) -> bool:
    return isinstance(v, SelectedRows)


def to_dense(v):
    return v.to_dense() if isinstance(v, SelectedRows) else v
