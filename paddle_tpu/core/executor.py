"""Scope + Executor.

Reference: `Scope` (paddle/fluid/framework/scope.h:46) is a hierarchical
name→Variable map; `Executor::Run` (framework/executor.cc:178) interprets a
block op-by-op against it. Here the executor *compiles* the whole program:
scope reads become jit inputs, scope writes become jit outputs
(core/lowering.py), and the compiled step is cached per
(program, feed-signature, fetch-list) — the role of the reference's
ExecutorPrepareContext cache (executor.py:831 program cache).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import (Any, Dict, Iterable, List, Optional, Sequence, Tuple,
                    Union)

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import health as _health
from ..observability import memwatch as _memwatch
from ..observability import perfwatch as _perfwatch
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing
from . import async_exec, compile_cache, framework, lowering
from . import precision as _precision
from .framework import Program, Variable
from .ir import normalize_dtype
from .places import CPUPlace, Place, default_place

RNG_STATE_VAR = "__rng_state__"


# ---------------------------------------------------------------------------
# Compile introspection
# ---------------------------------------------------------------------------


def _compile_cost(compiled) -> Tuple[Optional[float], Optional[int]]:
    """(flops, output bytes) from an AOT executable's cost/memory
    analysis; either is None when the backend doesn't report it."""
    flops = out_bytes = None
    try:
        ca = compiled.cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) and ca else ca
        if isinstance(d, dict) and d.get("flops", -1) >= 0:
            flops = float(d["flops"])
    except Exception:  # lint-exempt:swallow: cost_analysis is backend-optional introspection
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out_bytes = int(getattr(ma, "output_size_in_bytes", 0))
    except Exception:  # lint-exempt:swallow: memory_analysis is backend-optional introspection
        pass
    return flops, out_bytes


def _executable_cost(compiled) -> Dict[str, Optional[float]]:
    """Retained per-signature cost/memory analysis of an AOT
    executable — the live-MFU numerator (observability/perfwatch.py)
    and the executables line of the HBM attribution
    (observability/memwatch.py). Works on deserialized compile-cache /
    warmstart executables too, so adopted executables are not blind
    spots. Missing fields are None (backend-optional introspection)."""
    flops, out_bytes = _compile_cost(compiled)
    cost: Dict[str, Optional[float]] = {
        "flops": flops, "out_bytes": out_bytes,
        "temp_bytes": None, "code_bytes": None, "arg_bytes": None}
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            cost["temp_bytes"] = int(getattr(
                ma, "temp_size_in_bytes", 0))
            cost["code_bytes"] = int(getattr(
                ma, "generated_code_size_in_bytes", 0))
            cost["arg_bytes"] = int(getattr(
                ma, "argument_size_in_bytes", 0))
    except Exception:  # lint-exempt:swallow: memory_analysis is backend-optional introspection
        pass
    return cost


# every _JitDispatch alive in the process: memwatch sums their retained
# generated-code bytes into the `executables` HBM line at sweep time
_live_dispatches: "weakref.WeakSet" = weakref.WeakSet()


def _live_executable_bytes() -> Tuple[int, int]:
    """(generated-code bytes, executable count) over live dispatch
    wrappers' retained signatures — the memwatch executables
    provider."""
    total = count = 0
    for disp in list(_live_dispatches):
        for cost in list(disp._cost_by_sig.values()):
            count += 1
            total += int(cost.get("code_bytes") or 0)
    return total, count


_memwatch.set_executables_provider(_live_executable_bytes)


_JIT_FALLBACK = object()  # sentinel: AOT redispatch failed, use plain jit


def mesh_device_kind(mesh) -> str:
    """device_kind of a jax Mesh's first device — the compile-cache /
    warmstart environment-binding component for sharded executables.
    One definition so compiler.py and spmd_executor.py cannot drift."""
    return getattr(next(iter(mesh.devices.flat), None),
                   "device_kind", "unknown")


class _JitDispatch:
    """A jitted callable that AOT-compiles on first dispatch so the
    compile itself is observable: wall seconds land in
    `paddle_tpu_compile_seconds{kind}`, the executable's cost_analysis()
    FLOPs in `paddle_tpu_compile_flops{kind}`, and a `compile` event in
    the JSONL log. Falls back to the plain jit path — which compiles
    transparently — if AOT lowering fails or a later call's avals drift
    from the compiled signature (jax raises TypeError before executing,
    so donated buffers are untouched).

    With PADDLE_TPU_COMPILE_CACHE set, warm()/first-dispatch consults
    the persistent compile cache (core/compile_cache.py) before
    compiling: a hit deserializes the stored executable (I/O, not XLA),
    a miss compiles and persists for the next process. AOT outcomes are
    remembered PER SIGNATURE (`_tried_sig`): after an AOT failure or a
    signature drift, a warm()/dispatch with new avals retries instead of
    being locked out — a reshaped serving bucket must still get its AOT
    executable.

    `policy` names the precision policy the wrapped computation was
    built under (core/precision.py). It is part of the aval SIGNATURE
    and of the persistent compile-cache fingerprint: a policy flip can
    never be served an executable compiled under the old policy — it
    misses and recompiles instead."""

    # executables already built for a signature, kept so alternating
    # shapes on ONE wrapper (SPMD partial final batch each epoch) swap
    # executables instead of re-paying an AOT compile per alternation
    _AOT_SIG_CAP = 8

    def __init__(self, jit_fn, kind: str, meta: Optional[Dict] = None,
                 policy: Optional[str] = None):
        self._jit = jit_fn
        self._kind = kind
        self._policy = str(policy) if policy else "f32"
        if self._policy != "f32":
            meta = dict(meta or {}, policy=self._policy)
        self._meta = meta
        self._aot = None
        self._tried = False
        self._tried_sig = None
        self._aot_by_sig: "OrderedDict[Tuple, Any]" = OrderedDict()
        # retained cost/memory analysis per compiled signature: the
        # live-MFU numerator reads the INSTALLED signature's FLOPs on
        # every recorded step without touching the executable again
        self._cost_by_sig: Dict[Tuple, Dict] = {}
        self._cost_current: Optional[Dict] = None
        self._compile_lock = threading.Lock()
        self._recorded_jit_compiles = 0
        _live_dispatches.add(self)

    def _aval_sig(self, args) -> Tuple:
        """Hashable shape/dtype signature of a warm()/call argument
        tuple — what decides whether a past AOT attempt covers these
        avals. Leads with the precision policy: two executables for the
        same avals under different policies are different programs."""
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (self._policy, treedef, tuple(
            (tuple(getattr(leaf, "shape", ()) or ()),
             str(getattr(leaf, "dtype", type(leaf).__name__)))
            for leaf in leaves))

    def cache_fingerprint(self, lowered) -> Optional[str]:
        """Persistent compile-cache key for `lowered` under this
        wrapper's precision policy — the policy is key material, so a
        flipped policy always misses instead of deserializing the old
        policy's executable (used by warm() and the serving warmstart
        bake/adopt pair, which must agree byte-for-byte). The default
        f32 policy contributes NO extra key material so f32 keys stay
        byte-identical to the pre-policy (PR 6) keys — upgrading must
        not invalidate every warm cache dir and baked artifact."""
        return compile_cache.fingerprint(
            lowered,
            extra=None if self._policy == "f32" else self._policy)

    def lower(self, *args, **kw):
        return self._jit.lower(*args, **kw)

    def _cache_size(self) -> int:
        """Executables compiled for this callable (AOT + any jit-cache
        fallbacks) — keeps the no-recompile assertions
        (test_step2_recompiles_nothing) meaningful across the AOT path."""
        return (1 if self._aot is not None else 0) + \
            self._jit._cache_size()

    def __getattr__(self, name):
        # only reached for attrs not on the wrapper; avoid recursing if
        # _jit itself is missing (e.g. mid-unpickle)
        return getattr(object.__getattribute__(self, "_jit"), name)

    def warm(self, *args) -> bool:
        """AOT-compile for the given avals (concrete arrays or
        jax.ShapeDtypeStructs) without executing — serving warmup
        compiles every traffic bucket before the first request lands.
        Records the same compile telemetry as a first dispatch; no-op
        once compiled (or once AOT already failed FOR THESE AVALS — a
        new signature retries, so a reshaped bucket can still AOT).
        Consults the persistent compile cache first when enabled: a hit
        installs the deserialized executable and records cache (not
        compile) telemetry, because no XLA compile happened. Returns
        whether an AOT executable is in place. Double-checked lock:
        concurrent first dispatches (HogwildWorker threads on a shared
        executor) must compile ONCE, with the second thread waiting
        rather than jit-compiling a duplicate."""
        sig = self._aval_sig(args)
        if self._tried and sig == self._tried_sig:
            return self._aot is not None
        with self._compile_lock:
            if self._tried and sig == self._tried_sig:
                return self._aot is not None
            remembered = self._aot_by_sig.get(sig)
            if remembered is not None:
                # a signature this wrapper already compiled (drifted
                # away and came back): swap executables, no XLA
                self._aot_by_sig.move_to_end(sig)
                self._aot = remembered
                self._cost_current = self._cost_by_sig.get(sig)
                self._tried, self._tried_sig = True, sig
                return True
            t0 = time.perf_counter()
            aot = None
            try:
                lowered = self._jit.lower(*args)
                key = (self.cache_fingerprint(lowered)
                       if compile_cache.enabled() else None)
                if key:
                    aot = compile_cache.load(key, self._kind)
                if aot is None:
                    aot = lowered.compile()
                    seconds = time.perf_counter() - t0
                    flops, out_bytes = _compile_cost(aot)
                    _telemetry.record_compile(self._kind, seconds,
                                              flops=flops,
                                              out_bytes=out_bytes,
                                              meta=self._meta)
                    if key:
                        compile_cache.store(key, aot, self._kind)
            except Exception:
                aot = None  # jit path compiles on dispatch
            self._aot = aot
            if aot is not None:
                self._remember_locked(sig, aot)
            self._tried, self._tried_sig = True, sig
        return self._aot is not None

    def _remember_locked(self, sig, executable):
        """Record sig -> executable + its retained cost/memory analysis
        (caller holds _compile_lock). Cost retention covers every
        install path — fresh compile, persistent-cache hit, warmstart
        adopt — so the live-MFU numerator never goes dark on a path
        that skipped XLA."""
        self._aot_by_sig[sig] = executable
        self._aot_by_sig.move_to_end(sig)
        self._cost_by_sig[sig] = _executable_cost(executable)
        self._cost_current = self._cost_by_sig[sig]
        while len(self._aot_by_sig) > self._AOT_SIG_CAP:
            old, _ = self._aot_by_sig.popitem(last=False)
            self._cost_by_sig.pop(old, None)

    def current_cost(self) -> Optional[Dict]:
        """Cost/memory analysis of the currently installed executable
        (None on the plain-jit fallback path): flops, out_bytes,
        temp_bytes, code_bytes, arg_bytes — fields None when the
        backend doesn't report them."""
        return self._cost_current

    def adopt(self, executable, *args) -> bool:
        """Install a pre-built executable (deserialized from a
        warmstart artifact) as if warm(*args) had just compiled it —
        the serving boot path where even the cache lookup's lowering
        cost is skipped. `args` must be the avals warm() would have
        been called with, so later warm() calls recognize the
        signature as covered."""
        with self._compile_lock:
            self._aot = executable
            self._tried = True
            self._tried_sig = self._aval_sig(args) if args else None
            if self._tried_sig is not None:
                self._remember_locked(self._tried_sig, executable)
        return True

    def _dispatch_after_drift(self, args):
        """The installed AOT executable raised TypeError/ValueError
        before executing `args` — either signature drift (these avals
        differ from the installed signature) or a genuinely
        incompatible input (e.g. committed to another device;
        _aval_sig ignores placement). Re-resolve an executable for
        THIS call's own signature and run it: a signature this wrapper
        already compiled is an _aot_by_sig dict swap, a new one warms
        through the persistent cache / XLA — so alternating shapes
        (SPMD partial final batch, reshaped serving buckets) never
        re-pay a compile per alternation. Every shared-state decision
        keys on this call's own sig, never the shared _tried_sig:
        concurrent threads (HogwildWorker) drift independently and
        must not evict each other's live executables. Returns
        _JIT_FALLBACK when the signature's own executable fails too —
        after evicting it and latching the signature, so a
        persistently bad executable pays exceptions once, not per
        hot-path call."""
        sig = self._aval_sig(args)
        with self._compile_lock:
            exe = self._aot_by_sig.get(sig)
            if exe is not None:
                self._aot_by_sig.move_to_end(sig)
                self._aot = exe
                self._cost_current = self._cost_by_sig.get(sig)
                self._tried, self._tried_sig = True, sig
        if exe is None and self.warm(*args):
            with self._compile_lock:
                exe = self._aot_by_sig.get(sig)
        if exe is not None:
            try:
                return exe(*args)
            except (TypeError, ValueError):
                with self._compile_lock:
                    self._aot_by_sig.pop(sig, None)
                    if self._tried_sig == sig:
                        self._aot = None
                        self._tried = True
        return _JIT_FALLBACK

    def __call__(self, *args):
        # OOM interceptor: a RESOURCE_EXHAUSTED raised by any dispatch
        # path (AOT, drift re-resolve, plain-jit fallback) dumps the
        # ranked per-owner HBM report + `oom` event before re-raising —
        # free on the happy path (one try frame, no work)
        try:
            return self._dispatch(*args)
        except Exception as e:
            _memwatch.maybe_handle_oom(self._kind, e)
            raise

    def _dispatch(self, *args):
        if not self._tried:
            self.warm(*args)
        elif self._aot is None and self._aval_sig(args) != self._tried_sig:
            # a past AOT failure latched _aot=None at _tried_sig, but
            # THIS call's signature is a different one: re-warm
            # (remembered signatures are a dict swap; cost only lands
            # on the already-degraded path) so one bad signature
            # doesn't strand every other signature's executable on
            # plain jit — the class contract is that new avals retry
            self.warm(*args)
        if self._aot is not None:
            try:
                return self._aot(*args)
            except (TypeError, ValueError):
                # raised before execution: TypeError for aval/dtype
                # mismatch, ValueError for sharding/committed-device
                # mismatch (jax 0.4.x) — donated buffers untouched
                out = self._dispatch_after_drift(args)
                if out is not _JIT_FALLBACK:
                    return out
        # jit path: compiles transparently inside the call, so detect a
        # fresh executable via the cache-size growth and time the call —
        # compile-dominated when a compile happened. Keeps
        # paddle_tpu_compiles_total honest after AOT failure/fallback
        # (the recompile-storm signal must not go dark). The high-water
        # mark makes concurrent dispatchers that blocked on the SAME
        # compile record it once, not once per waiting thread.
        t0 = time.perf_counter()
        out = self._jit(*args)
        after = self._jit._cache_size()
        if after > self._recorded_jit_compiles:
            with self._compile_lock:
                if after > self._recorded_jit_compiles:
                    self._recorded_jit_compiles = after
                    _telemetry.record_compile(
                        self._kind, time.perf_counter() - t0,
                        meta=dict(self._meta or {}, jit_fallback=True))
        return out


def _health_scan(site: str, named_values, level: int):
    """Device-side prefilter in front of health.check_numerics: reduce
    isfinite (and the optional |x| threshold) ON DEVICE so the per-step
    cost is one scalar transfer per float var — only arrays that are
    actually suspect get downloaded to host for nan/inf classification.
    (The pre-health FLAGS_check_nan_inf code had the same shape; the
    health layer keeps the counting/event/raise semantics.)"""
    suspects = []
    thresh = _health.max_abs()
    for n, v in named_values:
        if v is None:
            continue
        try:
            arr = jnp.asarray(v)
        except (TypeError, ValueError):
            continue
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        bad = not bool(jnp.isfinite(arr).all())
        if not bad and thresh is not None and arr.size:
            bad = bool(jnp.abs(arr).max() > thresh)
        if bad:
            suspects.append((n, v))
    # always called (even with no suspects) so the sweep counter ticks
    _health.check_numerics(site, suspects, level=level)


def _post_step_health(writes, fetch_names, fetches, scope):
    """Shared post-step epilogue for Executor.run / run_chained /
    CompiledProgram._run: resolve the check level (legacy
    FLAGS_check_nan_inf forces raise semantics), scan written states +
    fetches, and sample the device-memory gauge. One definition so the
    level semantics and scan sites cannot drift between run paths."""
    from .flags import get_flag

    level = 2 if get_flag("FLAGS_check_nan_inf") \
        else _health.check_level()
    if level:
        _health_scan("executor_state",
                     ((n, scope.find_var(n)) for n in writes), level)
        _health_scan("executor_fetch", zip(fetch_names, fetches), level)
    if _health.introspection_enabled():
        _record_live_device_memory()


_MEM_SWEEP_MIN_INTERVAL_S = 5.0
_last_mem_sweep = [0.0]  # monotonic seconds of the last live_arrays walk


def _record_live_device_memory():
    """Gauge live device-buffer bytes. Only called when observability
    is enabled (health.introspection_enabled), and rate-limited: the
    sweep walks every live jax.Array, which on a big model costs more
    per step than any scraper can use — gauges are sampled on
    seconds-scale intervals anyway. The walk itself lives in
    observability/memwatch.py, which attributes each buffer to its
    registered owner (KV pool, params, optimizer state, other) and
    keeps the legacy paddle_tpu_device_live_bytes totals in sync."""
    now = time.monotonic()
    if now - _last_mem_sweep[0] < _MEM_SWEEP_MIN_INTERVAL_S:
        return
    _last_mem_sweep[0] = now
    _memwatch.sweep(force=True)


class Scope:
    """Hierarchical variable store (reference: framework/scope.h:46)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self.parent = parent
        self.kids: List[Scope] = []

    def var(self, name: str):
        if name not in self._vars:
            self._vars[name] = None
        return self._vars[name]

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def set_var(self, name: str, value):
        self._vars[name] = value

    def erase(self, names: Sequence[str]):
        for n in names:
            self._vars.pop(n, None)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def drop_kids(self):
        self.kids.clear()

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    # numpy convenience used everywhere in tests
    def get(self, name: str) -> np.ndarray:
        v = self.find_var(name)
        if v is None:
            raise KeyError(f"variable '{name}' not found in scope")
        return np.asarray(v)


_global_scope = Scope()
_scope_stack: List[Scope] = []


def global_scope() -> Scope:
    return _scope_stack[-1] if _scope_stack else _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


def _as_fetch_name(f) -> str:
    if isinstance(f, Variable):
        return f.name
    return str(f)


@functools.lru_cache(maxsize=None)
def _canonical_dtype_cached(want: str, x64: bool) -> np.dtype:
    from jax import dtypes as _jdt

    del x64  # part of the cache key only: canonicalization depends on it
    return np.dtype(_jdt.canonicalize_dtype(np.dtype(want)))


def _canonical_dtype(want) -> np.dtype:
    """Feed-normalization target dtype, canonicalized to jax's x64
    state. Without this, an int64-declared feed under 32-bit jax costs
    an astype (plus a truncation warning) EVERY step on the hot path,
    only for jnp to hand back int32 anyway. Cached per (dtype, x64
    flag) — this runs once per feed var per step on every run path."""
    return _canonical_dtype_cached(np.dtype(want).str,
                                   bool(jax.config.jax_enable_x64))


# run_stream unrolls its windows (straight-line XLA ~2x a rolled scan
# on CPU conv bodies) only up to this size — unroll compile time grows
# with n_steps, and past this the amortization no longer pays for it.
_UNROLL_WINDOW_MAX = 32


def _chained_cache_limit() -> int:
    """Per-program bound on cached chained executables (PADDLE_TPU_
    CHAINED_CACHE, default 8): every (n_steps, per_step_feeds) key is a
    full XLA executable, so an unbounded map under a driver that varies
    its window size is a memory leak with a compile bill attached."""
    raw = os.environ.get("PADDLE_TPU_CHAINED_CACHE")
    if not raw:
        return 8
    try:
        return max(1, int(raw))
    except ValueError:
        return 8


def _feed_signature(feed: Dict[str, Any]) -> Tuple:
    """Shape/dtype signature of a feed dict — what decides whether two
    per-step feeds can share a stacked window / compiled step."""
    return tuple(sorted(
        (k, tuple(getattr(v, "shape", ())),
         str(getattr(v, "dtype", type(v).__name__)))
        for k, v in feed.items()))


def _stack_feed_window(feeds: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Collate same-signature per-step feeds with a leading [n] axis.
    Host-resident windows take one memcpy + ONE transfer at dispatch
    (np.stack) instead of K per-item transfers + a device concat;
    device-resident (prefetched) values stay on device (jnp.stack)."""
    def _stack(vals):
        if all(isinstance(v, np.ndarray) for v in vals):
            return np.stack(vals)
        return jnp.stack(vals)

    return {k: _stack([f[k] for f in feeds]) for k in feeds[0]}


def _pre_run_validate(program: Program, feed_names, fetch_names,
                      policy, where: str):
    """Env-gated static analysis in front of every run path
    (PADDLE_TPU_VALIDATE=0|1|2 — off/warn/error; paddle_tpu/analysis).
    The env probe keeps the default hot path at one dict lookup and the
    analysis package entirely unimported; when enabled, results are
    cached per (program version, run signature) so a steady-state loop
    pays for exactly one walk."""
    if not os.environ.get("PADDLE_TPU_VALIDATE"):
        return
    from ..analysis import maybe_validate

    maybe_validate(program, feed_names=feed_names,
                   fetch_names=fetch_names, policy=policy, where=where)


def _normalize_feed(program: Program, feed: Dict[str, Any],
                    policy: Optional["_precision.PrecisionPolicy"] = None
                    ) -> Dict[str, Any]:
    """Feed normalization shared by every run path (Executor._lookup_
    step, CompiledProgram._run, SPMDRunner.run): device-transfer via
    jnp.asarray and cast to the var's declared dtype, canonicalized to
    jax's x64 state — except that under a non-f32 precision policy
    FLOATING feeds target the policy's compute dtype instead of the
    declared one. That kills the silent upcast on the stream hot path:
    a bf16 feed under a bf16/mixed_bf16 policy already matches the
    target and is passed through with no astype at all."""
    if policy is None:
        policy = _precision.resolve(program)
    norm_feed = {}
    for name, val in feed.items():
        vdesc = None
        for b in program.desc.blocks:
            if name in b.vars:
                vdesc = b.vars[name]
                break
        arr = jnp.asarray(val)
        if vdesc is not None:
            want = policy.feed_dtype(
                _canonical_dtype(normalize_dtype(vdesc.dtype)))
            if arr.dtype != want:
                arr = arr.astype(want)
        norm_feed[name] = arr
    return norm_feed


def _finish_fetches(fetches, return_numpy: bool, sync: bool,
                    site: str = "executor"):
    """Shared fetch epilogue for every run path. sync=False wraps the
    device arrays in a lazy FetchHandle (nothing touches the host until
    .result()). sync=True with return_numpy forces the classic
    synchronous fetch — instrumented as host-blocked time, which is
    exactly the per-step round trip the async paths exist to hide.
    return_numpy=False returns the device arrays untouched."""
    if not sync:
        return async_exec.FetchHandle(fetches, site=site)
    if not return_numpy:
        return list(fetches)
    t0 = time.perf_counter()
    try:
        jax.block_until_ready(fetches)
    except Exception as e:  # lint-exempt:swallow: non-array fetches (rare lowering paths) convert below
        # an async device OOM surfaces HERE, not at dispatch: dump the
        # forensics before the conversion below re-raises it
        _memwatch.maybe_handle_oom(site, e)
    out = [np.asarray(f) for f in fetches]
    _telemetry.record_host_blocked("executor_sync",
                                   time.perf_counter() - t0, stall=False)
    return out


class _CompiledStep:
    """One jitted program specialization, built under ONE precision
    policy: a pure-bf16 policy casts floating state to the compute
    dtype at step entry (inside the jit — params stay bf16 on device
    thereafter, so the cast is a one-time signature transition), a
    mixed policy activates the lowering-time op autocast instead and
    leaves master state f32."""

    def __init__(self, program: Program, feed_names: Tuple[str, ...],
                 fetch_names: Tuple[str, ...], is_test: bool,
                 policy: Optional["_precision.PrecisionPolicy"] = None):
        desc = program.desc
        policy = policy if policy is not None \
            else _precision.resolve(program)
        self.policy = policy
        reads, writes = lowering.analyze_state_vars(desc, set(feed_names))
        persistable = {
            v.name
            for b in desc.blocks
            for v in b.vars.values()
            if v.persistable
        }
        for n in fetch_names:
            if n in persistable and n not in reads and n not in writes:
                reads.append(n)
        self.const_reads = tuple(n for n in reads if n not in writes)
        self.mut_reads = tuple(n for n in reads if n in writes)
        self.writes = tuple(writes)
        self.fetch_names = fetch_names
        self.feed_names = feed_names

        def step(feeds, const_states, mut_states, rng):
            env = dict(const_states)
            env.update(mut_states)
            env.update(feeds)
            if policy.cast_state:
                # pure low-precision: state joins the compute width; the
                # first step's f32->bf16 casts compile once, thereafter
                # the scope holds bf16 arrays and the cast is a no-op
                env = {k: _precision.cast_floating(v, policy.compute_dtype)
                       for k, v in env.items()}
            step_key, new_rng = jax.random.split(rng)
            with _precision.autocast(policy):
                lowering.lower_block(desc, 0, env, rng_key=step_key,
                                     is_test=is_test)
            fetches = []
            for n in fetch_names:
                if n not in env:
                    raise lowering.LoweringError(
                        f"fetch var '{n}' was not produced by the program")
                fetches.append(env[n])
            new_states = {n: env[n] for n in self.writes if n in env}
            return fetches, new_states, new_rng

        # mut_states (param updates) are donated: in-place on device, the
        # reference's overwrite-in-scope semantics without a copy.
        self._step = step
        self.fn = _JitDispatch(
            jax.jit(step, donate_argnums=(2,)), "step",
            meta={"fetches": len(fetch_names), "writes": len(writes)},
            policy=policy.name)
        # LRU-bounded: each entry is a whole XLA executable (see
        # _chained_cache_limit); evictions are counted in the registry.
        # Key: (n_steps, per_step_feeds, unroll).
        self._chained: "OrderedDict[Tuple[int, bool, bool], Any]" = \
            OrderedDict()
        self._last_chained_fn: Optional[_JitDispatch] = None

    def chained_cost(self) -> Optional[Dict]:
        """Retained cost analysis of the last chained dispatch used by
        run_chained — note its FLOPs cover the WHOLE n_steps window,
        matching the one wall-time window run_chained records."""
        fn = self._last_chained_fn
        return fn.current_cost() if fn is not None else None

    def chained_fn(self, n_steps: int, per_step_feeds: bool = False,
                   unroll="auto", platform: Optional[str] = None):
        """n_steps program iterations scan-chained in ONE executable.
        Amortizes the fixed per-invocation dispatch/host-tunnel cost
        (~100 ms on tunneled backends, PROFILE.md) so repeated-step
        timing measures framework+compute, not transport. With
        per_step_feeds, each feed carries a leading [n_steps] axis and
        the scan consumes one slice per iteration — a whole data chunk
        trains in ONE dispatch (the fast path under
        train_from_dataset's batch loop). Reference analogue: the C++
        executor's prepared-context replay loop (executor.py:418
        ExecutorPrepareContext).

        `unroll` unrolls the scan body: XLA optimizes the window as
        straight-line code (on CPU a conv inside the rolled while-loop
        runs ~2x slower than the same conv inlined), trading compile
        time proportional to n_steps. The streaming driver uses it for
        its small windows. "auto" resolves per backend: unrolled on CPU
        (up to _UNROLL_WINDOW_MAX — the rolled while-loop is the
        BENCH_r05 2.6x per-step regression, reproduced by a pure-jax
        control, so it is opt-in there), rolled elsewhere (one bounded
        compile, no CPU penalty applies)."""
        if unroll == "auto":
            # resolve against the EXECUTING device's platform when the
            # caller supplies it (run_chained passes the place's) — a
            # CPUPlace executor on a TPU-default host must still get
            # the unrolled CPU path
            unroll = ((platform or jax.default_backend()) == "cpu"
                      and n_steps <= _UNROLL_WINDOW_MAX)
        key = (n_steps, per_step_feeds, bool(unroll))
        fn = self._chained.get(key)
        if fn is not None:
            self._chained.move_to_end(key)
            return fn
        step = self._step
        mut_keys = set(self.mut_reads)

        def chained(feeds, const_states, mut_states, rng):
            def split(new_states, mut):
                merged = dict(mut)
                merged.update({k: v for k, v in new_states.items()
                               if k in mut_keys})
                rest = {k: v for k, v in new_states.items()
                        if k not in mut_keys}
                return merged, rest

            def feeds_at(i):
                if not per_step_feeds:
                    return feeds
                return {k: v[i] for k, v in feeds.items()}

            # step 1 runs outside the scan: write-only states don't exist
            # before it, and the scan carry needs their fixed structure.
            # Carrying them (instead of stacking as scan ys) keeps memory
            # O(1) in n_steps — only the final value is observable in the
            # scope, exactly like sequential execution.
            fetches0, new0, rng1 = step(feeds_at(0), const_states,
                                        mut_states, rng)
            mut1, rest1 = split(new0, mut_states)

            def body(carry, i):
                mut, rest, r = carry
                del rest  # fully replaced: new_rest has the same key set
                fetches, new_states, new_r = step(feeds_at(i),
                                                  const_states, mut, r)
                merged, new_rest = split(new_states, mut)
                return (merged, new_rest, new_r), fetches

            (mut_f, rest_f, rng_f), ys = jax.lax.scan(
                body, (mut1, rest1, rng1),
                jnp.arange(1, n_steps), length=n_steps - 1,
                unroll=bool(unroll))
            stacked = jax.tree_util.tree_map(
                lambda f0, fs: jnp.concatenate([f0[None], fs]),
                fetches0, ys)
            new_states = dict(mut_f)
            new_states.update(rest_f)
            return stacked, new_states, rng_f

        # donate mut_states AND the rng key: together with `rest`
        # (created inside) that is the whole scan carry, so XLA can
        # alias every carry component in place of an input buffer
        fn = _JitDispatch(
            jax.jit(chained, donate_argnums=(2, 3)), "chained",
            meta={"n_steps": int(n_steps),
                  "per_step_feeds": bool(per_step_feeds),
                  "unroll": bool(unroll)},
            policy=self.policy.name)
        self._chained[key] = fn
        limit = _chained_cache_limit()
        while len(self._chained) > limit:
            self._chained.popitem(last=False)
            _telemetry.record_chained_eviction()
        return fn

    def run_chained(self, scope: Scope, feed: Dict[str, Any], rng,
                    n_steps: int, per_step_feeds: bool = False,
                    unroll=False, platform: Optional[str] = None):
        """Like __call__ but n_steps scan-chained; fetches come back
        stacked along a leading [n_steps] axis. With per_step_feeds,
        each feed value carries its own leading [n_steps] axis and step
        i consumes slice i. unroll="auto" picks per backend (see
        chained_fn); on CPU with n_steps beyond the unroll cap the run
        is split into unrolled windows instead of rolling the scan."""
        plat = platform or jax.default_backend()
        if unroll == "auto" and plat == "cpu" \
                and n_steps > _UNROLL_WINDOW_MAX:
            return self._run_chained_windowed(scope, feed, rng, n_steps,
                                              per_step_feeds)
        const_states, mut_states = self._gather_states(scope)
        fn = self.chained_fn(n_steps, per_step_feeds, unroll,
                             platform=plat)
        self._last_chained_fn = fn
        fetches, new_states, new_rng = fn(feed, const_states,
                                          mut_states, rng)
        for n, v in new_states.items():
            scope.set_var(n, v)
        return fetches, new_rng

    def _run_chained_windowed(self, scope: Scope, feed, rng,
                              n_steps: int, per_step_feeds: bool):
        """CPU fallback for big chained runs: XLA-CPU executes convs
        inside a rolled while-loop ~2.6x slower than straight-line
        code (BENCH_r05's scan-chained regression; a pure-jax
        loop-vs-scan control reproduces it, so it is the backend, not
        lost donation), so n_steps is split into <=_UNROLL_WINDOW_MAX
        unrolled windows — identical sequential semantics and rng
        stream, a handful of dispatches instead of one (dispatch
        overhead on CPU is microseconds, not the tunnel's ~100ms)."""
        out_chunks: Optional[List[List[Any]]] = None
        done = 0
        while done < n_steps:
            n = min(_UNROLL_WINDOW_MAX, n_steps - done)
            chunk = feed if not per_step_feeds else \
                {k: v[done:done + n] for k, v in feed.items()}
            const_states, mut_states = self._gather_states(scope)
            fn = self.chained_fn(n, per_step_feeds, True)
            self._last_chained_fn = fn
            fetches, new_states, rng = fn(chunk, const_states,
                                          mut_states, rng)
            for name, v in new_states.items():
                scope.set_var(name, v)
            if out_chunks is None:
                out_chunks = [[f] for f in fetches]
            else:
                for lst, f in zip(out_chunks, fetches):
                    lst.append(f)
            done += n
        fetches = [jnp.concatenate(ch) if len(ch) > 1 else ch[0]
                   for ch in (out_chunks or [])]
        return fetches, rng

    def _gather_states(self, scope: Scope):
        const_states = {}
        for n in self.const_reads:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"variable '{n}' is read by the program but missing from "
                    f"the scope — run the startup program first")
            const_states[n] = v
        mut_states = {}
        for n in self.mut_reads:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"variable '{n}' is updated in place but missing from the "
                    f"scope — run the startup program first")
            mut_states[n] = v
        return const_states, mut_states

    def __call__(self, scope: Scope, feed: Dict[str, Any], rng):
        const_states, mut_states = self._gather_states(scope)
        fetches, new_states, new_rng = self.fn(feed, const_states, mut_states, rng)
        for n, v in new_states.items():
            scope.set_var(n, v)
        return fetches, new_rng


# the cache-entries gauge promises a process-wide total, not the count of
# whichever executor ran last; the lock keeps hot-path iteration safe
# against a concurrent Executor() construction in another thread
_live_executors: "weakref.WeakSet[Executor]" = weakref.WeakSet()
_live_executors_lock = threading.Lock()


class Executor:
    """reference: python/paddle/fluid/executor.py:418."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or default_place()
        self._cache: Dict[Any, _CompiledStep] = {}
        self._cache_hits = 0
        self._cache_misses = 0
        self._dev_kind: Optional[str] = None
        with _live_executors_lock:
            _live_executors.add(self)

    def _device_kind(self) -> str:
        """device_kind of this executor's place — the live-MFU peak
        lookup key (observability/device_peaks.py). Cached: the place
        never changes after construction."""
        if self._dev_kind is None:
            self._dev_kind = getattr(self.place.jax_device(),
                                     "device_kind", "unknown")
        return self._dev_kind

    def close(self):
        self._cache.clear()

    def cache_stats(self) -> Dict[str, int]:
        """Program-cache behavior, observable for benchmarks/tests: after
        the first run of a (program, feed-signature) pair every later
        run must be a hit — step 2+ retraces/recompiles nothing. The same
        events feed the process-wide registry
        (paddle_tpu_executor_cache_total in observability.snapshot());
        this per-instance view stays for single-executor assertions."""
        return {"hits": self._cache_hits, "misses": self._cache_misses,
                "entries": len(self._cache)}

    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        feed_var_name: str = "feed",
        fetch_var_name: str = "fetch",
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        sync: bool = True,
    ):
        """One program step. sync=False returns a FetchHandle — the
        device arrays stay put and the host moves on immediately;
        .result() resolves to numpy on demand (async_exec). With
        sync=True, return_numpy=False likewise returns the device
        arrays untouched so callers can stay async by hand."""
        # CompiledProgram carries its own sharded run path (core/compiler.py).
        from .compiler import CompiledProgram

        if isinstance(program, CompiledProgram):
            return program._run(self, feed, fetch_list, scope,
                                return_numpy, sync=sync)

        program = program if program is not None else framework.default_main_program()
        scope = scope if scope is not None else global_scope()
        feed = dict(feed or {})
        fetch_names = tuple(_as_fetch_name(f) for f in (fetch_list or []))

        # pserver program: a single listen_and_serv op — run the host server
        # loop, blocking like the reference (listen_and_serv_op.cc)
        ops0 = program.desc.block(0).ops
        if len(ops0) == 1 and ops0[0].type == "listen_and_serv":
            from ..ps.server import ParameterServer, snapshot_config_from_env

            a = ops0[0].attrs
            server = ParameterServer(
                a["endpoint"], int(a["num_trainers"]),
                mode=a.get("mode", "sync"),
                dc_asgd_lambda=float(a.get("dc_asgd_lambda", 0.0)),
                # PADDLE_TPU_PS_SNAPSHOT_DIR et al: a respawned server
                # restores its committed tables instead of reinitializing
                **snapshot_config_from_env(a["endpoint"]))
            server.serve_forever()  # blocks until shutdown request
            return []

        with _telemetry.executor_step("run") as rec:
            step, norm_feed = self._lookup_step(program, feed, fetch_names,
                                                use_program_cache)
            rec.set_feed(norm_feed)
            rng = self._get_rng(scope, program)
            # step_span: joins the ambient trace when one is active and
            # STARTS one (head-sampled) when PADDLE_TPU_TRACE_SAMPLE is
            # armed — the training path's trace origin, so PS RPCs
            # issued inside the step inherit the step's trace id
            with _tracing.step_span("executor.run", cat="step",
                                    fetches=len(fetch_names)):
                with jax.default_device(self.place.jax_device()):
                    fetches, new_rng = step(scope, norm_feed, rng)
            scope.set_var(RNG_STATE_VAR, new_rng)
            # after execution: the dispatch wrapper has compiled by now,
            # so current_cost() carries this signature's retained FLOPs
            rec.set_perf("step", step.fn.current_cost(),
                         device_kind=self._device_kind())

            # reference: FLAGS_check_nan_inf (flags.cc:44). The legacy
            # flag forces raise-level checking; PADDLE_TPU_CHECK_NUMERICS
            # selects warn (1) or raise (2). Both route through the
            # health layer so anomalies are counted, logged as events,
            # and flip /healthz — the flag's raise semantics (and its
            # post-step scan of every written state + fetch) are kept.
            _post_step_health(step.writes, fetch_names, fetches, scope)

            return _finish_fetches(fetches, return_numpy, sync,
                                   site="executor")

    def _lookup_step(self, program: Program, feed: Dict[str, Any],
                     fetch_names: Tuple[str, ...], use_program_cache: bool):
        """Normalize feeds and resolve the compiled step from the program
        cache, keyed by (program identity+version, feed shapes/dtypes,
        fetches, mode, PRECISION POLICY) — the reference's
        ExecutorPrepareContext cache (executor.py:418/831). The policy
        is resolved once here (program attr > PADDLE_TPU_PRECISION >
        f32) and baked into both the feed normalization and the
        compiled step, so a policy flip re-keys instead of reusing the
        old width's executable."""
        policy = _precision.resolve(program)
        norm_feed = _normalize_feed(program, feed, policy)
        _pre_run_validate(program, tuple(norm_feed), fetch_names, policy,
                          where="executor")
        feed_sig = tuple(sorted((k, tuple(v.shape), str(v.dtype)) for k, v in norm_feed.items()))
        key = (id(program), program._version, feed_sig, fetch_names,
               program._is_test, policy.name)
        step = self._cache.get(key) if use_program_cache else None
        hit = step is not None
        if step is None:
            self._cache_misses += 1
            step = _CompiledStep(program, tuple(norm_feed), fetch_names,
                                 program._is_test, policy=policy)
            if use_program_cache:
                self._cache[key] = step
        else:
            self._cache_hits += 1
        with _live_executors_lock:
            entries = sum(len(e._cache) for e in _live_executors)
        _telemetry.record_cache_event(hit=hit, entries=entries)
        return step, norm_feed

    def run_chained(self, program=None, feed=None, fetch_list=None,
                    n_steps=1, scope=None, return_numpy=True,
                    per_step_feeds=False, sync=True, unroll="auto"):
        """Run `program` n_steps times inside one jitted lax.scan — the
        cached-executable fast path: a single dispatch covers n_steps
        iterations, so per-step overhead is framework+compute time
        rather than the per-invocation host round trip (~100 ms on
        tunneled backends). With per_step_feeds, every feed value
        carries a leading [n_steps] axis and step i trains on slice i
        (a whole data chunk per dispatch — the fast path under a batch
        loop); otherwise the same feeds repeat. Scope state afterwards
        matches n_steps sequential `run` calls; each fetch comes back
        stacked with a leading [n_steps] axis.

        `unroll` defaults to "auto": on CPU the scan body is unrolled
        (or, past _UNROLL_WINDOW_MAX steps, windowed into unrolled
        chunks) because XLA-CPU runs the rolled while-loop ~2.6x slower
        per step (BENCH_r05); on TPU/GPU it stays a rolled scan — ONE
        dispatch, bounded compile time. Pass unroll=False explicitly to
        opt back into the rolled scan everywhere."""
        if int(n_steps) < 1:
            raise ValueError(f"run_chained needs n_steps >= 1, got "
                             f"{n_steps}")
        program = program if program is not None \
            else framework.default_main_program()
        scope = scope if scope is not None else global_scope()
        fetch_names = tuple(_as_fetch_name(f) for f in (fetch_list or []))
        feed = dict(feed or {})
        if per_step_feeds:
            for name, val in feed.items():
                # shape only — np.asarray would force a device-to-host
                # copy of the whole chunk on the very path built to
                # avoid host round trips
                shape = getattr(val, "shape", None)
                if shape is None:
                    shape = np.asarray(val).shape  # lists etc.
                if tuple(shape[:1]) != (int(n_steps),):
                    raise ValueError(
                        f"per_step_feeds: feed '{name}' needs a leading "
                        f"[{n_steps}] axis, got shape {tuple(shape)}")
        with _telemetry.executor_step("chained") as rec:
            step, norm_feed = self._lookup_step(program, feed, fetch_names,
                                                True)
            rec.set_feed(norm_feed)
            rng = self._get_rng(scope, program)
            # step_span: trace origin for the chained/stream fast path
            # (run_stream windows flush through here)
            with _tracing.step_span("executor.run_chained", cat="step",
                                    n_steps=int(n_steps)):
                with jax.default_device(self.place.jax_device()):
                    fetches, new_rng = step.run_chained(
                        scope, norm_feed, rng, int(n_steps),
                        per_step_feeds=bool(per_step_feeds),
                        unroll=unroll,
                        platform=getattr(self.place.jax_device(),
                                         "platform", None))
            scope.set_var(RNG_STATE_VAR, new_rng)
            rec.set_perf("chained", step.chained_cost(),
                         device_kind=self._device_kind())
            _post_step_health(step.writes, fetch_names, fetches, scope)
            return _finish_fetches(fetches, return_numpy, sync,
                                   site="chained")

    def run_stream(self, program=None, feed_iter: Optional[Iterable] = None,
                   fetch_list=None, window: int = 8, scope=None,
                   in_flight: int = async_exec.DEFAULT_IN_FLIGHT):
        """Streaming driver: consume an ITERATOR of per-step feed dicts
        and yield one lazy FetchHandle per window of up to `window`
        micro-chained steps — the cached-executable amortization of
        run_chained without requiring all feeds pre-stacked up front.

        Feeds are buffered until the window fills (or the feed
        signature changes — e.g. a short final batch — or the iterator
        ends), host-collated with a leading [n] axis, and dispatched as
        ONE chained executable with per_step_feeds=True. Each yielded
        handle carries `.start_step`/`.n_steps`; its `.result()` is the
        stacked fetch list. A bounded InFlightWindow (`in_flight`,
        default 2) resolves the oldest handle before admitting a new
        one, so no more than `in_flight` windows of fetch buffers are
        ever device-resident; the remainder are drained when the
        generator closes. Feeds may already be device arrays (a
        DevicePrefetcher upstream) — collation then stays on device.

        Scope state after exhaustion matches per-step `run` calls; see
        RESILIENCE.md for the window-boundary semantics the
        fault-tolerant drivers layer on top."""
        if feed_iter is None:
            raise ValueError("run_stream needs a feed iterator")
        program = program if program is not None \
            else framework.default_main_program()
        scope = scope if scope is not None else global_scope()
        window = max(1, int(window))
        win = async_exec.InFlightWindow(limit=in_flight, site="stream")

        def gen():
            buf: List[Dict[str, Any]] = []
            sig = None
            step0 = 0

            def flush():
                nonlocal buf, step0
                feeds, buf = buf, []
                n = len(feeds)
                stacked = _stack_feed_window(feeds)
                # the explicit reserve is load-bearing: it must run
                # BEFORE run_chained creates the new handle, or
                # limit+1 windows of buffers coexist transiently
                # (admit's own reserve would fire too late)
                win.reserve()
                h = self.run_chained(program, feed=stacked,
                                     fetch_list=fetch_list, n_steps=n,
                                     per_step_feeds=True, scope=scope,
                                     sync=False,
                                     unroll=n <= _UNROLL_WINDOW_MAX)
                h.start_step, h.n_steps = step0, n
                step0 += n
                return win.admit(h)

            try:
                for feed in feed_iter:
                    feed = dict(feed)
                    s = _feed_signature(feed)
                    if buf and s != sig:
                        yield flush()
                    sig = s
                    buf.append(feed)
                    if len(buf) >= window:
                        yield flush()
                if buf:
                    yield flush()
            finally:
                # resolve stragglers so device fetch buffers free even
                # when the consumer abandons the stream mid-way
                win.drain()

        return gen()

    def _get_rng(self, scope: Scope, program: Program):
        rng = scope.find_var(RNG_STATE_VAR)
        if rng is None:
            seed = program.random_seed or framework.global_seed()
            rng = jax.random.key(seed)
            scope.set_var(RNG_STATE_VAR, rng)
        return rng

    # ------------------------------------------------------------------
    # Dataset entry points (reference: executor.py train_from_dataset) are
    # provided by paddle_tpu.trainer; thin delegation keeps API parity.
    # ------------------------------------------------------------------

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        from ..trainer import train_from_dataset

        return train_from_dataset(self, program, dataset, scope, thread, debug,
                                  fetch_list, fetch_info, print_period)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        from ..trainer import infer_from_dataset

        return infer_from_dataset(self, program, dataset, scope, thread, debug,
                                  fetch_list, fetch_info, print_period)
