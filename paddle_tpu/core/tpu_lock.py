"""Single-flight discipline for the one real TPU chip.

Only one process may hold the tunneled TPU at a time: concurrent
backend init / remote compiles wedge BOTH processes, and a wedged chip
then hangs every later ``jax.devices()`` in the environment (the
round-4 BENCH rc=1 post-mortem). Everything that touches the real chip
— ``bench.py`` and the TPU tools under ``tools/`` — funnels through
:func:`tpu_singleflight`.

Reference analogue: the reference serializes device-exclusive tests by
partitioning ``CUDA_VISIBLE_DEVICES`` per test process
(/root/reference/paddle/fluid/tests/unittests/CMakeLists.txt:13); with
a single tunneled chip we serialize with an fcntl lease lock instead.

Design notes:

- The lock file is MACHINE-global (default under ``tempfile.
  gettempdir()``): the chip is a machine-scoped resource, and two
  checkouts of this repo must still serialize against each other.
- ``flock`` is process-scoped, so a holder that exits (even SIGKILL)
  releases the lock automatically. Because the holder's TPU work may
  live in child subprocesses (bench.py's ``--one`` children), a fresh
  acquirer also sweeps for known orphaned TPU processes by cmdline
  before proceeding.
- Lease + auto-renew: the holder records ``{pid, argv0, acquired_at,
  lease_s}`` and :func:`tpu_singleflight` renews it from a daemon
  thread, so lease expiry means the holder is genuinely wedged (a hung
  process stops renewing; a merely slow one keeps its lease). A waiter
  that finds the lease expired SIGKILLs the holder's descendant tree,
  then the holder — an aborted or hung tool can never wedge the next
  run.
- Waiter registration: every ``acquire()`` caller drops a pid beacon in
  ``<lock>.waiters/`` for the duration of its wait, and the orphan
  sweep spares registered waiters (and their descendants). Without
  this, a second legitimate bench.py blocked in ``acquire()`` matched
  the cmdline markers and was SIGKILLed whenever a holder died with
  two or more contenders queued (ADVICE r5) — exactly the concurrency
  the lock exists to serialize.
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import glob
import json
import os
import signal
import sys
import tempfile
import threading
import time

DEFAULT_LOCK_PATH = os.environ.get(
    "PADDLE_TPU_LOCK_FILE",
    os.path.join(tempfile.gettempdir(), "paddle_tpu_singleflight.lock"))

# With auto-renew (tpu_singleflight), expiry == the holder stopped
# renewing, so the lease only needs to outlast one renew interval plus
# slack — but keep it larger than the slowest single blocking phase
# that could starve the renew thread (a first tunnel compile, ~40 s).
DEFAULT_LEASE_S = 900.0

# Cmdline markers of processes that drive the chip; used to reap
# orphans whose lock-holding parent died (children reparent to init and
# would otherwise keep the tunnel busy while a new holder inits).
_TPU_PROC_MARKERS = ("bench.py", "tools/attn_ab.py", "tools/infer_bench.py",
                     "tools/op_bench.py", "tools/rn50_exp.py",
                     "tools/rn50_roofline.py", "tools/warmstart.py")


def _read_holder(path):
    try:
        with open(path, "r") as f:
            return json.loads(f.read() or "{}")
    except (OSError, ValueError):
        return {}


def _write_holder(fd, lease_s):
    os.ftruncate(fd, 0)
    os.lseek(fd, 0, os.SEEK_SET)
    os.write(fd, json.dumps({
        "pid": os.getpid(), "argv0": sys.argv[0] if sys.argv else "",
        "acquired_at": time.time(), "lease_s": lease_s,
    }).encode())
    os.fsync(fd)


def _cmdline(pid):
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return [a.decode(errors="replace")
                    for a in f.read().split(b"\0") if a]
    except OSError:
        return []


def _pid_is_python(pid):
    """True iff pid is alive AND looks like a python process (guards the
    lease-expiry kill against pid recycling)."""
    argv = _cmdline(pid)
    return bool(argv) and "python" in os.path.basename(argv[0])


def _children_map():
    """ppid -> [child pids] for every live process (one /proc walk)."""
    children = {}
    for stat in glob.glob("/proc/[0-9]*/stat"):
        try:
            with open(stat) as f:
                parts = f.read().rsplit(")", 1)[1].split()
            pid = int(stat.split("/")[2])
            children.setdefault(int(parts[1]), []).append(pid)  # ppid
        except (OSError, ValueError, IndexError):
            continue
    return children


def _descendants_from(children, root_pid):
    """Breadth-first descendants of root_pid over a _children_map()."""
    out, queue = [], list(children.get(root_pid, []))
    while queue:
        pid = queue.pop(0)
        out.append(pid)
        queue.extend(children.get(pid, []))
    return out


def _descendants(root_pid):
    """All live descendant pids of root_pid (breadth-first), via /proc."""
    return _descendants_from(_children_map(), root_pid)


def _kill_tree(root_pid):
    """SIGKILL root_pid's descendants (so orphans can't outlive it), then
    root_pid itself. Returns True if anything was signalled."""
    killed = False
    for pid in _descendants(root_pid) + [root_pid]:
        try:
            os.kill(pid, signal.SIGKILL)
            killed = True
        except OSError:
            pass
    return killed


def _maybe_kill_expired_holder(path):
    info = _read_holder(path)
    pid = info.get("pid")
    if not pid or pid == os.getpid():
        return False
    expiry = info.get("acquired_at", 0) + info.get("lease_s",
                                                  DEFAULT_LEASE_S)
    if time.time() <= expiry or not _pid_is_python(pid):
        return False
    if _kill_tree(pid):
        # flock releases when the holder's fd closes at process death;
        # give the kernel a beat to reap.
        time.sleep(0.5)
        return True
    return False


def _waiters_dir(path):
    return path + ".waiters"


def _register_waiter(path):
    """Record this pid as a live waiter blocked in acquire(): the
    orphan sweep must never SIGKILL a process that is merely queueing
    for the lock (the ADVICE r5 bug — a second legitimate bench.py
    waiter matched the cmdline markers and died whenever a holder
    crashed with >=2 waiters). One beacon file per pid, removed on
    every acquire() exit path."""
    d = _waiters_dir(path)
    beacon = os.path.join(d, str(os.getpid()))
    try:
        os.makedirs(d, exist_ok=True)
        # a torn/lost beacon only widens the conservative keep-set
        # check below, so this single write needs no atomic publish
        with open(beacon, "w") as f:  # atomic-exempt: pid beacon
            f.write(json.dumps({"pid": os.getpid(),
                                "registered_at": time.time()}))
    except OSError:
        return None  # unregisterable waiter: sweep falls back to markers
    return beacon


def _unregister_waiter(beacon):
    if beacon:
        try:
            os.unlink(beacon)
        except OSError:
            pass


def _pid_start_time(pid):
    """Epoch seconds the process started: /proc/<pid>/stat field 22
    (clock ticks since boot) + boot time. None when unreadable."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
        # split after the parenthesized comm — it may contain spaces
        ticks = float(stat.rsplit(") ", 1)[1].split()[19])
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("btime "):
                    return (float(line.split()[1])
                            + ticks / os.sysconf("SC_CLK_TCK"))
    except (OSError, ValueError, IndexError):
        pass
    return None


def _live_waiter_pids(path):
    """Pids with a live waiter beacon. Beacons of dead pids are stale
    (a SIGKILLed waiter can't clean up) and are swept here — as are
    beacons whose pid was RECYCLED by an unrelated process (the process
    started after the beacon was written), which would otherwise shield
    a true orphan from the sweep forever."""
    d = _waiters_dir(path)
    try:
        names = os.listdir(d)
    except OSError:
        return set()
    live = set()
    for name in names:
        try:
            pid = int(name)
        except ValueError:
            continue
        beacon = os.path.join(d, name)
        stale = not os.path.exists(f"/proc/{pid}")
        if not stale:
            try:
                with open(beacon) as f:
                    registered_at = json.loads(f.read()).get(
                        "registered_at")
            except (OSError, ValueError):
                registered_at = None  # torn write: keep conservatively
            if registered_at is not None:
                started = _pid_start_time(pid)
                # 2 s slack covers clock-granularity skew between
                # btime-derived start and time.time() at registration
                stale = (started is not None
                         and started > registered_at + 2.0)
        if stale:
            try:
                os.unlink(beacon)
            except OSError:
                pass
        else:
            live.add(pid)
    return live


def _reap_tpu_orphans(lock_path=None):
    """Kill leftover chip-driving processes whose lock-holding ancestor
    died (e.g. bench.py's ``--one`` children after the orchestrator was
    OOM-killed: the flock released instantly, but the child is still
    mid-compile on the tunnel). Matched conservatively: python
    interpreters whose argv names one of the known TPU scripts, and that
    are not us, our ancestors, our descendants, or a REGISTERED WAITER
    blocked in acquire() on this lock (waiters queue legitimately; only
    true orphans — marker processes nobody is waiting behind — die)."""
    keep = {os.getpid()}
    pid = os.getpid()
    while pid > 1:  # ancestors
        try:
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().rsplit(")", 1)[1].split()[1])
            keep.add(pid)
        except (OSError, ValueError, IndexError):
            break
    keep.update(_descendants(os.getpid()))
    if lock_path:
        for waiter in _live_waiter_pids(lock_path):
            keep.add(waiter)
            keep.update(_descendants(waiter))
    reaped = []
    for proc_dir in glob.glob("/proc/[0-9]*"):
        pid = int(proc_dir.rsplit("/", 1)[1])
        if pid in keep:
            continue
        argv = _cmdline(pid)
        if not argv or "python" not in os.path.basename(argv[0]):
            continue
        if any(any(a.endswith(m) for m in _TPU_PROC_MARKERS)
               for a in argv[1:]):
            if lock_path:
                # re-read the beacon dir at the last moment: a waiter
                # that registered AFTER the keep-set snapshot (entered
                # acquire() while this sweep walked /proc) must not be
                # killed — the registration race is exactly the ADVICE
                # r5 false positive this sweep must never reproduce
                fresh = _live_waiter_pids(lock_path)
                shield = set(fresh)
                if fresh:  # one /proc walk covers every waiter
                    fresh_children = _children_map()
                    for w in fresh:
                        shield.update(
                            _descendants_from(fresh_children, w))
                if pid in shield:
                    keep.add(pid)
                    continue
            try:
                os.kill(pid, signal.SIGKILL)
                reaped.append(pid)
            except OSError:
                pass
    return reaped


def acquire(timeout=600.0, lease_s=DEFAULT_LEASE_S, lock_path=None,
            poll_s=2.0):
    """Block until the TPU lock is ours; return the open lock fd.

    Raises TimeoutError after ``timeout`` seconds. While waiting, a
    holder whose lease expired (== it stopped renewing: wedged) is
    SIGKILLed along with its process tree. After acquiring, known TPU
    orphans of a dead previous holder are reaped before returning.
    """
    path = lock_path or DEFAULT_LOCK_PATH
    deadline = time.monotonic() + timeout
    # registered BEFORE the first flock attempt: another contender that
    # wins the lock and runs the orphan sweep must see us as a waiter,
    # not a reapable marker-matching orphan
    beacon = _register_waiter(path)
    try:
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    except OSError:
        _unregister_waiter(beacon)
        raise
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    os.close(fd)
                    raise
            _maybe_kill_expired_holder(path)
            if time.monotonic() >= deadline:
                holder = _read_holder(path)
                os.close(fd)
                raise TimeoutError(
                    f"TPU single-flight lock busy after {timeout:.0f}s "
                    f"(holder: {holder})")
            time.sleep(poll_s)
        prev = _read_holder(path)
        if prev.get("pid") and prev["pid"] != os.getpid() \
                and not os.path.exists(f"/proc/{prev['pid']}"):
            _reap_tpu_orphans(path)
        _write_holder(fd, lease_s)
        return fd
    finally:
        # holder or not, we are no longer *waiting*; the holder's own
        # liveness is covered by the flock + lease, and its descendants
        # are never swept while it holds the lock (the sweep only runs
        # in a process that just ACQUIRED it)
        _unregister_waiter(beacon)


def release(fd):
    try:
        os.ftruncate(fd, 0)
        fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)


def renew(fd, lease_s=DEFAULT_LEASE_S):
    """Extend the current lease (auto-called by tpu_singleflight)."""
    _write_holder(fd, lease_s)


@contextlib.contextmanager
def tpu_singleflight(timeout=600.0, lease_s=DEFAULT_LEASE_S,
                     lock_path=None):
    """Hold the single-flight TPU lock for the body, renewing the lease
    from a daemon thread every lease_s/3 — so a long-but-healthy run
    keeps its lease, while a wedged process (renew thread starved or
    dead) expires and gets reaped by the next waiter."""
    t_wait = time.monotonic()
    fd = acquire(timeout=timeout, lease_s=lease_s, lock_path=lock_path)
    t_held = time.monotonic()
    stop = threading.Event()

    def _renewer():
        while not stop.wait(lease_s / 3):
            try:
                renew(fd, lease_s)
            except OSError:
                return

    thread = threading.Thread(target=_renewer, daemon=True,
                              name="tpu-lock-renew")
    thread.start()
    try:
        yield fd
    finally:
        stop.set()
        thread.join(timeout=5)  # don't close fd under a mid-renew write
        release(fd)
        # the cross-process single-flight lease rides the same held-
        # seconds/contention table as the in-process locks (the acquire
        # poll is 2s, so a wait of >=1s means another holder was inside)
        from ..analysis import lockcheck as _lockcheck  # deferred

        if _lockcheck.level() >= 1:
            _lockcheck.note_held(
                "core.tpu_lock.singleflight",
                time.monotonic() - t_held,
                contended=(t_held - t_wait) >= 1.0)