"""Single-flight discipline for the one real TPU chip.

Only one process may hold the tunneled TPU at a time: concurrent
backend init / remote compiles wedge BOTH processes, and a wedged chip
then hangs every later ``jax.devices()`` in the environment (the
round-4 BENCH rc=1 post-mortem). Everything that touches the real chip
— ``bench.py`` and the TPU tools under ``tools/`` — funnels through
:func:`tpu_singleflight`.

Reference analogue: the reference serializes device-exclusive tests by
partitioning ``CUDA_VISIBLE_DEVICES`` per test process
(/root/reference/paddle/fluid/tests/unittests/CMakeLists.txt:13); with
a single tunneled chip we serialize with an fcntl lease lock instead.

Design notes:

- The lock file is MACHINE-global (default under ``tempfile.
  gettempdir()``): the chip is a machine-scoped resource, and two
  checkouts of this repo must still serialize against each other.
- ``flock`` is process-scoped, so a holder that exits (even SIGKILL)
  releases the lock automatically. Because the holder's TPU work may
  live in child subprocesses (bench.py's ``--one`` children), a fresh
  acquirer also sweeps for known orphaned TPU processes by cmdline
  before proceeding.
- Lease + auto-renew: the holder records ``{pid, argv0, acquired_at,
  lease_s}`` and :func:`tpu_singleflight` renews it from a daemon
  thread, so lease expiry means the holder is genuinely wedged (a hung
  process stops renewing; a merely slow one keeps its lease). A waiter
  that finds the lease expired SIGKILLs the holder's descendant tree,
  then the holder — an aborted or hung tool can never wedge the next
  run.
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import glob
import json
import os
import signal
import sys
import tempfile
import threading
import time

DEFAULT_LOCK_PATH = os.environ.get(
    "PADDLE_TPU_LOCK_FILE",
    os.path.join(tempfile.gettempdir(), "paddle_tpu_singleflight.lock"))

# With auto-renew (tpu_singleflight), expiry == the holder stopped
# renewing, so the lease only needs to outlast one renew interval plus
# slack — but keep it larger than the slowest single blocking phase
# that could starve the renew thread (a first tunnel compile, ~40 s).
DEFAULT_LEASE_S = 900.0

# Cmdline markers of processes that drive the chip; used to reap
# orphans whose lock-holding parent died (children reparent to init and
# would otherwise keep the tunnel busy while a new holder inits).
_TPU_PROC_MARKERS = ("bench.py", "tools/attn_ab.py", "tools/infer_bench.py",
                     "tools/op_bench.py", "tools/rn50_exp.py",
                     "tools/rn50_roofline.py")


def _read_holder(path):
    try:
        with open(path, "r") as f:
            return json.loads(f.read() or "{}")
    except (OSError, ValueError):
        return {}


def _write_holder(fd, lease_s):
    os.ftruncate(fd, 0)
    os.lseek(fd, 0, os.SEEK_SET)
    os.write(fd, json.dumps({
        "pid": os.getpid(), "argv0": sys.argv[0] if sys.argv else "",
        "acquired_at": time.time(), "lease_s": lease_s,
    }).encode())
    os.fsync(fd)


def _cmdline(pid):
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return [a.decode(errors="replace")
                    for a in f.read().split(b"\0") if a]
    except OSError:
        return []


def _pid_is_python(pid):
    """True iff pid is alive AND looks like a python process (guards the
    lease-expiry kill against pid recycling)."""
    argv = _cmdline(pid)
    return bool(argv) and "python" in os.path.basename(argv[0])


def _descendants(root_pid):
    """All live descendant pids of root_pid (breadth-first), via /proc."""
    children = {}
    for stat in glob.glob("/proc/[0-9]*/stat"):
        try:
            with open(stat) as f:
                parts = f.read().rsplit(")", 1)[1].split()
            pid = int(stat.split("/")[2])
            children.setdefault(int(parts[1]), []).append(pid)  # ppid
        except (OSError, ValueError, IndexError):
            continue
    out, queue = [], list(children.get(root_pid, []))
    while queue:
        pid = queue.pop(0)
        out.append(pid)
        queue.extend(children.get(pid, []))
    return out


def _kill_tree(root_pid):
    """SIGKILL root_pid's descendants (so orphans can't outlive it), then
    root_pid itself. Returns True if anything was signalled."""
    killed = False
    for pid in _descendants(root_pid) + [root_pid]:
        try:
            os.kill(pid, signal.SIGKILL)
            killed = True
        except OSError:
            pass
    return killed


def _maybe_kill_expired_holder(path):
    info = _read_holder(path)
    pid = info.get("pid")
    if not pid or pid == os.getpid():
        return False
    expiry = info.get("acquired_at", 0) + info.get("lease_s",
                                                  DEFAULT_LEASE_S)
    if time.time() <= expiry or not _pid_is_python(pid):
        return False
    if _kill_tree(pid):
        # flock releases when the holder's fd closes at process death;
        # give the kernel a beat to reap.
        time.sleep(0.5)
        return True
    return False


def _reap_tpu_orphans():
    """Kill leftover chip-driving processes whose lock-holding ancestor
    died (e.g. bench.py's ``--one`` children after the orchestrator was
    OOM-killed: the flock released instantly, but the child is still
    mid-compile on the tunnel). Matched conservatively: python
    interpreters whose argv names one of the known TPU scripts, and that
    are not us, our ancestors, or our descendants."""
    keep = {os.getpid()}
    pid = os.getpid()
    while pid > 1:  # ancestors
        try:
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().rsplit(")", 1)[1].split()[1])
            keep.add(pid)
        except (OSError, ValueError, IndexError):
            break
    keep.update(_descendants(os.getpid()))
    reaped = []
    for proc_dir in glob.glob("/proc/[0-9]*"):
        pid = int(proc_dir.rsplit("/", 1)[1])
        if pid in keep:
            continue
        argv = _cmdline(pid)
        if not argv or "python" not in os.path.basename(argv[0]):
            continue
        if any(any(a.endswith(m) for m in _TPU_PROC_MARKERS)
               for a in argv[1:]):
            try:
                os.kill(pid, signal.SIGKILL)
                reaped.append(pid)
            except OSError:
                pass
    return reaped


def acquire(timeout=600.0, lease_s=DEFAULT_LEASE_S, lock_path=None,
            poll_s=2.0):
    """Block until the TPU lock is ours; return the open lock fd.

    Raises TimeoutError after ``timeout`` seconds. While waiting, a
    holder whose lease expired (== it stopped renewing: wedged) is
    SIGKILLed along with its process tree. After acquiring, known TPU
    orphans of a dead previous holder are reaped before returning.
    """
    path = lock_path or DEFAULT_LOCK_PATH
    deadline = time.monotonic() + timeout
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            break
        except OSError as e:
            if e.errno not in (errno.EAGAIN, errno.EACCES):
                os.close(fd)
                raise
        _maybe_kill_expired_holder(path)
        if time.monotonic() >= deadline:
            holder = _read_holder(path)
            os.close(fd)
            raise TimeoutError(
                f"TPU single-flight lock busy after {timeout:.0f}s "
                f"(holder: {holder})")
        time.sleep(poll_s)
    prev = _read_holder(path)
    if prev.get("pid") and prev["pid"] != os.getpid() \
            and not os.path.exists(f"/proc/{prev['pid']}"):
        _reap_tpu_orphans()
    _write_holder(fd, lease_s)
    return fd


def release(fd):
    try:
        os.ftruncate(fd, 0)
        fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)


def renew(fd, lease_s=DEFAULT_LEASE_S):
    """Extend the current lease (auto-called by tpu_singleflight)."""
    _write_holder(fd, lease_s)


@contextlib.contextmanager
def tpu_singleflight(timeout=600.0, lease_s=DEFAULT_LEASE_S,
                     lock_path=None):
    """Hold the single-flight TPU lock for the body, renewing the lease
    from a daemon thread every lease_s/3 — so a long-but-healthy run
    keeps its lease, while a wedged process (renew thread starved or
    dead) expires and gets reaped by the next waiter."""
    fd = acquire(timeout=timeout, lease_s=lease_s, lock_path=lock_path)
    stop = threading.Event()

    def _renewer():
        while not stop.wait(lease_s / 3):
            try:
                renew(fd, lease_s)
            except OSError:
                return

    thread = threading.Thread(target=_renewer, daemon=True,
                              name="tpu-lock-renew")
    thread.start()
    try:
        yield fd
    finally:
        stop.set()
        thread.join(timeout=5)  # don't close fd under a mid-renew write
        release(fd)