"""Core runtime: IR descs, op registry, lowering, executor, autodiff.

Maps to the reference's `paddle/fluid/framework/` layer (SURVEY.md §2.1), but
the execution model is compile-once (JAX/XLA) instead of interpret-per-op.
"""

from . import ir
from . import registry
from . import framework
from . import precision
from . import lowering
from . import executor
from . import backward
from . import compiler
from . import places
