"""Serializable program IR.

Mirrors the reference's protobuf ProgramDesc/BlockDesc/OpDesc/VarDesc
(reference: paddle/fluid/framework/framework.proto:212,174,43,165) but as plain
dataclasses with JSON serialization — protobuf adds nothing on TPU where the
program is lowered to StableHLO by JAX anyway, and JSON keeps save files
human-debuggable. VarType values follow framework.proto:105.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Var types (reference framework.proto:105 VarType.Type)
# ---------------------------------------------------------------------------


class VarType:
    DENSE_TENSOR = "dense_tensor"  # reference LOD_TENSOR; no LoD on TPU (SURVEY §5)
    SELECTED_ROWS = "selected_rows"  # sparse row-slices (embedding grads)
    TENSOR_ARRAY = "tensor_array"  # reference LOD_TENSOR_ARRAY
    READER = "reader"
    STEP_SCOPES = "step_scopes"
    RAW = "raw"

    # compat aliases
    LOD_TENSOR = DENSE_TENSOR
    LOD_TENSOR_ARRAY = TENSOR_ARRAY


_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bf16": "bfloat16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
}


def normalize_dtype(dtype) -> str:
    """Canonical dtype string ('float32', 'bfloat16', ...)."""
    if dtype is None:
        return "float32"
    name = getattr(dtype, "name", None) or str(dtype)
    name = name.replace("numpy.", "").replace("jnp.", "")
    return _DTYPE_ALIASES.get(name, name)


# ---------------------------------------------------------------------------
# Descs
# ---------------------------------------------------------------------------


@dataclass
class VarDesc:
    """reference: framework.proto:165 VarDesc + VarType.TensorDesc."""

    name: str
    shape: Optional[Tuple[int, ...]] = None  # -1 = dynamic (batch) dim
    dtype: str = "float32"
    type: str = VarType.DENSE_TENSOR
    persistable: bool = False
    stop_gradient: bool = False
    is_parameter: bool = False
    need_check_feed: bool = False
    # Extra serializable metadata (ParamAttr, sharding annotations, etc.)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "type": self.type,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_parameter": self.is_parameter,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "VarDesc":
        return VarDesc(
            name=d["name"],
            shape=tuple(d["shape"]) if d.get("shape") is not None else None,
            dtype=d.get("dtype", "float32"),
            type=d.get("type", VarType.DENSE_TENSOR),
            persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            is_parameter=d.get("is_parameter", False),
            attrs=d.get("attrs", {}),
        )


@dataclass
class OpDesc:
    """reference: framework.proto:43 OpDesc.

    inputs/outputs map slot name -> list of var names ('' allowed = empty slot).
    attrs must be JSON-serializable; a sub-block reference is stored as
    {"__block__": idx} (reference stores BLOCK attr type, framework.proto:27).
    """

    type: str
    inputs: Dict[str, List[str]] = field(default_factory=dict)
    outputs: Dict[str, List[str]] = field(default_factory=dict)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def input_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns if n]

    def output_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns if n]

    def block_attr(self, name: str) -> Optional[int]:
        v = self.attrs.get(name)
        if isinstance(v, dict) and "__block__" in v:
            return v["__block__"]
        return None

    def sub_block_ids(self) -> List[int]:
        out = []
        for v in self.attrs.values():
            if isinstance(v, dict) and "__block__" in v:
                out.append(v["__block__"])
            elif isinstance(v, list):
                for e in v:
                    if isinstance(e, dict) and "__block__" in e:
                        out.append(e["__block__"])
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": _jsonify_attrs(self.attrs),
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "OpDesc":
        return OpDesc(
            type=d["type"],
            inputs={k: list(v) for k, v in d.get("inputs", {}).items()},
            outputs={k: list(v) for k, v in d.get("outputs", {}).items()},
            attrs=d.get("attrs", {}),
        )


def _jsonify_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in attrs.items():
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            v = v.item()
        elif hasattr(v, "tolist"):
            v = v.tolist()
        out[k] = v
    return out


@dataclass
class BlockDesc:
    """reference: framework.proto:174 BlockDesc."""

    idx: int = 0
    parent_idx: int = -1
    vars: Dict[str, VarDesc] = field(default_factory=dict)
    ops: List[OpDesc] = field(default_factory=list)
    forward_block_idx: int = -1

    def var(self, name: str) -> VarDesc:
        return self.vars[name]

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def to_dict(self) -> Dict[str, Any]:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [o.to_dict() for o in self.ops],
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "BlockDesc":
        b = BlockDesc(idx=d["idx"], parent_idx=d.get("parent_idx", -1))
        b.forward_block_idx = d.get("forward_block_idx", -1)
        for vd in d.get("vars", []):
            v = VarDesc.from_dict(vd)
            b.vars[v.name] = v
        b.ops = [OpDesc.from_dict(od) for od in d.get("ops", [])]
        return b


@dataclass
class ProgramDesc:
    """reference: framework.proto:212 ProgramDesc (+ version :184)."""

    blocks: List[BlockDesc] = field(default_factory=list)
    version: int = 1

    def __post_init__(self):
        if not self.blocks:
            self.blocks.append(BlockDesc(idx=0, parent_idx=-1))

    def block(self, idx: int) -> BlockDesc:
        return self.blocks[idx]

    def append_block(self, parent_idx: int) -> BlockDesc:
        b = BlockDesc(idx=len(self.blocks), parent_idx=parent_idx)
        self.blocks.append(b)
        return b

    def to_dict(self) -> Dict[str, Any]:
        return {"version": self.version, "blocks": [b.to_dict() for b in self.blocks]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def to_bytes(self) -> bytes:
        return self.to_json().encode("utf-8")

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ProgramDesc":
        p = ProgramDesc(blocks=[BlockDesc.from_dict(b) for b in d["blocks"]])
        p.version = d.get("version", 1)
        return p

    @staticmethod
    def from_json(s: str) -> "ProgramDesc":
        return ProgramDesc.from_dict(json.loads(s))

    @staticmethod
    def from_bytes(b: bytes) -> "ProgramDesc":
        return ProgramDesc.from_json(b.decode("utf-8"))

    def clone(self) -> "ProgramDesc":
        return copy.deepcopy(self)


GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def is_grad_var(name: str) -> bool:
    return name.endswith(GRAD_SUFFIX)
