"""Program → JAX lowering.

The reference interprets a ProgramDesc op-by-op in C++
(framework/executor.cc:437 `for (op : ops) op->Run(scope, place)`); here the
whole block is *functionalized* into one pure JAX function — scope reads
become function inputs, scope writes become function outputs — and compiled
once by XLA. This single decision subsumes the reference's kernel-fusion
passes (ir/fc_fuse_pass.cc etc.: XLA fuses), memory-optimize passes
(buffer_shared_inplace_op_pass.cc: XLA buffer-assigns), and garbage collector
(framework/garbage_collector.h: nothing to collect in a compiled program).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp

from . import precision as _precision
from . import registry
from .ir import OpDesc, ProgramDesc, VarType
from .registry import KernelCtx

# Ops handled by the executor itself, not lowered as kernels.
STRUCTURAL_OPS = {"feed", "fetch"}


class LoweringError(RuntimeError):
    pass


def lower_block(
    program_desc: ProgramDesc,
    block_idx: int,
    env: Dict[str, Any],
    rng_key=None,
    is_test: bool = False,
) -> Dict[str, Any]:
    """Execute (trace) every op in a block against `env` (name -> jnp value).

    Mutates and returns env. Kernels for ops with sub-block attrs receive a
    ctx whose lower_block recursively invokes this.
    """
    block = program_desc.block(block_idx)

    def _lower_sub(sub_idx: int, sub_env: Dict[str, Any], ctx: KernelCtx):
        return lower_block(program_desc, sub_idx, sub_env, rng_key=rng_key, is_test=is_test)

    for op in block.ops:
        if op.type in STRUCTURAL_OPS:
            continue
        run_op(op, env, program_desc, block_idx, _lower_sub, rng_key, is_test)
    return env


def run_op(
    op: OpDesc,
    env: Dict[str, Any],
    program_desc: Optional[ProgramDesc],
    block_idx: int,
    lower_sub: Optional[Callable],
    rng_key,
    is_test: bool,
):
    opdef = registry.get_op_def(op.type)
    ins: Dict[str, List] = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if not n:
                vals.append(None)
            elif n in env:
                vals.append(env[n])
            else:
                raise LoweringError(
                    f"op '{op.type}': input var '{n}' has no value (not fed, "
                    f"not in scope, and not produced by an earlier op)"
                )
        ins[slot] = vals
    # mixed-precision policies insert their casts HERE, jnp-natively at
    # trace time (white-list ops take compute-dtype floats, black-list
    # ops take f32) — the executor activates the policy around
    # lower_block, so XLA sees and fuses the casts; grad ops inherit
    # their forward op's class (core/precision.py).
    pol = _precision.active_autocast()
    if pol is not None:
        ins = _precision.autocast_op_inputs(op.type, ins, pol)
    ctx = KernelCtx(
        op,
        lower_block_fn=lower_sub,
        rng_key=rng_key,
        is_test=is_test or bool(op.attrs.get("is_test", False)),
        program=program_desc,
        block_idx=block_idx,
        env=env,
    )
    outs = opdef.call(ins, op.attrs, ctx)
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, n in enumerate(names):
            if not n:
                continue
            if i < len(vals) and vals[i] is not None:
                env[n] = vals[i]
    return env


def make_infer_lower_block_fn(program) -> Callable:
    """Sub-block lowering callback used during eval_shape-based inference."""

    def fn(sub_idx: int, sub_env: Dict[str, Any], ctx: KernelCtx):
        return lower_block(program.desc, sub_idx, sub_env)

    return fn


# ---------------------------------------------------------------------------
# Static analysis: which scope vars does a program read / write?
# ---------------------------------------------------------------------------


def analyze_state_vars(
    program_desc: ProgramDesc,
    feed_names: Set[str],
) -> Tuple[List[str], List[str]]:
    """Return (reads, writes): persistable/state vars the program reads from
    the scope before writing, and those it writes back.

    This is what turns scope mutation (reference: framework/scope.h) into
    explicit functional state threading.
    """
    persistable: Set[str] = set()
    for b in program_desc.blocks:
        for name, v in b.vars.items():
            if v.persistable:
                persistable.add(name)

    reads: List[str] = []
    writes: List[str] = []
    seen_read: Set[str] = set()
    seen_write: Set[str] = set()
    defined: Set[str] = set(feed_names)

    def visit(block_idx: int):
        block = program_desc.block(block_idx)
        for op in block.ops:
            if op.type in STRUCTURAL_OPS:
                continue
            for n in op.input_names():
                if n in persistable and n not in seen_write and n not in seen_read:
                    seen_read.add(n)
                    reads.append(n)
            for sub in op.sub_block_ids():
                visit(sub)
            for n in op.output_names():
                defined.add(n)
                if n in persistable and n not in seen_write:
                    seen_write.add(n)
                    writes.append(n)

    visit(0)
    return reads, writes


def collect_feed_fetch(program_desc: ProgramDesc) -> Tuple[List[str], List[str]]:
    """Names used by feed/fetch ops if the program carries them (reference
    injects feed/fetch ops into block 0; we also accept executor-side
    binding)."""
    feeds, fetches = [], []
    for op in program_desc.block(0).ops:
        if op.type == "feed":
            feeds.extend(op.output_names())
        elif op.type == "fetch":
            fetches.extend(op.input_names())
    return feeds, fetches
