"""Precision policy — a first-class executor concept.

Reference capability: contrib/mixed_precision (fp16 cast insertion +
dynamic loss scaling) and slim/quantization (post-training INT8) exist
because half/int8 hot paths are where real throughput lives. On TPU,
bf16 is the native matmul width; this module makes the choice of
compute width an explicit, named POLICY that every run path resolves
the same way, instead of an ad-hoc property of whichever cast ops a
program rewrite happened to insert.

Named policies:

  f32         — today's behavior, bit for bit: feeds canonicalize to
                the declared var dtype, nothing is cast.
  bf16        — pure bf16: floating feeds AND state (params, optimizer
                moments) are cast to bfloat16; the whole step computes
                and stores in bf16. Maximum speed, fewest bytes.
  mixed_bf16  — bf16 compute with f32 master params/optimizer state:
                floating feeds arrive/cast to bf16, white-list ops
                (matmul/conv family) compute in bf16, black-list ops
                (softmax/norm/reductions) compute in f32 — the casts
                are inserted jnp-natively at LOWERING time, inside the
                jit trace, so XLA fuses them — and the jax-native
                trainer adds dynamic loss scaling whose state lives in
                TrainState (checkpointed by CheckpointManager).
  mixed_f16   — same shape with float16 compute; kept for reference
                parity (amp.decorate(use_bf16=False)). f16's narrow
                exponent range is why loss scaling exists at all.

Resolution order (first hit wins), shared by Executor.run/run_chained/
run_stream, CompiledProgram, SPMDRunner, the Predictor, and
make_train_step:

  1. explicit argument (ServingConfig(precision=...),
     AnalysisConfig.set_precision, make_train_step(precision=...))
  2. program attr (`set_program_precision(program, name)`, also set by
     amp.decorate on the program it rewrites)
  3. env `PADDLE_TPU_PRECISION`
  4. default: f32

The resolved policy is part of the executor program-cache key, the
`_JitDispatch` aval signature, and the persistent compile-cache
fingerprint — flipping the policy can never serve a stale executable
compiled under the old one.

The int8 SERVING path is not a policy here (it rewrites the saved
program to quantized_* ops via slim/quantization and serves that
program under f32 semantics); `ServingConfig(precision="int8")` drives
it in serving/engine.py.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, List, Optional, Union

import jax.numpy as jnp
import numpy as np

__all__ = [
    "PrecisionPolicy", "POLICY_NAMES", "get_policy", "resolve",
    "set_program_precision", "program_precision", "env_precision",
    "autocast", "active_autocast", "autocast_op_inputs", "cast_floating",
    "cast_tree", "init_loss_scale_state", "LOSS_SCALE_COUNTER_KEYS",
]

ENV_VAR = "PADDLE_TPU_PRECISION"
PROGRAM_ATTR = "precision"


class PrecisionPolicy:
    """One named precision configuration. Immutable; compare by name."""

    def __init__(self, name: str, *,
                 compute_dtype: Optional[str] = None,
                 cast_state: bool = False,
                 op_autocast: bool = False,
                 dynamic_loss_scale: bool = False,
                 init_loss_scale: float = 2.0 ** 15,
                 growth_interval: int = 1000,
                 incr_ratio: float = 2.0,
                 decr_ratio: float = 0.5,
                 min_loss_scale: float = 1.0,
                 max_loss_scale: float = 2.0 ** 24):
        self.name = name
        # None = leave dtypes alone (the f32 policy must be a byte-for-
        # byte no-op, including float64 feeds under x64 jax)
        self.compute_dtype = (np.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self.cast_state = cast_state
        self.op_autocast = op_autocast
        self.dynamic_loss_scale = dynamic_loss_scale
        self.init_loss_scale = float(init_loss_scale)
        self.growth_interval = int(growth_interval)
        self.incr_ratio = float(incr_ratio)
        self.decr_ratio = float(decr_ratio)
        self.min_loss_scale = float(min_loss_scale)
        self.max_loss_scale = float(max_loss_scale)

    def feed_dtype(self, declared: np.dtype) -> np.dtype:
        """Feed-normalization target for a var declared `declared`:
        floating feeds follow the policy's compute width, everything
        else (ints, bools, keys) keeps the declared dtype. This is what
        kills the silent bf16→f32 upcast on the stream hot path: under
        a bf16 policy a bf16 feed already IS the target dtype, so no
        per-step astype happens at all."""
        # jnp.issubdtype: np.issubdtype does not recognize the
        # ml_dtypes extension floats (bfloat16) as np.floating
        if self.compute_dtype is not None and \
                jnp.issubdtype(declared, jnp.floating):
            return self.compute_dtype
        return declared

    def __repr__(self):
        return f"PrecisionPolicy({self.name!r})"

    def __eq__(self, other):
        return isinstance(other, PrecisionPolicy) and \
            other.name == self.name

    def __hash__(self):
        return hash(self.name)


_POLICIES: Dict[str, PrecisionPolicy] = {
    "f32": PrecisionPolicy("f32"),
    "bf16": PrecisionPolicy("bf16", compute_dtype="bfloat16",
                            cast_state=True),
    # bf16 shares f32's exponent range, so overflow is as rare as in
    # f32 — but the dynamic-scaling machinery still skips nonfinite
    # steps and its state must live in TrainState either way, so the
    # policy keeps it on with the reference's classic 2^15 seed.
    "mixed_bf16": PrecisionPolicy("mixed_bf16", compute_dtype="bfloat16",
                                  op_autocast=True,
                                  dynamic_loss_scale=True),
    "mixed_f16": PrecisionPolicy("mixed_f16", compute_dtype="float16",
                                 op_autocast=True,
                                 dynamic_loss_scale=True),
}

POLICY_NAMES = tuple(sorted(_POLICIES))


def get_policy(name: Union[str, PrecisionPolicy, None]) -> PrecisionPolicy:
    """Policy for `name` (a PrecisionPolicy passes through; None = f32).
    Unknown names fail fast — a typo'd PADDLE_TPU_PRECISION silently
    meaning f32 would be the exact class of silent wrong-width bug this
    module exists to kill."""
    if name is None:
        return _POLICIES["f32"]
    if isinstance(name, PrecisionPolicy):
        return name
    pol = _POLICIES.get(str(name))
    if pol is None:
        raise ValueError(
            f"unknown precision policy {name!r}; choose from "
            f"{list(POLICY_NAMES)}")
    return pol


def env_precision() -> Optional[str]:
    raw = os.environ.get(ENV_VAR)
    return raw or None


def set_program_precision(program, name: Optional[str]):
    """Pin `program` to a named policy (None clears it). Bumps the
    program version so every executor program-cache key re-keys — the
    old policy's compiled steps are never served for the new one."""
    if name is not None:
        get_policy(name)  # validate before mutating
    new = str(name) if name is not None else None
    if program._attrs.get(PROGRAM_ATTR) == new:
        return  # re-pinning the same policy must not invalidate the
        # program's compiled steps (bench/decorator paths re-pin)
    if new is None:
        program._attrs.pop(PROGRAM_ATTR, None)
    else:
        program._attrs[PROGRAM_ATTR] = new
    program._bump_version()


def program_precision(program) -> Optional[str]:
    attrs = getattr(program, "_attrs", None)
    if not attrs:
        return None
    return attrs.get(PROGRAM_ATTR)


def resolve(program=None, explicit=None) -> PrecisionPolicy:
    """The policy in effect for a run: explicit arg > program attr >
    PADDLE_TPU_PRECISION > f32."""
    if explicit is not None:
        return get_policy(explicit)
    name = program_precision(program) if program is not None else None
    if name is None:
        name = env_precision()
    return get_policy(name)


# ---------------------------------------------------------------------------
# Casting helpers
# ---------------------------------------------------------------------------


def cast_floating(value, dtype):
    """`value` cast to `dtype` iff it is a floating array of another
    float width; ints/bools/keys/non-arrays pass through untouched."""
    if value is None or dtype is None:
        return value
    vdt = getattr(value, "dtype", None)
    if vdt is None:
        return value
    try:
        if not jnp.issubdtype(vdt, jnp.floating) or vdt == dtype:
            return value
    except TypeError:
        return value  # exotic dtypes (prng keys) are never cast
    return value.astype(dtype)


def cast_tree(tree, dtype):
    """cast_floating over every leaf of a pytree."""
    import jax

    return jax.tree_util.tree_map(lambda v: cast_floating(v, dtype), tree)


# ---------------------------------------------------------------------------
# Lowering-time op autocast (the jnp-native replacement for the amp
# protobuf cast-op rewrite): core/lowering.run_op consults the active
# policy for every op it traces, casting white-list op inputs to the
# compute dtype and black-list op inputs back to f32. The casts are
# jnp ops inserted inside the jit trace — XLA fuses them — and grad ops
# (`foo_grad`, lowered via jax.vjp of `foo`) inherit their forward op's
# class, so the backward matmuls run at the same width as the forward.
# ---------------------------------------------------------------------------

_tl = threading.local()
_op_lists = None  # (white, black), loaded lazily from amp.fp16_lists


def _lists():
    global _op_lists
    if _op_lists is None:
        from ..amp import fp16_lists

        _op_lists = (frozenset(fp16_lists.white_list),
                     frozenset(fp16_lists.black_list))
    return _op_lists


@contextlib.contextmanager
def autocast(policy: Optional[PrecisionPolicy]):
    """Activate lowering-time op autocast for the with-block (a trace).
    No-op for policies without op_autocast. Thread-local: concurrent
    HogwildWorker traces on other threads are unaffected."""
    if policy is None or not policy.op_autocast:
        yield
        return
    prev = getattr(_tl, "policy", None)
    _tl.policy = policy
    try:
        yield
    finally:
        _tl.policy = prev


def active_autocast() -> Optional[PrecisionPolicy]:
    return getattr(_tl, "policy", None)


def _base_op_type(op_type: str) -> str:
    # conv2d_grad / conv2d_grad_grad classify as conv2d
    while op_type.endswith("_grad"):
        op_type = op_type[:-len("_grad")]
    return op_type


def autocast_op_inputs(op_type: str, ins: Dict[str, List],
                       policy: PrecisionPolicy) -> Dict[str, List]:
    """Cast `ins` (slot -> value list) for `op_type` under `policy`:
    white-list ops take compute-dtype floats, black-list ops take f32
    floats, everything else passes through (dtype propagation decides).
    """
    white, black = _lists()
    base = _base_op_type(op_type)
    if base in white:
        want = policy.compute_dtype
    elif base in black:
        want = np.dtype(np.float32)
    else:
        return ins
    return {slot: [cast_floating(v, want) for v in vals]
            for slot, vals in ins.items()}


# ---------------------------------------------------------------------------
# Dynamic loss scaling state (the TrainState-resident piece). The state
# is a plain dict pytree so orbax checkpoints round-trip it with zero
# special casing; hyperparameters (ratios, interval) stay static in the
# policy and are closed over by the jitted step.
# ---------------------------------------------------------------------------

# cumulative device-side outcome counters, diffed host-side by the
# trainer to tick paddle_tpu_amp_total{event=...}
LOSS_SCALE_COUNTER_KEYS = ("overflows", "growths")


def init_loss_scale_state(policy: PrecisionPolicy) -> Optional[Dict[str, Any]]:
    """Fresh loss-scale state for `policy`, or None when the policy has
    no dynamic loss scaling (the TrainState field stays an empty
    subtree, keeping old checkpoints restorable)."""
    if not policy.dynamic_loss_scale:
        return None
    return {
        "scale": jnp.asarray(policy.init_loss_scale, jnp.float32),
        "good_steps": jnp.asarray(0, jnp.int32),
        "overflows": jnp.asarray(0, jnp.int32),
        "growths": jnp.asarray(0, jnp.int32),
    }
