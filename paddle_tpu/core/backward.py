"""Symbolic autodiff over the Program IR.

Reference: python/paddle/fluid/backward.py — `append_backward` :933 walks the
op path to the loss (`_find_op_path_` :1159), asks each op's GradOpMaker for
grad OpDescs, dedups repeated grads (`_addup_repetitive_outputs_` :324) and
prunes no-grad vars (:406).

Here each forward op gets ONE generically-generated grad op `<type>_grad`
whose kernel is jax.vjp of the forward kernel (core/registry.py), so this
module only does the graph walk + grad accumulation bookkeeping. Grad ops use
slots fwd_in::/fwd_out::/out_grad::/in_grad:: instead of the reference's
X / Out / Out@GRAD / X@GRAD convention.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import registry
from .framework import Block, OpRole, Parameter, Program, Variable, unique_name
from .ir import GRAD_SUFFIX, OpDesc, VarDesc, grad_var_name
from .registry import GRAD_PREFIX_IG, GRAD_PREFIX_IN, GRAD_PREFIX_OG, GRAD_PREFIX_OUT

_FLOAT_DTYPES = {"float16", "bfloat16", "float32", "float64"}


def _is_float_var(desc: VarDesc) -> bool:
    return desc.dtype in _FLOAT_DTYPES


def _base_var_of_grad(gname: str) -> str:
    name = gname.split("@RENAME@")[0]
    if name.endswith(GRAD_SUFFIX):
        name = name[: -len(GRAD_SUFFIX)]
    return name


class _GradEmitter:
    def __init__(self, block: Block, no_grad_set: Set[str],
                 force_grad: Optional[Set[str]] = None):
        self.block = block
        self.no_grad = no_grad_set
        self.force_grad = force_grad or set()
        # var -> list of pending (unsummed) grad names
        self.pending: Dict[str, List[str]] = defaultdict(list)
        self.finalized: Dict[str, str] = {}
        # var -> this invocation's canonical grad name. A prior
        # append_backward/gradients call may already own `var@GRAD` (the
        # double-backward case: the second pass differentiates THROUGH the
        # first pass's grad ops); writing it again would alias the
        # first-order gradient, so each emitter claims fresh names
        # (var@GRAD@2, @3, ...) when the plain name is taken.
        self._canonical: Dict[str, str] = {}

    # -- var/desc helpers ----------------------------------------------------

    def canonical_grad_name(self, var: str) -> str:
        if var in self._canonical:
            return self._canonical[var]
        name = grad_var_name(var)
        k = 1
        while self.block._find_var_recursive(name) is not None:
            k += 1
            name = f"{grad_var_name(var)}@{k}"
        self._canonical[var] = name
        return name

    def _ensure_grad_var(self, gname: str, base: Optional[str] = None):
        base = base if base is not None else _base_var_of_grad(gname)
        bvar = self.block._find_var_recursive(base)
        if self.block._find_var_recursive(gname) is None:
            self.block.create_var(
                name=gname,
                shape=bvar.shape if bvar is not None else None,
                dtype=bvar.dtype if bvar is not None else "float32",
            )

    def _append_raw(self, desc: OpDesc):
        """Append a grad OpDesc without eval_shape inference (grad shapes are
        the forward shapes by construction)."""
        from .framework import Operator

        desc.attrs.setdefault(OpRole.AttrName, OpRole.Backward)
        self.block.desc.ops.append(desc)
        self.block.ops.append(Operator(self.block, desc))
        self.block.program._bump_version()

    # -- accumulation --------------------------------------------------------

    def new_grad_name(self, var: str) -> str:
        canonical = self.canonical_grad_name(var)
        if not self.pending[var]:
            g = canonical
        else:
            g = f"{canonical}@RENAME@{len(self.pending[var])}"
        self.pending[var].append(g)
        self._ensure_grad_var(g, base=var)
        return g

    def finalize(self, var: str) -> Optional[str]:
        """Sum pending grad contributions into this invocation's canonical
        grad var (var@GRAD, or var@GRAD@k under double backward)."""
        if var in self.finalized:
            return self.finalized[var]
        names = self.pending.get(var)
        if not names:
            return None
        if len(names) == 1:
            # single contribution keeps its name (for emitter-made names
            # this IS the canonical; for seeds it is the caller's var)
            self.finalized[var] = names[0]
            return names[0]
        canonical = self.canonical_grad_name(var)
        # Out may alias X[0] (the canonical usually holds the first
        # contribution): the functional executor reads all inputs before
        # binding the output, so the in-place sum is well-defined.
        self._ensure_grad_var(canonical, base=var)
        self._append_raw(OpDesc(
            type="sum",
            inputs={"X": list(names)},
            outputs={"Out": [canonical]},
            attrs={OpRole.AttrName: OpRole.Backward},
        ))
        self.finalized[var] = canonical
        return canonical


def _find_op_path(
    block: Block,
    target_names: Set[str],
    source_names: Optional[Set[str]],
    no_grad_set: Set[str],
    force_grad: Optional[Set[str]] = None,
) -> Tuple[List[bool], Set[str]]:
    """Reverse pass marking ops on the grad path and vars needing grads
    (reference: backward.py:1159 _find_op_path_)."""
    ops = block.desc.ops
    needed = set(target_names)
    on_path = [False] * len(ops)
    for i in reversed(range(len(ops))):
        op = ops[i]
        try:
            opdef = registry.get_op_def(op.type)
        except KeyError:
            continue
        if not opdef.has_grad():
            continue
        if not any(o in needed for o in op.output_names()):
            continue
        on_path[i] = True
        for slot, names in op.inputs.items():
            if slot in opdef.nondiff_inputs:
                continue
            for n in names:
                if not n or n in no_grad_set:
                    continue
                v = block._find_var_recursive(n)
                if v is None or not _is_float_var(v.desc):
                    continue
                # explicitly-requested gradient inputs override
                # stop_gradient (reference calc_gradient semantics:
                # fluid.gradients(y, x) works for feed/data x)
                if v.desc.stop_gradient and n not in (force_grad or ()):
                    continue
                needed.add(n)
    if source_names is not None:
        # forward-reachability pruning for gradients(targets, inputs)
        reach = set(source_names)
        fwd_reachable = [False] * len(ops)
        for i, op in enumerate(ops):
            if any(n in reach for n in op.input_names()):
                fwd_reachable[i] = True
                reach.update(op.output_names())
        on_path = [a and b for a, b in zip(on_path, fwd_reachable)]
    return on_path, needed


def _emit_backward(
    block: Block,
    on_path: List[bool],
    needed: Set[str],
    no_grad_set: Set[str],
    seed_grads: Dict[str, str],
    force_grad: Optional[Set[str]] = None,
) -> _GradEmitter:
    """Emit grad ops in reverse program order. seed_grads maps target var ->
    the name of an already-materialized output gradient."""
    em = _GradEmitter(block, no_grad_set, force_grad)
    for var, gname in seed_grads.items():
        em.pending[var].append(gname)

    # snapshot of the forward ops only (ops appended after on_path was
    # computed — e.g. the loss-grad fill — are not part of the walk)
    fwd_ops = list(block.desc.ops)[: len(on_path)]
    for i in reversed(range(len(fwd_ops))):
        if not on_path[i]:
            continue
        op = fwd_ops[i]
        opdef = registry.get_op_def(op.type)

        out_grad_slots: Dict[str, List[str]] = {}
        any_out_grad = False
        for slot, names in op.outputs.items():
            gl = []
            for n in names:
                g = em.finalize(n) if n else None
                gl.append(g or "")
                any_out_grad = any_out_grad or bool(g)
            out_grad_slots[slot] = gl
        if not any_out_grad:
            continue

        in_grad_slots: Dict[str, List[str]] = {}
        any_in_grad = False
        for slot, names in op.inputs.items():
            if slot in opdef.nondiff_inputs:
                continue
            gl = []
            for n in names:
                want = bool(n) and n in needed and n not in no_grad_set
                if want:
                    v = block._find_var_recursive(n)
                    want = v is not None and _is_float_var(v.desc) and (
                        not v.desc.stop_gradient or n in em.force_grad)
                gl.append(em.new_grad_name(n) if want else "")
                any_in_grad = any_in_grad or want
            if any(gl):
                in_grad_slots[GRAD_PREFIX_IG + slot] = gl
        if not any_in_grad:
            continue

        grad_inputs: Dict[str, List[str]] = {}
        for slot, names in op.inputs.items():
            grad_inputs[GRAD_PREFIX_IN + slot] = list(names)
        for slot, names in op.outputs.items():
            grad_inputs[GRAD_PREFIX_OUT + slot] = list(names)
            grad_inputs[GRAD_PREFIX_OG + slot] = out_grad_slots[slot]

        gdesc = OpDesc(
            type=op.type + "_grad",
            inputs=grad_inputs,
            outputs=in_grad_slots,
            attrs={**{k: v for k, v in op.attrs.items() if k != OpRole.AttrName},
                   OpRole.AttrName: OpRole.Backward},
        )
        em._append_raw(gdesc)
    return em


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
    checkpoints: Optional[Sequence] = None,
) -> List[Tuple[Parameter, Variable]]:
    """Append grad ops for `loss` and return [(param, grad_var)]
    (reference: backward.py:933). `checkpoints` enables recompute segments
    (reference: backward.py:576) — handled by marking remat scopes, see
    optimizer.RecomputeOptimizer."""
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    on_path, needed = _find_op_path(block, {loss.name}, None, no_grad)

    # Seed: d loss / d loss = 1 (reference: backward.py _append_loss_ops_).
    loss_grad = grad_var_name(loss.name)
    block.create_var(name=loss_grad, shape=loss.shape, dtype=loss.dtype)
    from .framework import Operator

    fill = OpDesc(
        type="fill_constant",
        inputs={},
        outputs={"Out": [loss_grad]},
        attrs={"shape": list(loss.shape or (1,)), "value": 1.0,
               "dtype": loss.dtype, OpRole.AttrName: OpRole.Backward | OpRole.Loss},
    )
    block.desc.ops.append(fill)
    block.ops.append(Operator(block, fill))
    program._bump_version()

    em = _emit_backward(block, on_path, needed, no_grad, {loss.name: loss_grad})

    # Collect (param, grad) pairs.
    if parameter_list is not None:
        params = [p if isinstance(p, Variable) else block.var(str(p)) for p in parameter_list]
    else:
        params = [p for p in program.all_parameters() if getattr(p, "trainable", True)]
    result = []
    for p in params:
        g = em.finalize(p.name)
        if g is None:
            continue
        gvar = block._find_var_recursive(g)
        result.append((p, gvar))
    # op_role_var annotation for transpilers/DGC (reference: backward.py).
    for p, g in result:
        for opdesc in block.desc.ops:
            if g.name in opdesc.output_names() and opdesc.attrs.get(OpRole.AttrName) == OpRole.Backward:
                opdesc.attrs.setdefault(OpRole.OpRoleVarAttrName, []).extend([p.name, g.name])
    return result


def gradients(
    targets: Sequence[Variable] | Variable,
    inputs: Sequence[Variable] | Variable,
    target_gradients: Optional[Sequence] = None,
    no_grad_set: Optional[Set[str]] = None,
) -> List[Optional[Variable]]:
    """Compute grads of targets w.r.t. inputs (reference: backward.py:1317)."""
    targets = [targets] if isinstance(targets, Variable) else list(targets)
    inputs = [inputs] if isinstance(inputs, Variable) else list(inputs)
    block = targets[0].block
    program = block.program
    no_grad = set(no_grad_set or ())

    force = {i.name for i in inputs}
    on_path, needed = _find_op_path(
        block, {t.name for t in targets}, force, no_grad, force_grad=force)
    needed.update(force)

    from .framework import Operator

    seed = {}
    for i, t in enumerate(targets):
        tg = None if target_gradients is None else target_gradients[i]
        gname = grad_var_name(t.name)
        k = 1
        while block._find_var_recursive(gname) is not None:
            k += 1
            gname = f"{grad_var_name(t.name)}@{k}"
        block.create_var(name=gname, shape=t.shape, dtype=t.dtype)
        if tg is None:
            fill = OpDesc(
                type="fill_constant", inputs={}, outputs={"Out": [gname]},
                attrs={"shape": list(t.shape or (1,)), "value": 1.0,
                       "dtype": t.dtype, OpRole.AttrName: OpRole.Backward},
            )
            block.desc.ops.append(fill)
            block.ops.append(Operator(block, fill))
            program._bump_version()
        else:
            gname = tg.name if isinstance(tg, Variable) else str(tg)
        seed[t.name] = gname

    em = _emit_backward(block, on_path, needed, no_grad, seed,
                        force_grad=force)
    out = []
    for i in inputs:
        g = em.finalize(i.name)
        out.append(block._find_var_recursive(g) if g else None)
    return out
