"""Operator registry.

The reference registers each op as C++ metadata + per-device kernels + a
hand-written GradOpMaker (reference: paddle/fluid/framework/op_registry.h:199,
grad_op_desc_maker.h). On TPU every kernel is a JAX lowering, which buys two
big simplifications:

* **Generic gradients** — the grad op for `foo` is `foo_grad`, whose kernel is
  `jax.vjp` of foo's forward kernel. No per-op grad code; XLA CSE dedups the
  replayed forward. Ops can still override with a custom grad kernel.
* **Generic shape/dtype inference** — `jax.eval_shape` over the kernel replaces
  per-op InferShape (reference: framework/shape_inference.h). Dynamic (-1)
  dims are inferred via a sentinel substitution.

Kernel signature: ``kernel(ins, attrs, ctx) -> outs`` where ins/outs map slot
name -> list of jnp arrays (a single array or None is normalized), and ctx is
a KernelCtx giving RNG, sub-block lowering and requested-output info.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from .ir import OpDesc, VarDesc, normalize_dtype

# Sentinel used to stand in for -1 dims during eval_shape-based inference.
# A distinctive prime so it never collides with a real computed dim.
_DYN_SENTINEL = 97

GRAD_PREFIX_IN = "fwd_in::"
GRAD_PREFIX_OUT = "fwd_out::"
GRAD_PREFIX_OG = "out_grad::"
GRAD_PREFIX_IG = "in_grad::"


class KernelCtx:
    """Execution context handed to kernels (reference: ExecutionContext,
    framework/operator.h:231)."""

    def __init__(
        self,
        op: OpDesc,
        lower_block_fn: Optional[Callable] = None,
        rng_key=None,
        is_test: bool = False,
        program=None,
        block_idx: int = 0,
        env: Optional[dict] = None,
        in_shape_inference: bool = False,
    ):
        self.op = op
        self._lower_block_fn = lower_block_fn
        self._rng_key = rng_key
        self.is_test = is_test
        self.program = program
        self.block_idx = block_idx
        self.env = env  # live name->value environment (control-flow ops)
        # True only under infer_op_outputs' eval_shape, where -1 dims are
        # stood in by _DYN_SENTINEL; kernels use this to relax static
        # batch-size checks that would trip on the sentinel.
        self.in_shape_inference = in_shape_inference

    def rng(self) -> jax.Array:
        """Deterministic per-op PRNG key: fold the per-step key with the op's
        build-time-assigned uid (replayed identically by the vjp grad)."""
        if self._rng_key is None:
            # eval_shape / no-rng-state path: fixed key keeps tracing total.
            base = jax.random.key(0)
        else:
            base = self._rng_key
        uid = int(self.op.attrs.get("__rng_uid__", 0))
        return jax.random.fold_in(base, uid)

    def lower_block(self, block_idx: int, env: Dict[str, Any]) -> Dict[str, Any]:
        """Lower a sub-block (control flow) into the current trace."""
        assert self._lower_block_fn is not None, "no sub-block lowering available"
        return self._lower_block_fn(block_idx, env, self)

    def requested_outputs(self) -> Set[str]:
        return {k for k, v in self.op.outputs.items() if any(v)}

    def child(self, op: OpDesc) -> "KernelCtx":
        return KernelCtx(
            op,
            lower_block_fn=self._lower_block_fn,
            rng_key=self._rng_key,
            is_test=self.is_test,
            program=self.program,
            block_idx=self.block_idx,
            env=self.env,
            in_shape_inference=self.in_shape_inference,
        )


class OpDef:
    def __init__(
        self,
        type: str,
        kernel: Callable,
        grad: Optional[str | Callable] = "generic",
        nondiff_inputs: Sequence[str] = (),
        infer_shape: Optional[Callable] = None,
        is_random: bool = False,
        default_attrs: Optional[Dict[str, Any]] = None,
        intermediate_outputs: Sequence[str] = (),
    ):
        self.type = type
        self.kernel = kernel
        self.grad = grad  # 'generic' | None | callable custom grad kernel
        self.nondiff_inputs = set(nondiff_inputs)
        self.custom_infer_shape = infer_shape
        self.is_random = is_random
        self.default_attrs = dict(default_attrs or {})
        self.intermediate_outputs = set(intermediate_outputs)

    # -- invocation helpers --------------------------------------------------

    def call(self, ins: Dict[str, List], attrs: Dict[str, Any], ctx: KernelCtx):
        merged = {**self.default_attrs, **attrs}
        outs = self.kernel(ins, merged, ctx)
        return normalize_outs(outs)

    def has_grad(self) -> bool:
        return self.grad is not None


def normalize_outs(outs) -> Dict[str, List]:
    if outs is None:
        return {}
    norm = {}
    for k, v in outs.items():
        if v is None:
            norm[k] = []
        elif isinstance(v, (list, tuple)):
            norm[k] = list(v)
        else:
            norm[k] = [v]
    return norm


_REGISTRY: Dict[str, OpDef] = {}


def register_op(
    type: str,
    grad: Optional[str | Callable] = "generic",
    nondiff_inputs: Sequence[str] = (),
    infer_shape: Optional[Callable] = None,
    is_random: bool = False,
    default_attrs: Optional[Dict[str, Any]] = None,
    intermediate_outputs: Sequence[str] = (),
):
    """Decorator registering a kernel (reference: REGISTER_OPERATOR,
    op_registry.h:199)."""

    def deco(fn):
        _REGISTRY[type] = OpDef(
            type,
            fn,
            grad=grad,
            nondiff_inputs=nondiff_inputs,
            infer_shape=infer_shape,
            is_random=is_random,
            default_attrs=default_attrs,
            intermediate_outputs=intermediate_outputs,
        )
        return fn

    return deco


def get_op_def(type: str) -> OpDef:
    if type in _REGISTRY:
        return _REGISTRY[type]
    if type.endswith("_grad"):
        base = type[: -len("_grad")]
        fwd = _REGISTRY.get(base)
        if fwd is None and base.endswith("_grad"):
            # second (or higher) order: synthesize the lower-order grad op
            # first — `conv2d_grad_grad` is the vjp of `conv2d_grad`, which
            # is itself the vjp of `conv2d` (the reference registers
            # *_grad_grad ops by hand, e.g. conv_op.cc:671; here every
            # order comes from jax.vjp for free)
            try:
                fwd = get_op_def(base)
            except KeyError:
                fwd = None
        if fwd is not None and fwd.grad == "generic":
            # grad="generic" (not None) keeps the synthesized op itself
            # differentiable, enabling gradients(gradients(...)).
            gd = OpDef(type, make_generic_grad_kernel(fwd), grad="generic")
            _REGISTRY[type] = gd
            return gd
        if fwd is not None and callable(fwd.grad):
            gd = OpDef(type, fwd.grad, grad="generic")
            _REGISTRY[type] = gd
            return gd
    raise KeyError(f"operator '{type}' is not registered")


def has_op(type: str) -> bool:
    try:
        get_op_def(type)
        return True
    except KeyError:
        return False


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Generic vjp-based gradient
# ---------------------------------------------------------------------------


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype, jnp.floating)


def make_generic_grad_kernel(fwd: OpDef) -> Callable:
    """Build the kernel for `<type>_grad` from the forward kernel via jax.vjp.

    Grad-op slot convention (replaces the reference's GradOpDescMaker naming
    X / Out / Out@GRAD / X@GRAD, grad_op_desc_maker.h):
      inputs : fwd_in::<slot>, fwd_out::<slot>, out_grad::<slot>
      outputs: in_grad::<slot>
    """

    def grad_kernel(ins, attrs, ctx: KernelCtx):
        fwd_ins: Dict[str, List] = {}
        out_grads: Dict[str, List] = {}
        inner_outs: Dict[str, List[str]] = {}
        for k, v in ins.items():
            if k.startswith(GRAD_PREFIX_IN):
                fwd_ins[k[len(GRAD_PREFIX_IN):]] = v
            elif k.startswith(GRAD_PREFIX_OG):
                out_grads[k[len(GRAD_PREFIX_OG):]] = v
            elif k.startswith(GRAD_PREFIX_OUT):
                # fwd_out:: VALUES are not needed (forward is replayed; XLA
                # CSE dedups) but their slot structure reconstructs the
                # forward op's outputs for the replay ctx below
                inner_outs[k[len(GRAD_PREFIX_OUT):]] = [
                    "_" if x is not None else "" for x in v]

        # Replay the forward under a ctx whose op LOOKS like the forward
        # op (type/attrs/outputs): kernels consult ctx.requested_outputs()
        # and ctx.rng() — with the outer grad op's ctx they would see
        # in_grad:: slot names and skip everything. This matters doubly for
        # grad-of-grad, where fwd is itself a generic grad kernel whose
        # `requested` derivation depends on the op's output slot names.
        from .ir import OpDesc as _OpDesc

        inner_op = _OpDesc(
            type=fwd.type,
            inputs={k: ["_" if x is not None else "" for x in v]
                    for k, v in fwd_ins.items()},
            outputs=inner_outs,
            attrs=dict(attrs),
        )
        replay_ctx = ctx.child(inner_op)

        requested = {
            k[len(GRAD_PREFIX_IG):]
            for k in ctx.requested_outputs()
            if k.startswith(GRAD_PREFIX_IG)
        }

        # Split differentiable vs. static inputs.
        diff_ins: Dict[str, List] = {}
        rest_ins: Dict[str, List] = {}
        for slot, vals in fwd_ins.items():
            if slot in fwd.nondiff_inputs or slot not in requested:
                rest_ins[slot] = vals
            else:
                d, r = [], []
                for x in vals:
                    (d if x is not None and _is_float(x) else r).append(x)
                if d and not r:
                    diff_ins[slot] = vals
                else:
                    rest_ins[slot] = vals

        def f(dins):
            all_ins = {**rest_ins, **dins}
            outs = fwd.call(all_ins, attrs, replay_ctx)
            # Only float outputs participate in the cotangent structure.
            return {
                k: [o for o in v if o is not None and _is_float(o)]
                for k, v in outs.items()
                if k not in fwd.intermediate_outputs or k in out_grads
            }

        primal_out, vjp_fn = jax.vjp(f, diff_ins)

        def _cot(g, v):
            # vjp demands the cotangent's dtype match the primal output
            # exactly. Under a mixed-precision policy the upstream grad
            # may arrive at a different float width than this op's
            # forward computed in (a bf16 matmul grad flowing into an
            # f32 gray op) — the cast is the transpose of the identity
            # cast autocast conceptually inserted between them.
            if hasattr(g, "dtype") and g.dtype != v.dtype:
                return g.astype(v.dtype)
            return g

        cots = {}
        for slot, vals in primal_out.items():
            given = out_grads.get(slot)
            cots[slot] = [
                (_cot(given[i], v) if given is not None and i < len(given)
                 and given[i] is not None
                 else jnp.zeros(v.shape, v.dtype))
                for i, v in enumerate(vals)
            ]
        (gins,) = vjp_fn(cots)

        outs = {}
        for slot, gvals in gins.items():
            outs[GRAD_PREFIX_IG + slot] = gvals
        # Requested grads for non-differentiable inputs come back as zeros.
        for slot in requested:
            if slot not in gins and slot in fwd_ins:
                outs[GRAD_PREFIX_IG + slot] = [
                    jnp.zeros(jnp.shape(x), jnp.result_type(x)) if x is not None else None
                    for x in fwd_ins[slot]
                ]
        return outs

    return grad_kernel


# ---------------------------------------------------------------------------
# Generic shape/dtype inference via eval_shape
# ---------------------------------------------------------------------------


def infer_op_outputs(
    op: OpDesc,
    input_descs: Dict[str, VarDesc],
    lower_block_fn: Optional[Callable] = None,
    program=None,
) -> Dict[str, "jax.ShapeDtypeStruct"]:
    """Infer output shapes/dtypes for `op` given input VarDescs.

    Returns {var_name: ShapeDtypeStruct}; -1 dims round-trip via a sentinel.
    """
    opdef = get_op_def(op.type)
    if opdef.custom_infer_shape is not None:
        return opdef.custom_infer_shape(op, input_descs)

    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if not n:
                vals.append(None)
                continue
            d = input_descs[n]
            shape = tuple(_DYN_SENTINEL if s == -1 else s for s in (d.shape or ()))
            vals.append(jax.ShapeDtypeStruct(shape, np.dtype(normalize_dtype(d.dtype))))
        ins[slot] = vals

    ctx = KernelCtx(op, lower_block_fn=lower_block_fn, program=program,
                    in_shape_inference=True)

    def f(ins):
        return opdef.call(ins, op.attrs, ctx)

    outs = jax.eval_shape(f, ins)

    result: Dict[str, jax.ShapeDtypeStruct] = {}
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, n in enumerate(names):
            if not n:
                continue
            if i < len(vals) and vals[i] is not None:
                v = vals[i]
                shape = tuple(-1 if s == _DYN_SENTINEL else s for s in v.shape)
                result[n] = jax.ShapeDtypeStruct(shape, v.dtype)
    return result
