"""Device places (reference: paddle/fluid/platform/place.h:26-52).

On TPU the device taxonomy is owned by JAX/PJRT; Place objects survive as
thin user-facing handles so `Executor(fluid.TPUPlace(0))` reads like the
reference's `Executor(fluid.CUDAPlace(0))`.
"""

from __future__ import annotations

import jax


class Place:
    device_id: int = 0

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == getattr(other, "device_id", 0)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def jax_device(self):
        # local_devices: under multi-host (jax.distributed) a process may
        # only place computations on its own devices; jax.devices()[0] would
        # be process 0's device everywhere
        devs = (jax.local_devices(backend=self.backend) if self.backend
                else jax.local_devices())
        return devs[self.device_id]

    backend = None


class CPUPlace(Place):
    backend = "cpu"

    def __repr__(self):
        return "CPUPlace"


class TPUPlace(Place):
    backend = None  # default backend (tpu when present)

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# Reference-compat alias: scripts written against fluid.CUDAPlace(0) run on
# the accelerator (TPU) unchanged.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace


class TPUPinnedPlace(Place):
    backend = "cpu"

    def __repr__(self):
        return "TPUPinnedPlace"


CUDAPinnedPlace = TPUPinnedPlace


def is_compiled_with_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def is_compiled_with_cuda() -> bool:
    # Reference-compat shim: "is there an accelerator".
    return is_compiled_with_tpu()


def default_place() -> Place:
    return TPUPlace(0) if is_compiled_with_tpu() else CPUPlace()
